"""The negative-key FIFO dictionary.

Reference: `moco/builder.py:~L38-42` registers `queue = randn(dim, K)`
(L2-normalized columns) and `queue_ptr`; `_dequeue_and_enqueue`
(`~L62-77`) all-gathers the step's keys across ranks, asserts
`K % batch == 0`, writes them at `ptr`, and advances `ptr` modulo K.

TPU-native redesign: the queue is a `(K, dim)` row-major array carried in
the train state (replicated sharding), updated with
`lax.dynamic_update_slice` *inside* the jitted step — no host round-trip,
no mutable buffer. Because `K % global_batch == 0` the write never wraps,
so a single dynamic slice suffices (same invariant as the reference).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from moco_tpu.ops.losses import l2_normalize


def init_queue(rng: jax.Array, num_negatives: int, dim: int) -> jax.Array:
    """Random L2-normalized rows, like the reference's normalized randn."""
    q = jax.random.normal(rng, (num_negatives, dim), dtype=jnp.float32)
    return l2_normalize(q, axis=-1)


def enqueue(queue: jax.Array, ptr: jax.Array, keys: jax.Array) -> tuple[jax.Array, jax.Array]:
    """FIFO write of a (N, dim) key block at ptr; returns (queue, new_ptr).

    Requires K % N == 0 (checked statically by the caller /
    `check_queue_divisibility`), mirroring the reference's
    `assert self.K % batch_size == 0`.
    """
    num_neg = queue.shape[0]
    keys = jax.lax.stop_gradient(keys).astype(queue.dtype)
    queue = jax.lax.dynamic_update_slice(queue, keys, (ptr, jnp.zeros_like(ptr)))
    new_ptr = (ptr + keys.shape[0]) % num_neg
    return queue, new_ptr


def check_queue_divisibility(num_negatives: int, global_batch: int) -> None:
    if num_negatives % global_batch != 0:
        raise ValueError(
            f"queue size K={num_negatives} must be divisible by the global batch "
            f"{global_batch} (reference invariant, moco/builder.py:~L70)"
        )
