"""The negative-key FIFO dictionary.

Reference: `moco/builder.py:~L38-42` registers `queue = randn(dim, K)`
(L2-normalized columns) and `queue_ptr`; `_dequeue_and_enqueue`
(`~L62-77`) all-gathers the step's keys across ranks, asserts
`K % batch == 0`, writes them at `ptr`, and advances `ptr` modulo K.

TPU-native redesign: the queue is a `(K, dim)` row-major array carried in
the train state (replicated sharding), updated with
`lax.dynamic_update_slice` *inside* the jitted step — no host round-trip,
no mutable buffer. Because `K % global_batch == 0` the write never wraps,
so a single dynamic slice suffices (same invariant as the reference).

Since the serving subsystem landed, the queue is the train-time instance
of the embedding index: the FIFO write itself lives in
`moco_tpu/serve/index.py` (`fifo_write`, bit-identical to the
pre-refactor body here — pinned by tests/test_serve.py), so training and
the `/neighbors` serving path maintain their dictionaries with one
kernel. This module keeps the training-facing API and invariants.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from moco_tpu.ops.losses import l2_normalize
from moco_tpu.serve.index import fifo_write


def init_queue(rng: jax.Array, num_negatives: int, dim: int) -> jax.Array:
    """Random L2-normalized rows, like the reference's normalized randn."""
    q = jax.random.normal(rng, (num_negatives, dim), dtype=jnp.float32)
    return l2_normalize(q, axis=-1)


def enqueue(queue: jax.Array, ptr: jax.Array, keys: jax.Array) -> tuple[jax.Array, jax.Array]:
    """FIFO write of a (N, dim) key block at ptr; returns (queue, new_ptr).

    Requires K % N == 0 (checked statically by the caller /
    `check_queue_divisibility`), mirroring the reference's
    `assert self.K % batch_size == 0`. Delegates to the shared index
    kernel (`serve/index.py:fifo_write`) — the refactor is bitwise
    invisible to the loss trajectory.
    """
    return fifo_write(queue, ptr, keys)


def check_queue_divisibility(num_negatives: int, global_batch: int) -> None:
    if num_negatives % global_batch != 0:
        raise ValueError(
            f"queue size K={num_negatives} must be divisible by the global batch "
            f"{global_batch} (reference invariant, moco/builder.py:~L70)"
        )
