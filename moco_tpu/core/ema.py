"""Momentum (EMA) key-encoder update.

Reference: `moco/builder.py:~L52-60` — under `@torch.no_grad()`,
`param_k = param_k * m + param_q * (1 - m)`, run once per step before the
key forward. There it relies on DDP keeping every rank's `encoder_q`
bit-identical so the per-rank local EMA stays in lockstep; here the state
is functional and threaded through the jitted step, so lockstep is
structural, not a protocol invariant.

Because the update is elementwise, it is layout-agnostic: ZeRO-2/3
(parallel/zero.py stage 2/3) calls the same function on the persistent
(m,)-row param SHARDS inside the gather stage — each replica advances
its own rows and the EMA costs zero collectives, one of the points of
persistently sharding both encoders in the same layout.
"""

from __future__ import annotations

import jax


def ema_update(params_k, params_q, momentum: float):
    """params_k <- params_k * m + params_q * (1 - m), elementwise over the tree."""
    return jax.tree.map(lambda k, q: k * momentum + q * (1.0 - momentum), params_k, params_q)
