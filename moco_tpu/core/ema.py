"""Momentum (EMA) key-encoder update.

Reference: `moco/builder.py:~L52-60` — under `@torch.no_grad()`,
`param_k = param_k * m + param_q * (1 - m)`, run once per step before the
key forward. There it relies on DDP keeping every rank's `encoder_q`
bit-identical so the per-rank local EMA stays in lockstep; here the state
is functional and threaded through the jitted step, so lockstep is
structural, not a protocol invariant.

Because the update is elementwise, it is layout-agnostic: ZeRO-2/3
(parallel/zero.py stage 2/3) calls the same function on the persistent
(m,)-row param SHARDS inside the gather stage — each replica advances
its own rows and the EMA costs zero collectives, one of the points of
persistently sharding both encoders in the same layout.
"""

from __future__ import annotations

import jax


def ema_update(params_k, params_q, momentum: float):
    """params_k <- params_k * m + params_q * (1 - m), elementwise over the tree."""
    return jax.tree.map(lambda k, q: k * momentum + q * (1.0 - momentum), params_k, params_q)


def momentum_bn_stats(running, batch, momentum: float):
    """Momentum-statistics BN update ("Momentum² Teacher",
    arXiv:2101.07525 §3.2): the NEW running statistic
    `m * running + (1 - m) * batch`, which the layer both normalizes
    with and stores — the large-batch alternative to cross-replica BN
    statistics. Same elementwise EMA as `ema_update`, exposed per tree
    OR per leaf for harness/report use; the in-model implementation
    lives inline in `models/resnet.py` (models/ must not import core/,
    see `moco_tpu/core/__init__.py`'s import order)."""
    if isinstance(running, (list, dict, tuple)):
        return ema_update(running, batch, momentum)
    return running * momentum + batch * (1.0 - momentum)
