"""Momentum (EMA) key-encoder update.

Reference: `moco/builder.py:~L52-60` — under `@torch.no_grad()`,
`param_k = param_k * m + param_q * (1 - m)`, run once per step before the
key forward. There it relies on DDP keeping every rank's `encoder_q`
bit-identical so the per-rank local EMA stays in lockstep; here the state
is functional and threaded through the jitted step, so lockstep is
structural, not a protocol invariant.
"""

from __future__ import annotations

import jax


def ema_update(params_k, params_q, momentum: float):
    """params_k <- params_k * m + params_q * (1 - m), elementwise over the tree."""
    return jax.tree.map(lambda k, q: k * momentum + q * (1.0 - momentum), params_k, params_q)
