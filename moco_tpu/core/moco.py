"""The MoCo algorithm as a pure SPMD train step.

This is the TPU-first re-design of `moco/builder.py` + the hot loop of
`main_moco.py:~L262-310`. Instead of a stateful `nn.Module` with
registered buffers mutated per rank under DDP, the whole algorithm is one
pure function

    train_step(state, batch, root_rng) -> (state, metrics)

jitted once over a `jax.sharding.Mesh` via `shard_map`. The reference's
trickiest invariant — queue + EMA replicas staying bit-identical across
ranks with no dedicated sync traffic (SURVEY.md §2.3) — is structural
here: replicated state in, deterministic math, replicated state out.

Per-step collectives (vs the reference's 3× all_gather + 1× broadcast +
DDP all-reduce, `SURVEY.md §3.1`):
- shuffle='gather_perm': 2× all_gather (images, embeddings; the
  broadcast is replaced by same-seed randomness, and the queue reuses
  the unshuffle gather — one collective fewer than upstream)
- shuffle='a2a': 2× all_to_all + 1× small all_gather (balanced random
  permutation — moves (n-1)/n of the batch over ICI vs the full
  n× batch an all_gather moves)
- 1× psum for gradients (the DDP bucketed all-reduce equivalent)
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from moco_tpu.core.ema import ema_update
from moco_tpu.core.queue import check_queue_divisibility, enqueue, init_queue
from moco_tpu.obs import comms
from moco_tpu.obs import health as obs_health
from moco_tpu.models import ProjectionHead, V3MLPHead, create_resnet
from moco_tpu.ops.losses import cross_entropy, infonce_logits, l2_normalize, topk_accuracy
from moco_tpu.parallel.compat import shard_map
from moco_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from moco_tpu.parallel.shuffle import (
    balanced_shuffle,
    balanced_unshuffle,
    make_permutation,
    shuffle_gather,
    unshuffle_gather,
)
from moco_tpu.parallel.zero import (
    BucketPlan,
    GroupPlan,
    expand_opt_state,
    padded_cols,
    shard_template,
    shard_tree,
    sharded_update,
    squeeze_opt_state,
)
from moco_tpu.utils.config import MocoConfig, TrainConfig


class MoCoEncoder(nn.Module):
    """backbone + projection head = the reference's `base_encoder(num_classes=dim)`
    with optional MLP surgery (`moco/builder.py:~L20-30`), composed explicitly.

    `group`: layer-granular apply (the ZeRO-3 per-group schedule) — run
    only the named backbone group ("stem"/"blockN"/"embed"/...) or the
    "head" group on `x`, which is then the PREVIOUS group's activation,
    not an image. `group=None` is the classic whole-encoder forward;
    both paths register identical parameter trees."""

    backbone: nn.Module
    head: nn.Module

    def __call__(self, x, train: bool = True, group: Optional[str] = None):
        if group is None:
            return self.head(self.backbone(x, train=train), train=train)
        if group == "head":
            return self.head(x, train=train)
        return self.backbone(x, train=train, group=group)


def create_backbone(cfg: MocoConfig, num_data: Optional[int] = None) -> nn.Module:
    """Backbone factory shared by pretraining and the linear probe:
    ResNet family or ViT family from `cfg.arch`."""
    dtype = jnp.dtype(cfg.compute_dtype)
    if cfg.vit_sequence_parallel and not cfg.arch.startswith("vit"):
        # must fail HERE, not just in the vit branch: v3_step keys its
        # backbone-grad psum on this flag, and a silently-ignored flag on
        # a ResNet would double backbone grads over the model axis
        raise ValueError(f"vit_sequence_parallel requires a ViT arch, got {cfg.arch!r}")
    if cfg.arch.startswith("vit"):
        if cfg.bn_stats_rows or cfg.bn_virtual_groups > 1 or cfg.bn_momentum_stats:
            # must fail loudly: a ViT has no BatchNorm, the lever would be
            # inert while the checkpoint config records it as active
            raise ValueError(
                "bn_stats_rows / bn_virtual_groups / bn_momentum_stats apply "
                "to ResNet BatchNorm, not ViT archs"
            )
        from moco_tpu.models.vit import create_vit

        vit_kw = {"patch_size": cfg.vit_patch_size} if cfg.vit_patch_size else {}
        if cfg.vit_sequence_parallel:
            if not cfg.v3:
                raise ValueError("vit_sequence_parallel requires the v3 (queue-free) step")
            if cfg.vit_pool != "gap":
                raise ValueError("vit_sequence_parallel requires vit_pool='gap'")
            vit_kw["sequence_axis"] = MODEL_AXIS
        return create_vit(
            cfg.arch,
            dtype=dtype,
            use_flash_attention=cfg.vit_flash_attention,
            pool=cfg.vit_pool,
            **vit_kw,
        )
    syncbn_axis = DATA_AXIS if cfg.shuffle == "syncbn" else None
    groups = None
    if syncbn_axis and cfg.syncbn_group_size and num_data is None:
        raise ValueError(
            "syncbn_group_size is set but build_encoder was called without "
            "num_data — subgrouped SyncBN needs the data-axis size to form groups"
        )
    if syncbn_axis and cfg.syncbn_group_size and num_data:
        # Subgrouped SyncBN — the detection configs' "per-8-GPU" statistics
        # pattern (Base-RCNN-C4-BN.yaml) via axis_index_groups.
        g = cfg.syncbn_group_size
        if num_data % g:
            raise ValueError(f"data axis {num_data} not divisible by syncbn group {g}")
        groups = [list(range(i, i + g)) for i in range(0, num_data, g)]
    if cfg.bn_virtual_groups > 1 and cfg.shuffle == "syncbn":
        raise ValueError("bn_virtual_groups does not compose with syncbn")
    if cfg.bn_stats_barrier and not cfg.bn_stats_rows:
        # must fail loudly: without subset rows the custom BatchNorm is
        # never even selected, and a compile-pathology A/B would silently
        # measure baseline-vs-baseline while reporting the barrier leg
        raise ValueError("bn_stats_barrier requires bn_stats_rows > 0")
    if (
        cfg.bn_stats_rows
        and (cfg.shuffle == "none" or cfg.v3)
        and (num_data or 1) > 1
        and not cfg.allow_leaky_bn
        # with an EMAN key forward the key path reads NO batch
        # statistics, so query-side subset stats cannot leak key
        # composition — stacking the two BN levers is safe. The
        # exemption must not extend to v3: key_bn_running_stats is
        # invalid there (make_train_step rejects the combo), so a
        # v3 config carrying it must still hit this gate rather
        # than silently building a leaky encoder.
        and not (cfg.key_bn_running_stats and not cfg.v3)
    ):
        # same leak logic as the virtual-groups gate below, sharpened:
        # statistics over a FIXED first-r-rows subset leak more than
        # whole-batch per-device BN (fewer rows correlate query/key
        # composition more tightly), so the perf lever must not be
        # combinable with unpermuted multi-device keys — and the v3
        # step never shuffles at all, so it is equally exposed.
        # Single-device training keeps it available (no cross-device
        # composition to leak beyond the known single-GPU MoCo caveat).
        raise ValueError(
            "bn_stats_rows needs a key permutation on a multi-device data "
            "axis (fixed first-N-rows statistics concentrate the BN leak "
            "Shuffle-BN prevents): use shuffle='gather_perm' or 'a2a', and "
            "leave it unset for the v3 step, which never shuffles"
        )
    if (
        cfg.bn_virtual_groups > 1
        and (cfg.shuffle == "none" or cfg.v3)
        and not cfg.allow_leaky_bn
        # EMAN key forward: the key path reads NO batch statistics, so
        # query-side per-group stats cannot leak key composition (same
        # exemption — and same v3 scoping — as the bn_stats_rows gate)
        and not (cfg.key_bn_running_stats and not cfg.v3)
    ):
        # must fail loudly: per-group BN with UNPERMUTED keys is the exact
        # intra-batch statistics leak Shuffle-BN exists to prevent — worse
        # than whole-batch BN, while the config would record virtual
        # Shuffle-BN as active (the v3 step never shuffles at all)
        raise ValueError(
            "bn_virtual_groups needs a key permutation: use shuffle='gather_perm' "
            "or 'a2a' (shuffle='none' and the v3 step would leak per-group stats)"
        )
    return create_resnet(
        cfg.arch,
        cifar_stem=cfg.cifar_stem,
        dtype=dtype,
        bn_cross_replica_axis=syncbn_axis,
        bn_axis_index_groups=groups,
        bn_stats_rows=cfg.bn_stats_rows,
        bn_stats_barrier=cfg.bn_stats_barrier,
        bn_virtual_groups=cfg.bn_virtual_groups,
        bn_momentum_stats=cfg.bn_momentum_stats,
    )


def build_encoder(cfg: MocoConfig, num_data: Optional[int] = None) -> MoCoEncoder:
    """Backbone + projection head. v3 head shape branches on backbone
    family, matching upstream `moco-v3`'s per-family builders
    (`_build_projector_and_predictor_mlps`): ViT gets the 3-layer
    projector, ResNet the 2-layer one (both end in affine-free BN);
    v1/v2 get the reference's Linear / 2-layer MLP
    (`moco/builder.py:~L20-30`)."""
    dtype = jnp.dtype(cfg.compute_dtype)
    backbone = create_backbone(cfg, num_data=num_data)
    if cfg.v3:
        axis = DATA_AXIS if (num_data or 1) > 1 else None
        num_layers = 3 if cfg.arch.startswith("vit") else 2
        head = V3MLPHead(
            num_layers=num_layers, dim=cfg.dim, cross_replica_axis=axis, dtype=dtype
        )
    else:
        head = ProjectionHead(dim=cfg.dim, mlp=cfg.mlp, dtype=dtype)
    return MoCoEncoder(backbone=backbone, head=head)


def build_predictor(cfg: MocoConfig, num_data: Optional[int] = None) -> Optional[nn.Module]:
    """v3's prediction MLP on the query side only (2-layer BN-MLP); None
    for v1/v2, whose query and key encoders are architecturally identical.
    The ViT predictor keeps the final affine-free BN; the ResNet one drops
    it (upstream `MoCo_ResNet` passes last_bn=False)."""
    if not cfg.v3:
        return None
    axis = DATA_AXIS if (num_data or 1) > 1 else None
    return V3MLPHead(
        num_layers=2,
        dim=cfg.dim,
        cross_replica_axis=axis,
        last_bn=cfg.arch.startswith("vit"),
        dtype=jnp.dtype(cfg.compute_dtype),
    )


class MocoState(struct.PyTreeNode):
    """Everything `main_moco.py`'s checkpoint carries (SURVEY.md §3.5):
    both encoders, queue + pointer, optimizer state, step — plus, for the
    v3 variant, the query-side prediction head (empty dicts otherwise)."""

    step: jax.Array
    params_q: Any
    params_k: Any
    batch_stats_q: Any
    batch_stats_k: Any
    queue: jax.Array  # (K, dim) rows; L2-normalized
    queue_ptr: jax.Array  # int32 scalar
    opt_state: Any
    params_pred: Any = struct.field(default_factory=dict)
    batch_stats_pred: Any = struct.field(default_factory=dict)


class ZeroGathered(struct.PyTreeNode):
    """Output of the ZeRO-2/3 per-step params gather (parallel/zero.py
    stage 2/3): the FULL trainable params + key-encoder params step k
    consumes (replicated, donated to the step so XLA frees them after
    the backward), plus the already-EMA'd key-encoder SHARDS that
    become step k's `params_k` — the EMA itself ran shard-local inside
    the gather, with no collective."""

    trainable: Any  # {"enc": ..., "pred": ...}, full shapes, replicated
    params_k: Any  # full enc-shaped tree, replicated
    shards_k: Any  # (n, m) persistent layout, P(data)-sharded


def zero_stage23(config: TrainConfig) -> bool:
    """Whether the config selects the persistently-sharded-params ZeRO
    stage (2 and 3 both map to the one implementation)."""
    return config.parallel.shard_weight_update and config.parallel.zero_stage >= 2


def zero_layer_granular(config: TrainConfig) -> bool:
    """Whether the config selects the LAYER-GRANULAR stage-2/3 schedule:
    per-group just-in-time gather/free instead of the whole-tree gather."""
    return zero_stage23(config) and config.parallel.zero_layer_granular


def _overlay(orig, upd):
    """Merge a PARTIAL mutated batch_stats tree (from a layer-group
    apply, which only touches the called group's entries) back over the
    full tree, preserving `orig`'s nesting — entries the group never
    visited pass through unchanged."""
    if not hasattr(orig, "items"):
        return upd
    return {k: (_overlay(v, upd[k]) if k in upd else v) for k, v in orig.items()}


def _tree_full_bytes(tree) -> int:
    """Bytes of a shape/dtype-carrying abstract tree's FULL leaves."""
    return sum(
        (int(np.prod(tuple(l.shape))) if l.shape else 1) * jnp.dtype(l.dtype).itemsize
        for l in jax.tree.leaves(tree)
    )


def _tree_shard_bytes_analytic(tree, n: int) -> int:
    """Per-chip bytes of the same tree in the persistent (n, m) layout
    (each replica's row, padding included)."""
    return sum(
        padded_cols(int(np.prod(tuple(l.shape))) if l.shape else 1, n)
        * jnp.dtype(l.dtype).itemsize
        for l in jax.tree.leaves(tree)
    )


def full_param_shapes(config: TrainConfig, encoder: MoCoEncoder, predictor=None) -> dict:
    """Abstract (ShapeDtypeStruct) trees of the FULL trainable params —
    the shape source the ZeRO-2/3 bucket plans, eval-side gathers, and
    reshard templates all derive from (the persistent (n, m) layout
    does not carry the original leaf shapes)."""
    sample = jnp.zeros(
        (1, config.data.image_size, config.data.image_size, 3), jnp.float32
    )
    enc = jax.eval_shape(
        lambda r: encoder.init(r, sample, train=False), jax.random.PRNGKey(0)
    )["params"]
    pred = {}
    if predictor is not None:
        pred = jax.eval_shape(
            lambda r: predictor.init(
                r, jnp.zeros((1, config.moco.dim), jnp.float32), train=False
            ),
            jax.random.PRNGKey(0),
        )["params"]
    return {"enc": enc, "pred": pred}


def create_state(
    rng: jax.Array,
    config: TrainConfig,
    encoder: MoCoEncoder,
    tx,
    sample_input: jax.Array,
    predictor: Optional[nn.Module] = None,
    zero_num_data: Optional[int] = None,
) -> MocoState:
    """`zero_num_data`: when config.parallel.shard_weight_update is on,
    the data-axis size — the optimizer state is then initialized in the
    (n, m) sharded-flat layout (moco_tpu/parallel/zero.py) instead of the
    param tree's shapes."""
    if config.parallel.shard_weight_update and not zero_num_data:
        # fail here, not downstream: a replicated opt state silently built
        # for a ZeRO config would later be mis-sharded by the ndim==2
        # spec heuristic or squeezed into garbage shapes
        raise ValueError(
            "config.parallel.shard_weight_update=True requires zero_num_data "
            "(the data-axis size) so the opt state gets the (n, m) layout"
        )
    p_rng, q_rng, pred_rng = jax.random.split(rng, 3)
    variables = encoder.init(p_rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    cfg = config.moco
    queue = (
        init_queue(q_rng, cfg.num_negatives, cfg.dim)
        if cfg.num_negatives > 0
        # queue-free (v3): a 1-row placeholder, never read by the step —
        # a (0, dim) array would be rejected by Orbax at checkpoint save
        else jnp.zeros((1, cfg.dim), jnp.float32)
    )
    params_pred, stats_pred = {}, {}
    if predictor is not None:
        pv = predictor.init(pred_rng, jnp.zeros((1, cfg.dim), jnp.float32), train=False)
        params_pred = pv["params"]
        stats_pred = pv.get("batch_stats", {})
    # opt state always initializes from the FULL trainable shapes (the
    # (n, m) template is derived from them); the param trees themselves
    # additionally move to the persistent sharded layout at stage 2/3
    zero = config.parallel.shard_weight_update and zero_num_data
    stage23 = bool(zero) and config.parallel.zero_stage >= 2
    params_k = jax.tree.map(jnp.copy, params)  # moco/builder.py:~L32-36
    opt_state = tx.init(
        {"enc": params, "pred": params_pred}
        if not zero
        else shard_template({"enc": params, "pred": params_pred}, zero_num_data)
    )
    if stage23:
        params = shard_tree(params, zero_num_data)
        params_k = shard_tree(params_k, zero_num_data)
        params_pred = shard_tree(params_pred, zero_num_data)
    return MocoState(
        step=jnp.zeros((), jnp.int32),
        params_q=params,
        params_k=params_k,
        batch_stats_q=batch_stats,
        batch_stats_k=jax.tree.map(jnp.copy, batch_stats),
        queue=queue,
        queue_ptr=jnp.zeros((), jnp.int32),
        opt_state=opt_state,
        params_pred=params_pred,
        batch_stats_pred=stats_pred,
    )


def state_specs(
    shard_queue_over_model: bool,
    zero_opt_state: Optional[Any] = None,
    zero_params: bool = False,
) -> MocoState:
    """PartitionSpec pytree for MocoState: everything replicated except,
    optionally, the queue rows sharded over the model axis (tensor
    parallelism for very large dictionaries), — with sharded weight
    update — the optimizer state's (n, m) leaves sharded over `data`
    (`zero_opt_state` is a concrete opt-state tree to derive per-leaf
    specs from; its 2-D leaves are the sharded ones, scalars replicate),
    and — at ZeRO stage 2/3 (`zero_params`) — the param trees
    themselves, whose leaves all live in the (n, m) persistent layout.
    """
    qspec = P(MODEL_AXIS, None) if shard_queue_over_model else P()
    opt_spec: Any = P()
    if zero_opt_state is not None:
        opt_spec = jax.tree.map(
            lambda x: P(DATA_AXIS, None) if getattr(x, "ndim", 0) == 2 else P(),
            zero_opt_state,
        )
    pspec = P(DATA_AXIS, None) if zero_params else P()
    return MocoState(
        step=P(),
        params_q=pspec,
        params_k=pspec,
        batch_stats_q=P(),
        batch_stats_k=P(),
        queue=qspec,
        queue_ptr=P(),
        opt_state=opt_spec,
        params_pred=pspec,
        batch_stats_pred=P(),
    )


def make_train_step(
    config: TrainConfig,
    encoder: MoCoEncoder,
    tx,
    mesh: Mesh,
    shard_queue_over_model: Optional[bool] = None,
    donate: bool = False,
    predictor: Optional[nn.Module] = None,
    total_steps: Optional[int] = None,
    state_template: Optional[MocoState] = None,
) -> Callable:
    """Builds the jitted SPMD train step over `mesh`.

    `state_template`: required when config.parallel.shard_weight_update
    is on — a concrete (un-placed is fine) MocoState whose opt_state tree
    provides the per-leaf sharding specs of the ZeRO layout.

    batch: {'im_q': (B_global,H,W,C), 'im_k': ...} fp32, already augmented
    (host- or device-side); sharded over the `data` axis.
    """
    cfg = config.moco
    # Training-health gauges (obs/health.py) computed inside the jitted
    # step and returned through the metrics dict — the host only ever
    # sees them on log steps, riding the existing fetch.
    health_on = config.health_metrics
    if cfg.key_bn_running_stats:
        # before the v3/predictor checks: the flag conflict is the more
        # fundamental config error and must be the one reported
        if cfg.v3:
            raise ValueError(
                "key_bn_running_stats is a v2-step lever; the v3 step "
                "manages its own momentum encoder"
            )
        if cfg.shuffle in ("gather_perm", "a2a"):
            raise ValueError(
                "key_bn_running_stats removes batch statistics from the key "
                "forward, so Shuffle-BN would be pure wasted communication: "
                "set shuffle='none' (or 'syncbn' for query-side statistics)"
            )
    if cfg.v3 and predictor is None:
        raise ValueError("v3=True requires a predictor module (build_predictor)")
    if cfg.v3 and cfg.num_negatives:
        raise ValueError("v3 is queue-free: set num_negatives=0")
    if cfg.momentum_cos and total_steps is None:
        raise ValueError("momentum_cos=True needs total_steps for the cosine ramp")
    def ema_momentum(step):
        """Constant m, or moco-v3's cosine ramp m -> 1.0 over training."""
        if not cfg.momentum_cos:
            return cfg.momentum
        # Clamp: a mid-epoch preemption resume can replay steps past
        # total_steps; without the clip cos(pi*frac) passes -1 and the
        # EMA momentum would ramp back DOWN from 1.0.
        frac = jnp.clip(step.astype(jnp.float32) / total_steps, 0.0, 1.0)
        return 1.0 - (1.0 - cfg.momentum) * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
    n_data = mesh.shape[DATA_AXIS]
    n_model = mesh.shape.get(MODEL_AXIS, 1)
    global_batch = config.data.global_batch
    if global_batch % n_data:
        raise ValueError(f"global batch {global_batch} not divisible by data axis {n_data}")
    if cfg.num_negatives:
        check_queue_divisibility(cfg.num_negatives, global_batch)
    if shard_queue_over_model is None:
        shard_queue_over_model = n_model > 1 and cfg.num_negatives > 0
    if shard_queue_over_model and cfg.num_negatives % (n_model * max(global_batch, 1)):
        raise ValueError("sharded queue requires K % (num_model*global_batch) == 0")
    zero = config.parallel.shard_weight_update
    zero23 = zero_stage23(config)
    if zero:
        if config.parallel.zero_stage not in (1, 2, 3):
            raise ValueError(
                f"zero_stage must be 1, 2 or 3, got {config.parallel.zero_stage}"
            )
        if config.optim.optimizer == "lars":
            # LARS trust ratios need whole-tensor norms; a flat shard
            # cannot compute them (moco_tpu/parallel/zero.py docstring)
            raise ValueError("shard_weight_update supports element-wise optimizers only (sgd/adamw), not lars")
        if state_template is None:
            raise ValueError("shard_weight_update needs state_template for the opt-state sharding specs")
    # ZeRO-2/3 static machinery: the persistent (n, m) layout loses the
    # original leaf shapes, so the bucket plans (and the in-step
    # reconstruction of full leaves) derive from an abstract init
    plan_trainable = plan_enc = None
    trainable_shapes = None
    zero_layer = zero_layer_granular(config)
    if config.parallel.zero_layer_granular and not zero23:
        raise ValueError(
            "zero_layer_granular requires shard_weight_update=True with "
            "zero_stage >= 2 (the per-group schedule runs on the persistent "
            "shard layout)"
        )
    if zero23:
        trainable_shapes = full_param_shapes(config, encoder, predictor)
        bucket_bytes = int(config.parallel.zero_bucket_mb * 1024 * 1024)
        plan_trainable = BucketPlan(
            jax.tree.leaves(trainable_shapes), n_data, bucket_bytes
        )
        plan_enc = BucketPlan(
            jax.tree.leaves(trainable_shapes["enc"]), n_data, bucket_bytes
        )
        _trainable_def = jax.tree.structure(trainable_shapes)
        _enc_def = jax.tree.structure(trainable_shapes["enc"])
    # ---- layer-granular stage 2/3 static machinery ---------------------
    # GroupPlan over the encoder leaves (backbone groups in schedule
    # order, then the projection head) + a separate single-group plan
    # for the predictor; the analytic HBM peak for BOTH schedules so
    # bench/harness legs can A/B without device memory_stats.
    enc_group_plan = None
    pred_bucket_plan = None
    g_names: tuple = ()
    hbm_model_peak_bytes = None
    if zero23:
        _shard_resident = _tree_shard_bytes_analytic(
            trainable_shapes, n_data
        ) + _tree_shard_bytes_analytic(trainable_shapes["enc"], n_data)
        hbm_model_peak_bytes = (
            _shard_resident
            + _tree_full_bytes(trainable_shapes)
            + _tree_full_bytes(trainable_shapes["enc"])
        )
    if zero_layer:
        if n_model > 1:
            raise ValueError(
                "zero_layer_granular requires num_model == 1 (the per-group "
                "schedule is a data-axis pipeline; model-axis sharding of the "
                "same params would double-gather)"
            )
        if cfg.vit_sequence_parallel:
            raise ValueError(
                "zero_layer_granular does not compose with vit_sequence_parallel "
                "(the token shard would cross layer-group boundaries)"
            )
        _enc_leaves = jax.tree.leaves(trainable_shapes["enc"])
        _index_tree = jax.tree.unflatten(_enc_def, list(range(len(_enc_leaves))))
        _bb_childmap = encoder.backbone.group_param_names()
        _group_specs = []
        for _g in encoder.backbone.group_names:
            _idx: list = []
            for _child in _bb_childmap[_g]:
                _idx.extend(jax.tree.leaves(_index_tree["backbone"][_child]))
            _group_specs.append((_g, tuple(_idx)))
        _group_specs.append(("head", tuple(jax.tree.leaves(_index_tree["head"]))))
        # GroupPlan raises if the backbone's group map misses any leaf —
        # a silently-ungathered param would train as garbage
        enc_group_plan = GroupPlan(_enc_leaves, _group_specs, n_data, bucket_bytes)
        g_names = tuple(g.name for g in enc_group_plan.groups)
        _pred_def = jax.tree.structure(trainable_shapes["pred"])
        _pred_bytes = _tree_full_bytes(trainable_shapes["pred"])
        if jax.tree.leaves(trainable_shapes["pred"]):
            pred_bucket_plan = BucketPlan(
                jax.tree.leaves(trainable_shapes["pred"]), n_data, bucket_bytes
            )
        # transient high-water mark of the one-group-ahead schedule: the
        # largest adjacent pair along (enc groups..., predictor)
        _sizes = [g.full_bytes for g in enc_group_plan.groups]
        if _pred_bytes:
            _sizes.append(_pred_bytes)
        _transient = (
            _sizes[0]
            if len(_sizes) == 1
            else max(a + b for a, b in zip(_sizes, _sizes[1:]))
        )
        hbm_model_peak_bytes = _shard_resident + _transient

        def _partial_enc(gname: str, full_leaves):
            """Rebuild the PARTIAL {"backbone"/"head": ...} params tree
            holding only group `gname`'s full leaves (group leaf order
            == the order `_group_specs` enumerated them). Flax never
            reads an uncalled module's params, so the grouped apply
            accepts the partial tree as-is."""
            it = iter(full_leaves)
            if gname == "head":
                d = jax.tree.structure(trainable_shapes["enc"]["head"])
                return {
                    "head": jax.tree.unflatten(
                        d, [next(it) for _ in range(d.num_leaves)]
                    )
                }
            out = {}
            for _child in _bb_childmap[gname]:
                d = jax.tree.structure(trainable_shapes["enc"]["backbone"][_child])
                out[_child] = jax.tree.unflatten(
                    d, [next(it) for _ in range(d.num_leaves)]
                )
            return {"backbone": out}

        from moco_tpu.parallel.compat import optimization_barrier

        def _tie(leaves_list, anchor):
            """One-group-ahead liveness bound: barrier-tie the NEXT
            group's gather inputs to the CURRENT group's input
            activation, so XLA may overlap that gather with the current
            group's compute but cannot hoist it any earlier — at most
            two adjacent groups' full params are ever live."""
            tied = optimization_barrier((tuple(leaves_list), anchor))
            return list(tied[0])

        def layer_key_forward(params_k0, shards_k, stats, x, train=True):
            """Grouped key forward (no grad): group 0's full params
            arrive pre-gathered from the prefetch program; each next
            group's gather is issued under the current group's compute
            (`_tie`). Returns (features, merged batch_stats)."""
            k_leaves = jax.tree.leaves(shards_k)
            cur_params = params_k0
            for gi, gname in enumerate(g_names):
                if gi + 1 < len(g_names):
                    nxt = enc_group_plan.group_shards(k_leaves, gi + 1)
                    nxt = _tie(nxt, x)
                    nxt_full = enc_group_plan.gather_group(
                        nxt, gi + 1, site_prefix="zero.gather.k"
                    )
                x, mut = encoder.apply(
                    {"params": cur_params, "batch_stats": stats},
                    x,
                    train=train,
                    mutable=["batch_stats"],
                    group=gname,
                )
                stats = _overlay(stats, mut.get("batch_stats", {}))
                if gi + 1 < len(g_names):
                    cur_params = _partial_enc(g_names[gi + 1], nxt_full)
            return x, stats

        def _make_q_segment(gi: int, gname: str):
            """One rematerialized query segment: gather the group's full
            params + run the group. `jax.checkpoint` drops the full
            params (and activations) after the forward and re-gathers in
            the backward — true ZeRO-3: backward too only ever holds one
            group's full params, at one extra gather of comms.

            Numerics: the LOSS trajectory is bitwise identical to the
            whole-tree stage (remat recomputes the same forward values),
            and on ResNet the gradients are too. On ViT, `jax.checkpoint`
            alone — no sharding, single device — shifts backward
            gradients by ~1e-9 ULPs on CPU (XLA fuses the rematerialized
            backward differently around layernorm/attention reductions),
            so ViT params track the baseline to ~1e-5 rather than
            bitwise; tests assert accordingly."""

            def seg(group_shards, x, stats):
                full = enc_group_plan.gather_group(
                    list(group_shards), gi, site_prefix="zero.gather.q"
                )
                out, mut = encoder.apply(
                    {"params": _partial_enc(gname, full), "batch_stats": stats},
                    x,
                    train=True,
                    mutable=["batch_stats"],
                    group=gname,
                )
                return out, mut.get("batch_stats", {})

            return jax.checkpoint(seg)

        _q_segments = [_make_q_segment(gi, g) for gi, g in enumerate(g_names)]

        def layer_query_forward(enc_sh, stats_q, x):
            """Grouped query forward over the SHARD tree. Each group's
            gather is tied one group ahead (to the previous segment's
            input), same liveness bound as the key side. Gradients flow
            through the in-segment gathers: their AD transpose is the
            bucketed psum_scatter, landing SUMMED cotangents directly on
            the (m,) shards. Stats thread SEQUENTIALLY through the
            segments (like the key side): flax returns the FULL mutated
            collection from a grouped apply, so feeding each segment the
            original stats would let later groups' returns clobber
            earlier groups' fresh running-stat updates in the overlay —
            and momentum-statistics BN reads the running values
            in-forward, so sequential threading is also the semantics
            that matches the whole-tree apply."""
            leaves = jax.tree.leaves(enc_sh)
            stats = stats_q
            prev_in = None
            for gi, seg in enumerate(_q_segments):
                gs = enc_group_plan.group_shards(leaves, gi)
                if prev_in is not None:
                    gs = _tie(gs, prev_in)
                cur_in = x
                x, mut = seg(tuple(gs), x, stats)
                stats = _overlay(stats, mut)
                prev_in = cur_in
            return x, stats

        def layer_pred_forward(pred_sh, stats_pred, feats):
            """Predictor segment (v3): one more group on the query
            schedule, same gather-inside-remat structure."""
            leaves = tuple(jax.tree.leaves(pred_sh))

            def seg(lvs, feats, stats):
                full = pred_bucket_plan.gather(list(lvs), site="zero.gather.q.pred")
                params = jax.tree.unflatten(_pred_def, full)
                out, mut = predictor.apply(
                    {"params": params, "batch_stats": stats},
                    feats,
                    train=True,
                    mutable=["batch_stats"],
                )
                return out, mut.get("batch_stats", {})

            return jax.checkpoint(seg)(leaves, feats, stats_pred)
    # Fused streaming InfoNCE (pallas): auto-on for a TPU backend with a
    # replicated, tile-divisible queue; explicit True forces it (interpret
    # mode off-TPU), False forces the dense logits path.
    from moco_tpu.ops.fused_infonce import DEFAULT_BLOCK_K

    fused_block_k = cfg.fused_block_k or DEFAULT_BLOCK_K
    use_fused = cfg.fused_infonce
    if use_fused and (
        fused_block_k <= 0
        or cfg.num_negatives <= 0
        or cfg.num_negatives % fused_block_k
    ):
        # infonce_stats would silently fall back to the dense path on a
        # non-divisor block — an explicit fused request must not degrade
        # to materializing the (B, 1+K) logits it exists to avoid.
        raise ValueError(
            f"fused_infonce=True needs a positive block that divides K: "
            f"K={cfg.num_negatives}, block_k={fused_block_k}"
        )
    if use_fused is None:
        use_fused = (
            jax.default_backend() == "tpu"
            and not (shard_queue_over_model or n_model > 1)
            and cfg.num_negatives > 0
            and cfg.num_negatives % fused_block_k == 0
        )
    if use_fused and shard_queue_over_model:
        raise ValueError("fused_infonce does not support a model-sharded queue")

    def apply_encoder(params, batch_stats, x, train=True):
        out, mut = encoder.apply(
            {"params": params, "batch_stats": batch_stats},
            x,
            train=train,
            mutable=["batch_stats"],
        )
        return out, mut["batch_stats"]

    # Rematerialization: recompute the query forward during backward
    # instead of keeping every activation live (SURVEY.md hard-part 6 /
    # the HBM-vs-FLOPs trade). Key-side forwards carry no gradient, so
    # only the grad-bearing query apply is wrapped.
    grad_apply_encoder = (
        jax.checkpoint(lambda p, s, x: apply_encoder(p, s, x)) if cfg.remat else apply_encoder
    )

    def apply_predictor(params, batch_stats, x, train=True):
        out, mut = predictor.apply(
            {"params": params, "batch_stats": batch_stats},
            x,
            train=train,
            mutable=["batch_stats"],
        )
        return out, mut["batch_stats"]

    def zero23_update(state: MocoState, grads):
        """ZeRO-2/3 weight update on the persistent shards: bucketed
        psum_scatter of the full local grads (one collective per fusion
        bucket, issued as backward produces each bucket's leaves), then
        the elementwise optimizer on this replica's (m,) rows only. NO
        trailing all_gather — the params stay sharded; the next step's
        gather re-materializes them. Returns (old shard trees, new
        shard trees, expanded opt state)."""
        grad_leaves, grad_def = jax.tree.flatten(grads)
        grad_sh = jax.tree.unflatten(
            grad_def, plan_trainable.scatter_mean(grad_leaves, site="zero.scatter")
        )
        trainable_sh = {
            "enc": squeeze_opt_state(state.params_q),
            "pred": squeeze_opt_state(state.params_pred),
        }
        updates, new_opt = tx.update(
            grad_sh, squeeze_opt_state(state.opt_state), trainable_sh
        )
        new_tr_sh = jax.tree.map(lambda p, u: p + u, trainable_sh, updates)
        return trainable_sh, new_tr_sh, expand_opt_state(new_opt)

    def zero_layer_update(state: MocoState, grad_sh):
        """Layer-granular weight update: the in-segment gathers' AD
        transposes already psum_scatter'd the grads onto the (m,)
        shards as cross-replica SUMS — divide by n for the mean
        (element→row assignment and ring order match `scatter_mean`,
        so the result is bit-identical to `zero23_update`'s), then the
        elementwise optimizer on this replica's rows. Same return
        contract as `zero23_update`."""
        grad_sh = jax.tree.map(lambda g: g / n_data, grad_sh)
        trainable_sh = {
            "enc": squeeze_opt_state(state.params_q),
            "pred": squeeze_opt_state(state.params_pred),
        }
        updates, new_opt = tx.update(
            grad_sh, squeeze_opt_state(state.opt_state), trainable_sh
        )
        new_tr_sh = jax.tree.map(lambda p, u: p + u, trainable_sh, updates)
        return trainable_sh, new_tr_sh, expand_opt_state(new_opt)

    def gather_core(state: MocoState) -> ZeroGathered:
        """ZeRO-2/3 step-start stage, hoisted into the pipelined driver
        so it hides under the previous step's compute: the EMA key
        update runs SHARD-LOCAL (elementwise on this replica's rows —
        no collective at all), then one bucketed all_gather per param
        family re-materializes the full trees the step consumes."""
        m = ema_momentum(state.step)
        trainable_sh = {
            "enc": squeeze_opt_state(state.params_q),
            "pred": squeeze_opt_state(state.params_pred),
        }
        k_sh = ema_update(
            squeeze_opt_state(state.params_k), trainable_sh["enc"], m
        )
        t_leaves, t_def = jax.tree.flatten(trainable_sh)
        trainable_full = jax.tree.unflatten(
            t_def, plan_trainable.gather(t_leaves, site="zero.gather_q")
        )
        k_leaves, k_def = jax.tree.flatten(k_sh)
        params_k_full = jax.tree.unflatten(
            k_def, plan_enc.gather(k_leaves, site="zero.gather_k")
        )
        return ZeroGathered(
            trainable=trainable_full,
            params_k=params_k_full,
            shards_k=expand_opt_state(k_sh),
        )

    def gather_core_layer(state: MocoState) -> ZeroGathered:
        """Layer-granular prefetch program: same shard-local EMA as
        `gather_core`, but gather ONLY key group 0 — the step's in-loop
        pipeline gathers each next key group under the previous group's
        compute, and the query side re-gathers inside its rematerialized
        segments, so nothing else pre-materializes. `trainable` is empty:
        the layer step differentiates over the shards directly."""
        m = ema_momentum(state.step)
        enc_sh = squeeze_opt_state(state.params_q)
        k_sh = ema_update(squeeze_opt_state(state.params_k), enc_sh, m)
        k_leaves = jax.tree.leaves(k_sh)
        g0_full = enc_group_plan.gather_group(
            enc_group_plan.group_shards(k_leaves, 0), 0, site_prefix="zero.gather.k"
        )
        return ZeroGathered(
            trainable={},
            params_k=_partial_enc(g_names[0], g0_full),
            shards_k=expand_opt_state(k_sh),
        )

    def v3_step(state: MocoState, batch, gathered: Optional[ZeroGathered] = None):
        """MoCo v3 (arXiv:2104.02057 alg. 1): symmetric queue-free
        contrastive loss, both views through both encoders, the global
        batch as negatives, 2τ loss scaling. `gathered` (ZeRO-2/3): the
        full params arrive pre-gathered (EMA already applied shard-local
        in the gather stage) and the update writes back to shards."""
        im_q, im_k = batch["im_q"], batch["im_k"]
        local_b = im_q.shape[0]
        x_cat = jnp.concatenate([im_q, im_k], axis=0)

        if zero_layer:
            # grouped key forward over the freshly-EMA'd shards; group 0
            # arrives pre-gathered from the prefetch program
            params_k = None
            k_cat, stats_k = layer_key_forward(
                gathered.params_k,
                squeeze_opt_state(gathered.shards_k),
                state.batch_stats_k,
                x_cat,
            )
        else:
            if gathered is None:
                params_k = ema_update(
                    state.params_k, state.params_q, ema_momentum(state.step)
                )
            else:
                params_k = gathered.params_k
            k_cat, stats_k = apply_encoder(params_k, state.batch_stats_k, x_cat)
        k1, k2 = jnp.split(lax.stop_gradient(l2_normalize(k_cat)), 2, axis=0)
        if n_data > 1:
            with comms.tag("v3.key_gather", "all_gather", (k1, k2), n_data):
                k1_g = lax.all_gather(k1, DATA_AXIS).reshape(-1, cfg.dim)
                k2_g = lax.all_gather(k2, DATA_AXIS).reshape(-1, cfg.dim)
            rank = lax.axis_index(DATA_AXIS)
        else:
            k1_g, k2_g, rank = k1, k2, 0
        labels = rank * local_b + jnp.arange(local_b, dtype=jnp.int32)

        def ctr(q, k_g):
            logits = q @ k_g.T / cfg.temperature
            return 2.0 * cfg.temperature * cross_entropy(logits, labels), logits

        def loss_fn(trainable):
            if zero_layer:
                # layer-granular: `trainable` is the SHARD tree; each
                # segment gathers its group's full params just-in-time
                feats, stats_q = layer_query_forward(
                    trainable["enc"], state.batch_stats_q, x_cat
                )
                preds, stats_pred = layer_pred_forward(
                    trainable["pred"], state.batch_stats_pred, feats
                )
            else:
                feats, stats_q = grad_apply_encoder(
                    trainable["enc"], state.batch_stats_q, x_cat
                )
                preds, stats_pred = apply_predictor(
                    trainable["pred"], state.batch_stats_pred, feats
                )
            q1, q2 = jnp.split(l2_normalize(preds), 2, axis=0)
            loss1, logits = ctr(q1, k2_g)
            loss2, _ = ctr(q2, k1_g)
            return loss1 + loss2, (stats_q, stats_pred, logits, q1)

        if zero_layer:
            trainable = {
                "enc": squeeze_opt_state(state.params_q),
                "pred": squeeze_opt_state(state.params_pred),
            }
        else:
            trainable = (
                {"enc": state.params_q, "pred": state.params_pred}
                if gathered is None
                else gathered.trainable
            )
        (loss, (stats_q, stats_pred, logits, q1)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(trainable)
        if cfg.freeze_patch_embed and "patch_embed" in grads["enc"].get("backbone", {}):
            grads["enc"]["backbone"]["patch_embed"] = jax.tree.map(
                jnp.zeros_like, grads["enc"]["backbone"]["patch_embed"]
            )
        if cfg.vit_sequence_parallel:
            # Sequence parallelism: each model-axis member backprops only
            # through ITS token shard, so backbone grads are PARTIAL sums
            # — psum over the sequence (model) axis restores the full
            # gradient. Head/predictor grads are replicated-identical
            # (they consume the psum-pooled feature) and stay untouched.
            with comms.tag(
                "grad.seq_psum", "psum", grads["enc"]["backbone"], n_model
            ):
                grads["enc"]["backbone"] = lax.psum(
                    grads["enc"]["backbone"], MODEL_AXIS
                )
        metrics = {"loss": loss, **topk_accuracy(logits, labels)}
        metrics = lax.pmean(metrics, DATA_AXIS)
        stats_q = lax.pmean(stats_q, DATA_AXIS)
        stats_k = lax.pmean(stats_k, DATA_AXIS)
        stats_pred = lax.pmean(stats_pred, DATA_AXIS)

        if gathered is not None:
            # ZeRO-2/3: bucketed psum_scatter + shard-local update; the
            # params never re-materialize — the next step's gather does.
            # In layer mode the scatter already ran inside the segments'
            # backward, so `grads` arrived as summed (m,) shards.
            if zero_layer:
                trainable_sh, new_tr_sh, opt_state = zero_layer_update(state, grads)
            else:
                trainable_sh, new_tr_sh, opt_state = zero23_update(state, grads)
            if cfg.freeze_patch_embed and "patch_embed" in new_tr_sh["enc"].get(
                "backbone", {}
            ):
                # zeroed grads stop the gradient; restoring the OLD
                # shards also blocks AdamW's decoupled decay — the
                # shard-level mirror of the stage-1 full-params freeze
                new_tr_sh["enc"]["backbone"]["patch_embed"] = trainable_sh["enc"][
                    "backbone"
                ]["patch_embed"]
            drift = lambda: obs_health.ema_drift_sharded(
                new_tr_sh["enc"], squeeze_opt_state(gathered.shards_k), DATA_AXIS
            )
            out_params = dict(
                params_q=expand_opt_state(new_tr_sh["enc"]),
                params_pred=expand_opt_state(new_tr_sh["pred"]),
                params_k=gathered.shards_k,
            )
        elif zero:
            # Sharded weight update (parallel/zero.py stage 1):
            # psum_scatter fuses the grad mean-reduction with the 1/n
            # sharding. The patch-embed freeze is applied to the
            # gathered FULL params below, so AdamW's decoupled decay
            # cannot move them either.
            frozen_pe = (
                trainable["enc"]["backbone"]["patch_embed"]
                if cfg.freeze_patch_embed
                and "patch_embed" in trainable["enc"].get("backbone", {})
                else None
            )
            new_trainable, opt_state = sharded_update(
                tx, grads, state.opt_state, trainable
            )
            if frozen_pe is not None:
                new_trainable["enc"]["backbone"]["patch_embed"] = frozen_pe
            drift = lambda: obs_health.ema_drift(new_trainable["enc"], params_k)
            out_params = dict(
                params_q=new_trainable["enc"],
                params_pred=new_trainable["pred"],
                params_k=params_k,
            )
        else:
            with comms.tag("grad.psum", "psum", grads, n_data):
                grads = lax.pmean(grads, DATA_AXIS)
            updates, opt_state = tx.update(grads, state.opt_state, trainable)
            if cfg.freeze_patch_embed and "patch_embed" in updates["enc"].get("backbone", {}):
                # zeroed grads are not enough: AdamW's decoupled weight decay
                # still moves zero-grad params, so zero the *update* as well
                updates["enc"]["backbone"]["patch_embed"] = jax.tree.map(
                    jnp.zeros_like, updates["enc"]["backbone"]["patch_embed"]
                )
            new_trainable = optax.apply_updates(trainable, updates)
            drift = lambda: obs_health.ema_drift(new_trainable["enc"], params_k)
            out_params = dict(
                params_q=new_trainable["enc"],
                params_pred=new_trainable["pred"],
                params_k=params_k,
            )
        if health_on:
            # batch-local stats pmean over data; drift is a function of
            # replicated params — or, at ZeRO stage 2/3, of the shards
            # with a psum'd norm (v3 has no queue, so no staleness gauges)
            hlocal = {
                **obs_health.logit_stats_from_dense(logits, labels),
                **obs_health.feature_stats(q1),
            }
            metrics.update(lax.pmean(hlocal, DATA_AXIS))
            metrics.update(drift())
        new_state = state.replace(
            step=state.step + 1,
            batch_stats_q=stats_q,
            batch_stats_k=stats_k,
            batch_stats_pred=stats_pred,
            opt_state=opt_state,
            **out_params,
        )
        return new_state, metrics

    def step_fn(state: MocoState, batch, root_rng, gathered: Optional[ZeroGathered] = None):
        if cfg.v3:
            return v3_step(state, batch, gathered=gathered)
        im_q, im_k = batch["im_q"], batch["im_k"]
        local_b = im_q.shape[0]
        # Deterministic per-step randomness, identical on every device:
        # replaces the reference's `broadcast(idx_shuffle, src=0)`
        # (moco/builder.py:~L89).
        step_rng = jax.random.fold_in(root_rng, state.step)

        # (1) EMA momentum update of the key encoder, *before* the key
        # forward, as upstream orders it (moco/builder.py:~L139-141).
        # At ZeRO stage 2/3 both encoders live as shards and the EMA
        # already ran shard-local inside the gather stage.
        if zero_layer:
            # grouped key forward (one-group-ahead pipeline); group 0
            # arrives pre-gathered from the prefetch program
            params_k = None
            _k_shards = squeeze_opt_state(gathered.shards_k)
            key_apply = lambda stats, x, train=True: layer_key_forward(
                gathered.params_k, _k_shards, stats, x, train=train
            )
        else:
            if gathered is None:
                params_k = ema_update(
                    state.params_k, state.params_q, ema_momentum(state.step)
                )
            else:
                params_k = gathered.params_k
            key_apply = lambda stats, x, train=True: apply_encoder(
                params_k, stats, x, train=train
            )

        # (2) Shuffle-BN: compute keys on a batch that contains none of
        # this device's own positives. With bn_virtual_groups the same
        # permutation machinery runs even on ONE device (all_gather over
        # a size-1 axis is the identity, so gather_perm degrades to a
        # pure in-batch permutation): per-group BN statistics + permuted
        # group composition = the reference's G-GPU Shuffle-BN inside a
        # single chip's batch.
        shuffle_active = n_data > 1 or cfg.bn_virtual_groups > 1
        if cfg.shuffle == "gather_perm" and shuffle_active:
            perm, inv_perm = make_permutation(step_rng, global_batch)
            im_k_sh = shuffle_gather(im_k, perm, DATA_AXIS)
            k_sh, stats_k = key_apply(state.batch_stats_k, im_k_sh)
            k_sh = l2_normalize(k_sh)
            k_local, k_global = unshuffle_gather(k_sh, inv_perm, DATA_AXIS)
        elif cfg.shuffle == "a2a" and shuffle_active:
            im_k_sh = balanced_shuffle(step_rng, im_k, DATA_AXIS)
            k_sh, stats_k = key_apply(state.batch_stats_k, im_k_sh)
            k_sh = l2_normalize(k_sh)
            # the unshuffle must regenerate the SAME permutation as the
            # shuffle above, so reusing step_rng is the contract, not a bug
            k_local = balanced_unshuffle(step_rng, k_sh, DATA_AXIS)  # mocolint: disable=JX003
            with comms.tag("queue.enqueue_gather", "all_gather", k_local, n_data):
                k_global = lax.all_gather(k_local, DATA_AXIS).reshape(-1, cfg.dim)
        else:  # 'syncbn' (cross-replica BN handles decorrelation) or 'none'
            # key_bn_running_stats (EMAN, config.py rationale): the key
            # forward runs EVAL-mode BN against the EMA'd running stats —
            # no statistics pass, no composition leak, no shuffle
            # collectives; the returned stats tree is unchanged and is
            # replaced by the EMA advance in (4) below.
            k_local, stats_k = key_apply(
                state.batch_stats_k, im_k, train=not cfg.key_bn_running_stats
            )
            k_local = l2_normalize(k_local)
            if n_data > 1:
                with comms.tag("queue.enqueue_gather", "all_gather", k_local, n_data):
                    k_global = lax.all_gather(k_local, DATA_AXIS).reshape(-1, cfg.dim)
            else:
                k_global = k_local
        k_local = lax.stop_gradient(k_local)
        k_global = lax.stop_gradient(k_global)

        # (3) Query forward + InfoNCE loss (moco/builder.py:~L128-161).
        def loss_fn(trainable):
            if zero_layer:
                q, stats_q = layer_query_forward(
                    trainable["enc"], state.batch_stats_q, im_q
                )
            else:
                q, stats_q = grad_apply_encoder(
                    trainable["enc"], state.batch_stats_q, im_q
                )
            q = l2_normalize(q)
            if cfg.num_negatives and use_fused:
                # streaming pallas kernel: never materializes (B, 1+K)
                from moco_tpu.ops.fused_infonce import fused_infonce_loss

                loss, acc = fused_infonce_loss(
                    q,
                    k_local,
                    state.queue,
                    cfg.temperature,
                    block_k=fused_block_k,
                    interpret=jax.default_backend() != "tpu",
                )
            elif cfg.num_negatives:
                logits, labels = infonce_logits(q, k_local, state.queue, cfg.temperature)
                if shard_queue_over_model:
                    # queue rows are sharded over `model`: logits currently
                    # hold [pos | my negative shard]; assemble full rows.
                    l_pos, l_neg = logits[:, :1], logits[:, 1:]
                    with comms.tag("queue.logits_gather", "all_gather", l_neg, n_model):
                        l_neg = lax.all_gather(l_neg, MODEL_AXIS, axis=1, tiled=True)
                    logits = jnp.concatenate([l_pos, l_neg], axis=1)
                loss = cross_entropy(logits, labels)
                acc = topk_accuracy(logits, labels)
            else:
                # v3-style queue-free: global batch keys are the negatives.
                logits = q @ k_global.T / cfg.temperature
                rank = lax.axis_index(DATA_AXIS)
                labels = rank * local_b + jnp.arange(local_b, dtype=jnp.int32)
                loss = cross_entropy(logits, labels)
                acc = topk_accuracy(logits, labels)
            return loss, (stats_q, acc, q)

        if zero_layer:
            trainable = {
                "enc": squeeze_opt_state(state.params_q),
                "pred": squeeze_opt_state(state.params_pred),
            }
        else:
            trainable = (
                {"enc": state.params_q, "pred": state.params_pred}
                if gathered is None
                else gathered.trainable
            )
        (loss, (stats_q, acc, q_feats)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            trainable
        )

        # (4) Gradient + metric reduction over data (DDP all-reduce equiv).
        # With a model-sharded queue the backward of the MODEL-axis
        # all_gather is a reduce-scatter: shard m's grads carry only (M x)
        # its own negative shard's contribution, so they must also be
        # pmean'd over MODEL — the factor M cancels exactly, restoring the
        # replicated-params invariant.
        metrics = {"loss": loss, **acc}
        metrics = lax.pmean(metrics, DATA_AXIS)
        # Running BN stats: average across devices (strictly better than
        # the reference, which checkpoints rank 0's local stats).
        stats_q = lax.pmean(stats_q, DATA_AXIS)
        if cfg.key_bn_running_stats:
            # the key's running statistics trail the query's on the
            # params' momentum schedule (EMAN); stats_q is already
            # pmean'd, so the EMA stays replicated in lockstep
            m_stats = ema_momentum(state.step)
            if cfg.key_bn_stats_warmup:
                # fast-track early statistics (tf.train.EMA num_updates
                # schedule): at m=0.999 a cold-start EMA would normalize
                # keys with badly stale statistics for hundreds of steps
                # — the r4 accuracy arm's suspected failure mechanism
                step_f = state.step.astype(jnp.float32)
                m_stats = jnp.minimum(m_stats, (1.0 + step_f) / (10.0 + step_f))
            stats_k = ema_update(state.batch_stats_k, stats_q, m_stats)
        else:
            stats_k = lax.pmean(stats_k, DATA_AXIS)

        # (5) Optimizer update: replicated full update, or — with
        # shard_weight_update — ZeRO-style (parallel/zero.py): the grad
        # psum_scatter replaces the pmean at identical comm volume, the
        # optimizer touches only this replica's 1/n shard, and an
        # all_gather rebuilds the full params (stage 1) — or never does,
        # because the params persist as shards (stage 2/3).
        if gathered is not None:
            if shard_queue_over_model:
                grads = lax.pmean(grads, MODEL_AXIS)
            if zero_layer:
                _, new_tr_sh, opt_state = zero_layer_update(state, grads)
            else:
                _, new_tr_sh, opt_state = zero23_update(state, grads)
            drift = lambda: obs_health.ema_drift_sharded(
                new_tr_sh["enc"], squeeze_opt_state(gathered.shards_k), DATA_AXIS
            )
            out_params = dict(
                params_q=expand_opt_state(new_tr_sh["enc"]),
                params_pred=expand_opt_state(new_tr_sh["pred"]),
                params_k=gathered.shards_k,
            )
        elif zero:
            if shard_queue_over_model:
                grads = lax.pmean(grads, MODEL_AXIS)
            new_trainable, opt_state = sharded_update(
                tx, grads, state.opt_state, trainable
            )
            params_q = new_trainable["enc"]
            drift = lambda: obs_health.ema_drift(params_q, params_k)
            out_params = dict(params_q=params_q, params_k=params_k)
        else:
            grad_axes = (DATA_AXIS, MODEL_AXIS) if shard_queue_over_model else DATA_AXIS
            grad_world = n_data * (n_model if shard_queue_over_model else 1)
            with comms.tag("grad.psum", "psum", grads, grad_world):
                grads = lax.pmean(grads, grad_axes)
            updates, opt_state = tx.update(grads, state.opt_state, trainable)
            params_q = optax.apply_updates(trainable, updates)["enc"]
            drift = lambda: obs_health.ema_drift(params_q, params_k)
            out_params = dict(params_q=params_q, params_k=params_k)

        # (6) FIFO enqueue of the global key batch
        # (moco/builder.py:~L62-77); with a model-sharded queue each shard
        # writes only the rows that fall inside it.
        if cfg.num_negatives:
            if shard_queue_over_model:
                shard_rows = cfg.num_negatives // n_model
                m_rank = lax.axis_index(MODEL_AXIS)
                offset = m_rank * shard_rows
                local_ptr = state.queue_ptr - offset
                in_range = (local_ptr >= 0) & (local_ptr + global_batch <= shard_rows)
                safe_ptr = jnp.clip(local_ptr, 0, shard_rows - global_batch)
                written, _ = enqueue(state.queue, safe_ptr, k_global)
                queue = jnp.where(in_range, written, state.queue)
                queue_ptr = (state.queue_ptr + global_batch) % cfg.num_negatives
            else:
                queue, queue_ptr = enqueue(state.queue, state.queue_ptr, k_global)
        else:
            queue, queue_ptr = state.queue, state.queue_ptr

        # (7) Training-health gauges (obs/health.py), identical math on
        # the fused and dense paths: positives recomputed from the
        # (q, k) diagonal; negatives from a bounded queue sample (the
        # full K-row pass is exactly what the fused kernel avoids
        # materializing), in post-temperature units.
        if health_on:
            q_h = lax.stop_gradient(q_feats)
            pos_l = jnp.sum(q_h * k_local, axis=-1) / cfg.temperature
            if cfg.num_negatives:
                rows = min(1024, state.queue.shape[0])
                neg_ref = lax.stop_gradient(state.queue[:rows])
            else:
                # queue-free: the gathered key batch is the negative set
                # (contains each row's own positive — 1/B_global of the
                # sample, negligible contamination for a gauge)
                neg_ref = k_global
            neg_l = (q_h @ neg_ref.T) / cfg.temperature
            hlocal = {
                **obs_health.logit_stats(pos_l, neg_l),
                **obs_health.feature_stats(q_h),
            }
            metrics.update(lax.pmean(hlocal, DATA_AXIS))
            metrics.update(drift())
            if cfg.num_negatives:
                metrics.update(
                    obs_health.queue_age(state.step, cfg.num_negatives, global_batch)
                )

        new_state = state.replace(
            step=state.step + 1,
            batch_stats_q=stats_q,
            batch_stats_k=stats_k,
            queue=queue,
            queue_ptr=queue_ptr,
            opt_state=opt_state,
            **out_params,
        )
        return new_state, metrics

    specs = state_specs(
        shard_queue_over_model,
        zero_opt_state=state_template.opt_state if zero else None,
        zero_params=zero23,
    )
    batch_spec = {"im_q": P(DATA_AXIS), "im_k": P(DATA_AXIS)}
    # Explicit in/out shardings matter: letting jit infer them from a
    # SingleDeviceSharding initial state makes every later call re-lay-out
    # the whole state (~120ms per step through the axon tunnel, measured).
    # Callers should `place_state` the initial state onto the mesh.
    to_sharding = lambda tree: jax.tree.map(
        lambda s: NamedSharding(mesh, s), tree, is_leaf=lambda x: isinstance(x, P)
    )
    state_shardings = to_sharding(specs)
    if not zero23:
        sharded = shard_map(
            step_fn,
            mesh=mesh,
            in_specs=(specs, batch_spec, P()),
            out_specs=(specs, P()),
            check_vma=False,
        )
        jit_kwargs = dict(
            in_shardings=(state_shardings, to_sharding(batch_spec), NamedSharding(mesh, P())),
            out_shardings=(state_shardings, NamedSharding(mesh, P())),
        )
        # Donation halves peak state memory but is pathologically slow through
        # the axon remote-TPU tunnel (~80ms/call fixed cost, measured); state
        # buffers are small relative to HBM, so it stays opt-in.
        if donate:
            jit_kwargs["donate_argnums"] = 0
        return jax.jit(sharded, **jit_kwargs)

    # -- ZeRO-2/3: two jitted programs, (gather, step) -------------------
    gathered_specs = ZeroGathered(
        trainable=P(), params_k=P(), shards_k=P(DATA_AXIS, None)
    )
    gather_sharded = shard_map(
        gather_core_layer if zero_layer else gather_core,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=gathered_specs,
        check_vma=False,
    )
    gather_jit = jax.jit(
        gather_sharded,
        in_shardings=(state_shardings,),
        out_shardings=to_sharding(gathered_specs),
    )
    step_sharded = shard_map(
        lambda state, gathered, batch, rng: step_fn(state, batch, rng, gathered=gathered),
        mesh=mesh,
        in_specs=(specs, gathered_specs, batch_spec, P()),
        out_specs=(specs, P()),
        check_vma=False,
    )
    step_kwargs = dict(
        in_shardings=(
            state_shardings,
            to_sharding(gathered_specs),
            to_sharding(batch_spec),
            NamedSharding(mesh, P()),
        ),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
    )
    # The gathered full params are one-shot by construction: donating
    # them lets XLA reuse their HBM during the backward, so peak ~
    # shards + one live gathered copy, never two. CPU lacks donation
    # support (it would only warn), so gate on the backend.
    donate_nums = tuple(
        ([0] if donate else []) + ([1] if jax.default_backend() in ("tpu", "gpu") else [])
    )
    if donate_nums:
        step_kwargs["donate_argnums"] = donate_nums
    return Zero23TrainStep(
        gather=gather_jit,
        step=jax.jit(step_sharded, **step_kwargs),
        param_shapes=trainable_shapes,
        bucket_plans={"trainable": plan_trainable, "enc": plan_enc},
        group_plan=enc_group_plan,
        layer_granular=zero_layer,
        hbm_model_peak_bytes=hbm_model_peak_bytes,
    )


def place_state(
    state: MocoState,
    mesh: Mesh,
    shard_queue_over_model: bool = False,
    zero: bool = False,
    zero_params: bool = False,
) -> MocoState:
    """device_put the state into the mesh shardings the train step expects.
    `zero=True` shards the (n, m) opt-state leaves over `data` (sharded
    weight update, parallel/zero.py); `zero_params=True` additionally
    shards the persistent param trees (ZeRO stage 2/3 layout)."""
    specs = state_specs(
        shard_queue_over_model,
        zero_opt_state=state.opt_state if zero else None,
        zero_params=zero_params,
    )
    placed = {}
    for name in state.__dataclass_fields__:
        spec = getattr(specs, name)
        value = getattr(state, name)
        if isinstance(spec, P):  # one spec for the whole subtree
            sharding = NamedSharding(mesh, spec)
            placed[name] = jax.tree.map(lambda x: jax.device_put(x, sharding), value)
        else:  # per-leaf spec tree (ZeRO opt state)
            placed[name] = jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), value, spec
            )
    return MocoState(**placed)


class Zero23TrainStep:
    """The ZeRO-2/3 train step as a (gather, step) pair of jitted
    programs (make_train_step return value when zero_stage >= 2).

    - `gather(state) -> ZeroGathered`: shard-local EMA + the bucketed
      params all_gather. The pipelined driver runs this on the
      AsyncParamGather worker so step k+1's gather hides under step k.
    - `step(state, gathered, batch, rng) -> (state, metrics)`: the SPMD
      step consuming the pre-gathered full params (donated on backends
      with donation support).

    Calling the object runs both inline — the un-hoisted schedule —
    so non-pipelined callers (tests, bench legs) keep the single-callable
    contract of the classic step.

    `layer_granular` marks the per-group schedule
    (`parallel.zero_layer_granular`): `gather` is then the group-0
    prefetch program and `group_plan` the encoder's `GroupPlan`.
    `hbm_model_peak_bytes` is the ANALYTIC per-chip model-memory
    high-water mark (persistent shards + the schedule's transient full
    params: whole trainable + key tree for the classic gather, the
    largest adjacent group pair for the layer schedule) — the gauge the
    CPU-smoke bench legs track where `device_memory_stats` is None.
    """

    def __init__(
        self,
        gather,
        step,
        param_shapes,
        bucket_plans,
        group_plan=None,
        layer_granular: bool = False,
        hbm_model_peak_bytes: Optional[int] = None,
    ):
        self.gather = gather
        self.step = step
        self.param_shapes = param_shapes  # {"enc": ..., "pred": ...} abstract
        self.bucket_plans = bucket_plans
        self.group_plan = group_plan
        self.layer_granular = layer_granular
        self.hbm_model_peak_bytes = hbm_model_peak_bytes

    def __call__(self, state, batch, root_rng):
        return self.step(state, self.gather(state), batch, root_rng)


def reshard_state(
    state_saved: MocoState,
    live_template: MocoState,
    full_template: MocoState,
) -> MocoState:
    """Host-side layout conversion between ZeRO checkpoint layouts —
    the "compatible but resharded" resume: zero1 <-> zero23, sharded <->
    replicated, and data-axis-width changes all route through the flat
    vector. `live_template` has the target layout's leaf shapes,
    `full_template` the replicated (true) shapes — needed because the
    (n, m) layout does not record them. Only the param trees and the
    optimizer state reshard; every other field passes through."""

    def _conv(saved, live, full):
        saved_np = np.asarray(saved)
        live_shape = tuple(live.shape)
        full_shape = tuple(full.shape)
        dtype = live.dtype
        if saved_np.shape == live_shape:
            return saved_np.astype(dtype)
        size = int(np.prod(full_shape)) if full_shape else 1
        flat = saved_np.reshape(-1)[:size]  # strip source padding
        if live_shape == full_shape:
            return flat.reshape(full_shape).astype(dtype)
        n, m = live_shape  # target (n, m) sharded-flat
        return np.pad(flat, (0, n * m - size)).reshape(n, m).astype(dtype)

    placed = {}
    for name in state_saved.__dataclass_fields__:
        value = getattr(state_saved, name)
        if name in ("params_q", "params_k", "params_pred", "opt_state"):
            placed[name] = jax.tree.map(
                _conv, value, getattr(live_template, name), getattr(full_template, name)
            )
        else:
            placed[name] = value
    return MocoState(**placed)
