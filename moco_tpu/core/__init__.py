from moco_tpu.core.ema import ema_update
from moco_tpu.core.moco import (
    MoCoEncoder,
    MocoState,
    Zero23TrainStep,
    ZeroGathered,
    build_encoder,
    build_predictor,
    create_state,
    full_param_shapes,
    make_train_step,
    place_state,
    reshard_state,
    state_specs,
    zero_stage23,
)
from moco_tpu.core.queue import check_queue_divisibility, enqueue, init_queue

__all__ = [
    "ema_update",
    "MoCoEncoder",
    "MocoState",
    "Zero23TrainStep",
    "ZeroGathered",
    "build_encoder",
    "build_predictor",
    "create_state",
    "full_param_shapes",
    "make_train_step",
    "place_state",
    "reshard_state",
    "state_specs",
    "zero_stage23",
    "check_queue_divisibility",
    "enqueue",
    "init_queue",
]
