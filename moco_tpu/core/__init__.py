from moco_tpu.core.ema import ema_update
from moco_tpu.core.moco import (
    MoCoEncoder,
    MocoState,
    build_encoder,
    build_predictor,
    create_state,
    make_train_step,
    place_state,
    state_specs,
)
from moco_tpu.core.queue import check_queue_divisibility, enqueue, init_queue

__all__ = [
    "ema_update",
    "MoCoEncoder",
    "MocoState",
    "build_encoder",
    "build_predictor",
    "create_state",
    "make_train_step",
    "place_state",
    "state_specs",
    "check_queue_divisibility",
    "enqueue",
    "init_queue",
]
