"""Checkpoint IMPORT: migrate a reference `.pth.tar` into this framework.

The reference's artifact of record is a torch checkpoint
(`main_moco.py:~L312-320`: `{'epoch','arch','state_dict','optimizer'}`
with DDP-prefixed keys `module.encoder_q.*`, `module.encoder_k.*`,
`module.queue`, `module.queue_ptr`). A user switching frameworks brings
those files along — this module is the inverse of `moco_tpu/export.py`:
torch/torchvision weight layout → Flax trees, then a full `MocoState`
saved as an Orbax checkpoint that `train.py --resume`-style auto-resume,
`eval_lincls.py`, and `convert_pretrain.py` consume directly.

What transfers: both encoders' params + BN running stats, the MLP/linear
head, the negative queue and its pointer ((dim, K) column layout →
our (K, dim) rows), and the epoch counter. The torch SGD momentum
buffers are NOT mapped — the optimizer state starts fresh, which the
reference itself treats as acceptable for transfer (its lincls/detection
consumers drop the optimizer too).

Weight-layout rules (inverse of export.py):
- conv (Cout, Cin, H, W) → (H, W, Cin, Cout)
- dense (Cout, Cin) → (Cin, Cout)
- BatchNorm weight→scale, bias→bias, running_mean→mean, running_var→var
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from moco_tpu.export import STAGE_SIZES

__all__ = [
    "torchvision_to_resnet",
    "timm_to_vit",
    "head_from_torch",
    "import_reference_state_dict",
]


def _conv(w: np.ndarray) -> np.ndarray:
    return np.asarray(w, np.float32).transpose(2, 3, 1, 0)


def _dense(w: np.ndarray) -> np.ndarray:
    return np.asarray(w, np.float32).T


def _f32(w) -> np.ndarray:
    return np.asarray(w, np.float32)


def _convbn(sd: Dict[str, Any], conv: str, bn: str) -> Tuple[dict, dict]:
    params = {
        "Conv_0": {"kernel": _conv(sd[f"{conv}.weight"])},
        "BatchNorm_0": {"scale": _f32(sd[f"{bn}.weight"]), "bias": _f32(sd[f"{bn}.bias"])},
    }
    stats = {
        "BatchNorm_0": {
            "mean": _f32(sd[f"{bn}.running_mean"]),
            "var": _f32(sd[f"{bn}.running_var"]),
        }
    }
    return params, stats


def torchvision_to_resnet(
    sd: Dict[str, Any], stage_sizes=(3, 4, 6, 3)
) -> Tuple[dict, dict]:
    """torchvision-named ResNet state dict → (params, batch_stats) Flax
    trees matching `moco_tpu.models.resnet` — exact inverse of
    `export.resnet_to_torchvision` (round-trip tested)."""
    params: dict = {}
    stats: dict = {}
    # ImageNet stem (conv1 7x7). The CIFAR-stem variant exports under the
    # same torchvision names, so the kernel size disambiguates on import.
    k = np.asarray(sd["conv1.weight"])
    stem_p = {
        "kernel": _conv(sd["conv1.weight"]),
    }
    bn_p = {"scale": _f32(sd["bn1.weight"]), "bias": _f32(sd["bn1.bias"])}
    bn_s = {"mean": _f32(sd["bn1.running_mean"]), "var": _f32(sd["bn1.running_var"])}
    if k.shape[-1] == 7:  # ImageNet stem: top-level Conv_0/BatchNorm_0
        params["Conv_0"] = stem_p
        params["BatchNorm_0"] = bn_p
        stats["BatchNorm_0"] = bn_s
    else:  # CIFAR stem: a ConvBN_0 submodule
        params["ConvBN_0"] = {"Conv_0": stem_p, "BatchNorm_0": bn_p}
        stats["ConvBN_0"] = {"BatchNorm_0": bn_s}

    # block class from the conv count of the first block
    is_bottleneck = "layer1.0.conv3.weight" in sd
    n_main = 3 if is_bottleneck else 2
    block_cls = "Bottleneck" if is_bottleneck else "BasicBlock"
    idx = 0
    for stage, num_blocks in enumerate(stage_sizes):
        for j in range(num_blocks):
            prefix = f"layer{stage + 1}.{j}"
            bp: dict = {}
            bs: dict = {}
            for c in range(n_main):
                p, s = _convbn(sd, f"{prefix}.conv{c + 1}", f"{prefix}.bn{c + 1}")
                bp[f"ConvBN_{c}"] = p
                bs[f"ConvBN_{c}"] = s
            if f"{prefix}.downsample.0.weight" in sd:
                bp[f"ConvBN_{n_main}"] = {
                    "Conv_0": {"kernel": _conv(sd[f"{prefix}.downsample.0.weight"])},
                    "BatchNorm_0": {
                        "scale": _f32(sd[f"{prefix}.downsample.1.weight"]),
                        "bias": _f32(sd[f"{prefix}.downsample.1.bias"]),
                    },
                }
                bs[f"ConvBN_{n_main}"] = {
                    "BatchNorm_0": {
                        "mean": _f32(sd[f"{prefix}.downsample.1.running_mean"]),
                        "var": _f32(sd[f"{prefix}.downsample.1.running_var"]),
                    }
                }
            params[f"{block_cls}_{idx}"] = bp
            stats[f"{block_cls}_{idx}"] = bs
            idx += 1
    return params, stats


def timm_to_vit(sd: Dict[str, Any], num_heads: int, strict_pos_embed: bool = False) -> dict:
    """timm `vision_transformer` state dict → Flax ViT params
    (moco_tpu.models.vit) — inverse of `export.vit_to_timm` (round-trip
    tested). `pos_embed` is dropped: ours is fixed 2-D sin-cos computed
    in the module (the v3 paper's choice). A v3-style checkpoint trained
    with frozen sincos loses nothing; an ordinary supervised timm ViT
    carries a LEARNED pos_embed whose information would be silently
    discarded — so the incoming table is compared against the sincos
    grid and a drift beyond tolerance warns (or raises with
    `strict_pos_embed=True`)."""
    dim = int(np.asarray(sd["patch_embed.proj.weight"]).shape[0])
    if "pos_embed" in sd:
        pe = np.asarray(sd["pos_embed"], np.float32).reshape(-1, dim)
        n_tok = pe.shape[0]
        has_cls = "cls_token" in sd
        grid = int(round((n_tok - (1 if has_cls else 0)) ** 0.5))
        from moco_tpu.models.vit import sincos_2d_posembed

        expect = sincos_2d_posembed(dim, grid, cls_token=has_cls).reshape(-1, dim)
        if expect.shape != pe.shape or not np.allclose(expect, pe, atol=1e-3):
            msg = (
                "timm checkpoint carries a pos_embed that differs from the fixed "
                "2-D sin-cos table this ViT computes — a LEARNED positional "
                "embedding would be discarded on import (fine for v3-style "
                "frozen-sincos checkpoints, lossy for supervised timm ViTs)"
            )
            if strict_pos_embed:
                raise ValueError(msg)
            import warnings

            warnings.warn(msg)
    if dim % num_heads:
        raise ValueError(f"hidden dim {dim} not divisible by num_heads {num_heads}")
    hd = dim // num_heads
    params: dict = {
        "patch_embed": {
            "kernel": _conv(sd["patch_embed.proj.weight"]),  # (D,3,P,P)->(P,P,3,D)
            "bias": _f32(sd["patch_embed.proj.bias"]),
        },
        "final_norm": {
            "scale": _f32(sd["norm.weight"]),
            "bias": _f32(sd["norm.bias"]),
        },
    }
    if "cls_token" in sd:
        params["cls_token"] = _f32(sd["cls_token"])
    i = 0
    while f"blocks.{i}.norm1.weight" in sd:
        pre = f"blocks.{i}"
        qkv_w = np.asarray(sd[f"{pre}.attn.qkv.weight"], np.float32)  # (3D, D)
        qkv_b = np.asarray(sd[f"{pre}.attn.qkv.bias"], np.float32)
        attn = {}
        for j, name in enumerate(("query", "key", "value")):
            attn[name] = {
                "kernel": qkv_w[j * dim : (j + 1) * dim].T.reshape(dim, num_heads, hd),
                "bias": qkv_b[j * dim : (j + 1) * dim].reshape(num_heads, hd),
            }
        attn["out"] = {
            "kernel": _dense(sd[f"{pre}.attn.proj.weight"]).reshape(num_heads, hd, dim),
            "bias": _f32(sd[f"{pre}.attn.proj.bias"]),
        }
        params[f"block_{i}"] = {
            "LayerNorm_0": {
                "scale": _f32(sd[f"{pre}.norm1.weight"]),
                "bias": _f32(sd[f"{pre}.norm1.bias"]),
            },
            "MultiHeadDotProductAttention_0": attn,
            "LayerNorm_1": {
                "scale": _f32(sd[f"{pre}.norm2.weight"]),
                "bias": _f32(sd[f"{pre}.norm2.bias"]),
            },
            "MlpBlock_0": {
                "Dense_0": {
                    "kernel": _dense(sd[f"{pre}.mlp.fc1.weight"]),
                    "bias": _f32(sd[f"{pre}.mlp.fc1.bias"]),
                },
                "Dense_1": {
                    "kernel": _dense(sd[f"{pre}.mlp.fc2.weight"]),
                    "bias": _f32(sd[f"{pre}.mlp.fc2.bias"]),
                },
            },
        }
        i += 1
    if i == 0:
        raise KeyError("no blocks.* keys — not a timm ViT state dict")
    return params


def head_from_torch(sd: Dict[str, Any]) -> Tuple[dict, bool]:
    """Reference head keys → ProjectionHead params. v2 MLP surgery
    (`moco/builder.py:~L25-30`: `fc = Sequential(Linear, ReLU, Linear)`)
    exports `fc.0.*`/`fc.2.*`; v1 keeps a single `fc.*`. Returns
    (head_params, mlp)."""
    if "fc.0.weight" in sd:
        return {
            "Dense_0": {"kernel": _dense(sd["fc.0.weight"]), "bias": _f32(sd["fc.0.bias"])},
            "Dense_1": {"kernel": _dense(sd["fc.2.weight"]), "bias": _f32(sd["fc.2.bias"])},
        }, True
    if "fc.weight" in sd:
        return {
            "Dense_0": {"kernel": _dense(sd["fc.weight"]), "bias": _f32(sd["fc.bias"])},
        }, False
    raise KeyError("no fc head keys found in the reference state dict")


def _split_prefix(state_dict: Dict[str, Any], prefix: str) -> Dict[str, Any]:
    out = {}
    for k, v in state_dict.items():
        if k.startswith(prefix):
            out[k[len(prefix) :]] = v
    return out


def import_reference_state_dict(
    state_dict: Dict[str, Any], arch: str
) -> Dict[str, Any]:
    """Reference (DDP-prefixed) state dict → a dict of Flax-side pieces:
    {params_q, batch_stats_q, params_k, batch_stats_k, queue, queue_ptr,
    mlp, dim}. Tensors come in as anything np.asarray handles (torch
    tensors included, via .numpy() upstream)."""
    if arch not in STAGE_SIZES:
        raise ValueError(f"unsupported arch for import: {arch!r}")
    stage_sizes = STAGE_SIZES[arch]
    pieces: Dict[str, Any] = {}
    for enc, (pkey, skey) in {
        "module.encoder_q.": ("params_q", "batch_stats_q"),
        "module.encoder_k.": ("params_k", "batch_stats_k"),
    }.items():
        sub = _split_prefix(state_dict, enc)
        if not sub and enc == "module.encoder_q.":
            # tolerate non-DDP checkpoints (single-GPU runs save without
            # the `module.` wrapper)
            sub = _split_prefix(state_dict, "encoder_q.")
        if not sub and enc == "module.encoder_k.":
            sub = _split_prefix(state_dict, "encoder_k.")
        if not sub:
            continue
        backbone_p, backbone_s = torchvision_to_resnet(sub, stage_sizes)
        head_p, mlp = head_from_torch(sub)
        pieces[pkey] = {"backbone": backbone_p, "head": head_p}
        pieces[skey] = {"backbone": backbone_s}
        pieces["mlp"] = mlp
        pieces["dim"] = int(
            np.asarray(sub["fc.2.weight" if mlp else "fc.weight"]).shape[0]
        )
    if "params_q" not in pieces:
        raise KeyError(
            "state dict has no encoder_q keys — is this a MoCo pretrain checkpoint?"
        )
    for qk in ("module.queue", "queue"):
        if qk in state_dict:
            # reference layout: (dim, K) L2-normalized columns -> (K, dim) rows
            pieces["queue"] = _f32(state_dict[qk]).T
            break
    for pk in ("module.queue_ptr", "queue_ptr"):
        if pk in state_dict:
            pieces["queue_ptr"] = int(np.asarray(state_dict[pk]).reshape(-1)[0])
            break
    return pieces
