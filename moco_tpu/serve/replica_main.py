"""One serving replica as a process: the unit the fleet supervisor
spawns, kills, and resurrects.

    python -m moco_tpu.serve.replica_main --ckpt-dir /run/workdir \
        --port 8001 --replica-index 1 [--workdir /fleet/replica1] \
        [--buckets 1,8,32] [--slo-ms 1000] [--neighbors-mode exact]

Loads the checkpoint's key encoder (`load_serving_encoder`), wraps the
checkpoint queue as the serving index, and boots a `ServeServer` on the
given port — which binds ONLY after AOT warmup, so the supervisor's
healthz wait doubles as a warmup barrier (connection refused = still
compiling, never a cold replica in rotation).

Faults install from `MOCO_FAULTS` (the supervisor plants per-replica
specs for the chaos smoke; `kill@replica=i` dies here mid-request).

SIGTERM/SIGINT is the graceful-drain path (the supervisor's
`restart_replica` and fleet shutdown both use it): stop intake, FLUSH
every accepted request (`ServeServer.drain` → the batcher's drain),
then tear down and exit 0 — a drained replica never fails a request it
already accepted.
"""

from __future__ import annotations

import argparse
import signal
import threading


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description="one serving-fleet replica process")
    ap.add_argument("--ckpt-dir", required=True, help="pretraining checkpoint workdir")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--replica-index", type=int, default=0)
    ap.add_argument("--workdir", default=None, help="metrics/trace output dir")
    ap.add_argument("--buckets", default="1,8,32", help="comma-separated AOT buckets")
    ap.add_argument("--slo-ms", type=float, default=1000.0)
    ap.add_argument("--neighbors-mode", default="exact")
    ap.add_argument("--neighbors-k", type=int, default=5)
    ap.add_argument("--metrics-flush-s", type=float, default=1.0)
    ap.add_argument("--drain-timeout-s", type=float, default=30.0)
    ap.add_argument(
        "--fresh-max-age-s", type=float, default=0.0,
        help="freshness SLO: max index-row age in wall seconds "
        "(0 = no freshness objective declared)",
    )
    return ap


def main(argv=None) -> int:
    from moco_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()
    args = build_argparser().parse_args(argv)

    import os

    from moco_tpu.analysis import contracts as contract_cov
    from moco_tpu.obs import quality
    from moco_tpu.obs.sinks import JsonlSink
    from moco_tpu.serve.engine import InferenceEngine, load_serving_encoder
    from moco_tpu.serve.index import EmbeddingIndex
    from moco_tpu.serve.server import ServeServer
    from moco_tpu.utils import faults
    from moco_tpu.utils.checkpoint import CheckpointManager

    faults.install_from_env()
    # contract-coverage arm: MOCO_CONTRACT_COVERAGE=1 (planted by a
    # smoke script before the supervisor spawns us) installs a recorder;
    # the snapshot dumps on graceful exit below. A killed replica never
    # dumps — its respawn covers the same contracts.
    recorder = contract_cov.maybe_install_from_env()
    buckets = tuple(int(b) for b in args.buckets.split(","))
    module, params, stats, queue, queue_ptr, config = load_serving_encoder(
        args.ckpt_dir
    )
    engine = InferenceEngine(
        module, params, stats,
        image_size=config.data.image_size, buckets=buckets,
    )
    index = EmbeddingIndex.from_train_queue(queue, queue_ptr)
    # served-model identity: which checkpoint step this encoder came
    # from + a content digest of its params — /stats and /admin/model
    # expose both, so the router's version-skew gauge has real data
    mgr = CheckpointManager(args.ckpt_dir)
    model_step = mgr.latest_step()
    mgr.close()
    model_digest = quality.params_digest(params)
    sink = None
    if args.workdir:
        os.makedirs(args.workdir, exist_ok=True)
        sink = JsonlSink(args.workdir)
    server = ServeServer(
        engine,
        index=index,
        host=args.host,
        port=args.port,
        slo_ms=args.slo_ms,
        neighbors_k=args.neighbors_k,
        neighbors_mode=args.neighbors_mode,
        sink=sink,
        metrics_flush_s=args.metrics_flush_s,
        workdir=args.workdir,
        replica_index=args.replica_index,
        model_step=model_step,
        model_digest=model_digest,
        fresh_max_age_s=args.fresh_max_age_s or None,
    )
    print(
        f"replica {args.replica_index} serving on "
        f"http://{args.host}:{server.port} (buckets={buckets})",
        flush=True,
    )

    stop = threading.Event()

    def _graceful(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    while not stop.wait(0.25):
        pass
    # graceful drain: intake shuts, every accepted request flushes —
    # then the ordinary close (final metrics flush included)
    drained = server.drain(timeout=args.drain_timeout_s)
    server.close()
    if sink is not None:
        sink.close()
    if recorder is not None and args.workdir:
        recorder.dump(os.path.join(args.workdir, "contract_coverage.json"))
    print(
        f"replica {args.replica_index} drained "
        f"({'clean' if drained else 'timed out'}) and exited",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
