"""Activation-quantized int8 inference: calibration + the w8a8 forward.

PR 9's engine PTQ is *weight-only* (w8): the int8 kernels dequantize to
f32 in-graph and every matmul/conv still runs f32×f32 — the at-rest
memory saving is real, the arithmetic saving is not. Going after the
full factor needs the activations on the int8 grid too (w8a8), and that
needs *calibration*: activation ranges are data-dependent, so a held-out
sample runs through the f32 encoder once, an observer records the
per-tensor |x|max at every quantized-op input, and symmetric per-tensor
scales are fitted from those ranges (`s = amax / 127` — the standard
symmetric PTQ recipe; per-tensor on activations, per-output-channel on
weights, as in `engine.quantize_params_int8`).

The seam is flax's method interceptor (`nn.intercept_methods`), the
same place for both passes:

- **observe** (:class:`ActivationObserver`): the f32 forward runs
  eagerly with an interceptor that records `amax[path] = max|input|`
  for every `nn.Conv` / `nn.Dense` call, keyed by the module's scope
  path. Deterministic: same sample → bitwise-identical ranges (the
  calibration-determinism test pins this).
- **quantize** (:func:`quantized_apply`): the serving forward replaces
  each Conv/Dense with its int8 twin — input quantized to the int8 grid
  with the calibrated per-tensor scale, the (already int8) kernel
  consumed directly, accumulation in int32, one f32 rescale
  (`a_scale · w_scale`) at the layer boundary. Everything between
  layers (BN, ReLU, residual adds, pooling, L2-normalize) stays f32,
  so error cannot compound through normalization statistics.

Backend reality (the bf16 precedent, measured the same way): XLA:CPU
has no int8 conv/GEMM kernels — an int8×int8→int32 conv falls to a
generic path ~45x slower than f32, exactly like its ~50x bf16
emulation that already forces the CPU engine to serve f32. So
`int8_compute` is capability-gated: tpu/gpu run true int8×int8→int32
(`preferred_element_type=jnp.int32`); CPU runs *scaled-integer
emulation* — the operands are the exact same int8-grid values held in
f32, so products and sums are exact integers (f32 is exact through
2^24) and the NUMERICS of w8a8 (embedding cosine, downstream recall)
are faithfully testable on the CPU smoke even though the arithmetic
speedup only exists on a chip. The w8a8-vs-w8 queries/s claim is
therefore an accelerator claim; the CPU smoke gates the cosine floor
(`perf_ledger.py` QUANT_COSINE_FLOOR) and records `int8_kernels` so a
ledger entry says which arithmetic actually ran.

Calibration persists as a small JSON artifact next to the checkpoint
(`quant_calib.json`: version, image size, sample size, per-path amax)
so a serving replica can boot w8a8 without re-running the sample —
`save_calibration` / `load_calibration` roundtrip bitwise (floats via
repr) and the engine validates the artifact against the module (every
quantized layer must have a range).
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Iterable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from flax.traverse_util import flatten_dict

CALIBRATION_VERSION = 1
CALIBRATION_FILENAME = "quant_calib.json"
# module types the quantized forward replaces; anything else runs f32
QUANT_LAYER_TYPES = (nn.Conv, nn.Dense)
# engine quantization tiers (serve/engine.py's engine_quant knob)
QUANT_MODES = ("off", "w8", "w8a8")


def _layer_path(module) -> str:
    """Stable string key for a bound module's scope path — matches the
    params-tree nesting (flax auto-names: ``backbone/ConvBN_0/Conv_0``)."""
    return "/".join(module.path)


def _is_plain(module) -> bool:
    """Only plain convs/dense quantize; anything exotic (input dilation,
    grouped features) passes through f32 rather than risking a silent
    semantics mismatch in the re-implemented int8 op."""
    if isinstance(module, nn.Dense):
        return True
    if getattr(module, "feature_group_count", 1) != 1:
        return False
    in_dil = getattr(module, "input_dilation", None)
    if in_dil not in (None, 1) and set(np.atleast_1d(in_dil).tolist()) != {1}:
        return False
    return True


class ActivationObserver:
    """Records per-tensor activation ranges (`amax[path] = max|input|`)
    for every plain Conv/Dense call while :meth:`intercept` is active.
    Ranges accumulate across calls (running max over calibration
    batches), so one observer can digest a whole held-out sample."""

    def __init__(self):
        self.amax: dict[str, float] = {}

    def _interceptor(self, next_fun, args, kwargs, context):
        mod = context.module
        if (
            context.method_name == "__call__"
            and isinstance(mod, QUANT_LAYER_TYPES)
            and _is_plain(mod)
            and args
        ):
            path = _layer_path(mod)
            v = float(jnp.max(jnp.abs(args[0])))
            self.amax[path] = max(self.amax.get(path, 0.0), v)
        return next_fun(*args, **kwargs)

    @contextlib.contextmanager
    def intercept(self):
        with nn.intercept_methods(self._interceptor):
            yield self


def fit_scales(amax: dict[str, float]) -> dict[str, float]:
    """Symmetric per-tensor activation scales from observed ranges:
    `s = amax / 127`, with a scale of 1 for a never-activated tensor
    (avoids a 0-divide; its quantized values are all zero anyway)."""
    return {
        path: (v / 127.0 if v > 0.0 else 1.0) for path, v in sorted(amax.items())
    }


def calibrate_encoder(
    module,
    params,
    batch_stats,
    images: np.ndarray,
    image_size: int,
    batch_size: int = 32,
) -> dict:
    """One calibration pass at the engine's preprocessing seam: the
    held-out uint8 `images` run through /255 → per-channel normalize →
    the f32 encoder (eagerly — calibration is offline, determinism
    beats speed) under the observer. Returns the JSON-ready artifact."""
    from moco_tpu.data.augment import get_recipe, normalize

    images = np.asarray(images, np.uint8)
    if images.ndim != 4 or images.shape[1:] != (image_size, image_size, 3):
        raise ValueError(
            f"calibration sample must be (n, {image_size}, {image_size}, 3) "
            f"uint8, got {images.shape}"
        )
    recipe = get_recipe(False, int(image_size))
    variables = {"params": params, "batch_stats": batch_stats}
    obs = ActivationObserver()
    with obs.intercept():
        for lo in range(0, images.shape[0], int(batch_size)):
            x = jnp.asarray(images[lo : lo + int(batch_size)], jnp.float32) / 255.0
            x = normalize(x, recipe.mean, recipe.std)
            module.apply(variables, x, train=False)
    if not obs.amax:
        raise ValueError("calibration saw no quantizable Conv/Dense layer")
    return {
        "version": CALIBRATION_VERSION,
        "image_size": int(image_size),
        "sample_n": int(images.shape[0]),
        "num_layers": len(obs.amax),
        "amax": {k: obs.amax[k] for k in sorted(obs.amax)},
    }


def calibration_path(ckpt_dir: str) -> str:
    """Where the artifact lives relative to a checkpoint directory."""
    return os.path.join(ckpt_dir, CALIBRATION_FILENAME)


def save_calibration(path: str, calib: dict) -> str:
    """Atomic JSON write (floats via repr-roundtripping json, so
    load(save(x)) == x bitwise). Accepts a checkpoint DIR or a file."""
    if os.path.isdir(path):
        path = calibration_path(path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(calib, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_calibration(path: str) -> dict:
    if os.path.isdir(path):
        path = calibration_path(path)
    with open(path) as f:
        calib = json.load(f)
    if calib.get("version") != CALIBRATION_VERSION or "amax" not in calib:
        raise ValueError(f"{path} is not a v{CALIBRATION_VERSION} calibration artifact")
    return calib


def default_int8_compute() -> bool:
    """True int8×int8→int32 kernels only where the backend has them —
    the same tpu/gpu gate as engine donation and the bf16 serve dtype
    (XLA:CPU measured ~45x slower on an int8 conv; module docstring)."""
    return jax.default_backend() in ("tpu", "gpu")


def _conv_geometry(mod, ndim: int):
    """nn.Conv attribute normalization → lax.conv_general_dilated args
    (spatial rank = ndim - 2; flax accepts ints where lax wants tuples)."""

    def _tup(v, default=1):
        if v is None:
            v = default
        if isinstance(v, int):
            return (v,) * (ndim - 2)
        return tuple(v)

    return _tup(mod.strides), _tup(mod.kernel_dilation), mod.padding


def quantized_apply(
    module,
    qparams,
    qscales,
    batch_stats,
    act_scales: dict[str, jax.Array],
    x: jax.Array,
    int8_compute: bool,
    train: bool = False,
):
    """The w8a8 forward: `module.apply` with every calibrated plain
    Conv/Dense replaced by its int8 twin (module docstring). All of
    `qparams`/`qscales`/`act_scales` are expected to be call ARGUMENTS
    of the enclosing jit — a closure constant would let XLA fold
    `int8 · scale` back into f32 constants and silently undo the 4x
    at-rest saving (the PR-9 lesson, engine.quantize_params_int8)."""
    # per-path per-output-channel weight scales from the scale tree —
    # structure is static under trace, so this flatten costs nothing
    flat_q = flatten_dict(qparams)
    flat_s = flatten_dict(qscales)
    w_scales = {
        "/".join(kpath[:-1]): flat_s[kpath].reshape(-1)
        for kpath, leaf in flat_q.items()
        if kpath[-1] == "kernel" and getattr(leaf, "dtype", None) == jnp.int8
    }

    def interceptor(next_fun, args, kwargs, context):
        mod = context.module
        if (
            context.method_name != "__call__"
            or not isinstance(mod, QUANT_LAYER_TYPES)
            or not _is_plain(mod)
        ):
            return next_fun(*args, **kwargs)
        path = _layer_path(mod)
        a_s = act_scales.get(path)
        w_s = w_scales.get(path)
        if a_s is None or w_s is None:
            # uncalibrated or unquantized layer: f32 pass-through (the
            # engine validates coverage up front, so this is the
            # deliberate escape hatch, not a silent hole)
            return next_fun(*args, **kwargs)
        xin = args[0]
        qx = jnp.clip(jnp.round(xin.astype(jnp.float32) / a_s), -127.0, 127.0)
        kern = mod.variables["params"]["kernel"]  # int8: applied tree is quantized
        if int8_compute:
            qx = qx.astype(jnp.int8)
            pet = {"preferred_element_type": jnp.int32}
        else:
            # scaled-integer emulation: identical int values in f32
            # (exact through 2^24), XLA:CPU keeps its fast f32 kernels
            kern = kern.astype(jnp.float32)
            pet = {}
        if isinstance(mod, nn.Dense):
            acc = jax.lax.dot_general(
                qx, kern, (((qx.ndim - 1,), (0,)), ((), ())), **pet
            )
        else:
            strides, kernel_dilation, padding = _conv_geometry(mod, qx.ndim)
            dn = jax.lax.conv_dimension_numbers(
                qx.shape, kern.shape, ("NHWC", "HWIO", "NHWC")
            )
            acc = jax.lax.conv_general_dilated(
                qx,
                kern,
                strides,
                padding,
                rhs_dilation=kernel_dilation,
                dimension_numbers=dn,
                **pet,
            )
        scale = a_s * w_s
        out = acc.astype(jnp.float32) * scale.reshape((1,) * (acc.ndim - 1) + (-1,))
        if mod.use_bias:
            out = out + mod.variables["params"]["bias"].astype(jnp.float32)
        return out

    with nn.intercept_methods(interceptor):
        return module.apply(
            {"params": qparams, "batch_stats": batch_stats}, x, train=train
        )


def quantized_layer_paths(params) -> set[str]:
    """Paths `quantize_params_int8` will quantize (ndim >= 2 floating
    kernels) — what a calibration artifact must cover for w8a8."""
    out = set()
    for kpath, leaf in flatten_dict(params).items():
        if (
            kpath[-1] == "kernel"
            and getattr(leaf, "ndim", 0) >= 2
            and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
        ):
            out.add("/".join(kpath[:-1]))
    return out


def validate_calibration(calib: dict, params, image_size: int) -> None:
    """Fail loudly at engine build, not silently at serve time: the
    artifact must match the serving geometry and cover every quantized
    layer (an uncovered layer would fall back to f32 — a silent tier
    downgrade)."""
    if int(calib.get("image_size", -1)) != int(image_size):
        raise ValueError(
            f"calibration was captured at image_size="
            f"{calib.get('image_size')}, engine serves {image_size}"
        )
    missing = quantized_layer_paths(params) - set(calib["amax"])
    if missing:
        raise ValueError(
            f"calibration covers {len(calib['amax'])} layers but the encoder "
            f"has {len(missing)} uncovered quantized layers: {sorted(missing)[:4]}"
        )


def activation_scales(calib: dict) -> dict[str, jax.Array]:
    """The calibration artifact as the traced-scale pytree the w8a8
    executable takes as an argument (sorted keys → stable treedef)."""
    return {
        path: jnp.float32(s) for path, s in fit_scales(calib["amax"]).items()
    }


__all__ = [
    "ActivationObserver",
    "CALIBRATION_FILENAME",
    "CALIBRATION_VERSION",
    "QUANT_LAYER_TYPES",
    "QUANT_MODES",
    "activation_scales",
    "calibrate_encoder",
    "calibration_path",
    "default_int8_compute",
    "fit_scales",
    "load_calibration",
    "quantized_apply",
    "quantized_layer_paths",
    "save_calibration",
    "validate_calibration",
]
