"""Continuous batching under a latency SLO.

The device is efficient on the engine's padded buckets; users arrive
one-at-a-time. The batcher is the adapter: requests enqueue from any
number of client threads, a single batcher thread coalesces them into
micro-batches — flushing when the pending rows reach `max_batch` (the
engine's largest bucket) **or** when the oldest pending request has
waited `slo_ms / 2` (half the budget queued, half for compute; the
classic continuous-batching deadline split) — runs the engine call on
its own thread (the wire/compute never touches a client thread, the
same discipline as the device prefetch ring), and scatters result rows
back to each request's future.

Thread hygiene is the JX011 contract (`data/pipeline.py` /
`device_prefetch.py` lineage): the submit queue is bounded, every
blocking put polls a stop flag (`_responsive_put`), `close()` drains
the queue, fails all pending futures with :class:`BatcherClosedError`
(so put-blocked producers and result-blocked clients both unblock), and
joins the batcher thread.

Shutdown comes in two flavors. `close()` is the abort path: anything
still pending fails fast with BatcherClosedError. `drain()` is the
graceful one — intake shuts (new submits raise), but every rider
already accepted is FLUSHED, not failed, and only then does the thread
stop. The server's SIGTERM path and `/admin/drain` ride drain(), which
is what makes a fleet-router drain/restart a zero-dropped-requests
operation rather than a burst of 503s.

Metrics (`ServeMetrics`): per-request latency reservoir → p50/p99,
completed-request QPS, batch occupancy (valid rows / padded bucket
rows — the padding tax), a per-bucket execution histogram, per-tier
request counts (`serve/mode_<tier>` — explicit `?mode=` riders under
their tier, the rest under "default"), SLO violation counts, a
cumulative latency histogram with the p99 exemplar request id,
per-stage request-trace means, and (when a `SLOBurnTracker` is
attached) the multi-window burn-rate family.
`payload()` emits the `serve/*` metric family the obs schema validates
and the Prometheus sink exposes as gauges + a real
`_bucket{le=...}` histogram.

Request tracing (obs/reqtrace.py): a future may carry a
`RequestTrace`; the batcher thread stamps `queue_wait` (per request)
and the shared flush stages (`batch_assemble` / `engine_execute` /
`index_query` / `scatter`) onto it — perf_counter pairs only, the
expensive rendering happens off-path. With `reqtrace=True` the batcher
allocates traces itself for trace-less submits (the bench serving leg's
A/B); with tracing off the per-request cost is a `None` check.
"""

from __future__ import annotations

import inspect
import queue
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Callable, Optional

import numpy as np

from moco_tpu.analysis import tsan
from moco_tpu.obs.reqtrace import RequestIdAllocator, RequestTrace
from moco_tpu.utils import faults

# Cumulative latency bucket bounds (ms) for the exported histogram —
# wide enough to cover a TPU replica at a tight SLO and the CPU smoke's
# multi-second tail in the same ladder.
DEFAULT_LATENCY_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
)


class BatcherClosedError(RuntimeError):
    """The batcher shut down before (or while) handling this request."""


def _responsive_put(q: queue.Queue, stop: threading.Event, item) -> bool:
    """Bounded put that stays responsive to a stop flag; False = stopped
    (the JX011-idiomatic put — see data/device_prefetch.py)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


class ServeFuture:
    """Single-assignment result handle: `result(timeout)` blocks until
    the batcher scatters this request's rows back (or fails it)."""

    def __init__(
        self,
        num_rows: int,
        submitted_at: float,
        want_neighbors: bool,
        mode: Optional[str] = None,
        trace: Optional[RequestTrace] = None,
    ):
        self.num_rows = num_rows
        self.submitted_at = submitted_at
        self.want_neighbors = want_neighbors
        self.mode = mode  # neighbor tier this rider asked for (None = default)
        self.trace = trace  # request-scoped waterfall (None = tracing off)
        self._done = threading.Event()
        self._value: Optional[dict] = None
        self._error: Optional[BaseException] = None
        self.latency_s: Optional[float] = None

    def _resolve(self, value: dict) -> None:
        self.latency_s = time.perf_counter() - self.submitted_at
        self._value = value
        self._done.set()

    def _fail(self, error: BaseException) -> None:
        self.latency_s = time.perf_counter() - self.submitted_at
        self._error = error
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> dict:
        if not self._done.wait(timeout):
            raise TimeoutError("serve request still pending")
        if self._error is not None:
            raise self._error
        return self._value


class ServeMetrics:
    """Thread-safe serving gauges; `payload()` is the schema'd
    `serve/*` line (README "metrics.jsonl line format")."""

    def __init__(
        self,
        slo_ms: float,
        window: int = 2048,
        burn=None,
        latency_buckets_ms=DEFAULT_LATENCY_BUCKETS_MS,
    ):
        self.slo_ms = float(slo_ms)
        # tsan factory: the serving gauges' lock is the INNER lock of the
        # sanctioned serve.index -> serve.metrics nesting (server.stats);
        # --sanitize-threads smoke runs watch its acquisition order
        self._lock = tsan.make_lock("serve.metrics")
        self._latencies_ms: deque = deque(maxlen=window)
        self._recalls: deque = deque(maxlen=window)
        self._bucket_counts: dict[int, int] = {}
        self._valid_rows = 0
        self._padded_rows = 0
        self._completed = 0
        self._violations = 0
        self._started_at = time.perf_counter()
        self._win_t0 = self._started_at
        self._win_completed = 0
        # multi-window SLO burn-rate tracker (obs/slo.py); fed one
        # ok/violation observation per completed request
        self.burn = burn
        # cumulative latency histogram (lifetime counters, Prometheus
        # semantics) + the window's worst request as the p99 exemplar
        self._hist_le = tuple(float(b) for b in latency_buckets_ms)
        self._hist_counts = [0] * (len(self._hist_le) + 1)
        self._hist_sum_ms = 0.0
        self._hist_count = 0
        self._exemplar: Optional[tuple[float, str]] = None  # (ms, request_id)
        # per-tier request counts (serve/mode_<tier>): which retrieval
        # mode answered the traffic — explicit ?mode= riders under their
        # tier name, everything else under "default" (the server's
        # neighbors_mode). The tier A/B and the fleet router both read
        # this to see where load actually lands.
        self._mode_counts: dict[str, int] = {}
        # per-stage request-trace sums over the current payload window
        self._stage_sums_ms: dict[str, float] = {}
        self._stage_reqs = 0

    def record_recall(self, recall: float) -> None:
        """One sampled online recall@k observation (approximate tier vs
        the exact oracle, same queries) — `serve/recall_estimate` is the
        window mean, the gauge the smoke's recall floor gates."""
        with self._lock:
            self._recalls.append(float(recall))

    def record_request(
        self,
        latency_s: float,
        request_id: Optional[str] = None,
        trace: Optional[RequestTrace] = None,
        mode: Optional[str] = None,
    ) -> None:
        ms = latency_s * 1e3
        with self._lock:
            key = mode or "default"
            self._mode_counts[key] = self._mode_counts.get(key, 0) + 1
            self._latencies_ms.append(ms)
            self._completed += 1
            self._win_completed += 1
            if ms > self.slo_ms:
                self._violations += 1
            self._hist_counts[bisect_left(self._hist_le, ms)] += 1
            self._hist_sum_ms += ms
            self._hist_count += 1
            if request_id is not None and (
                self._exemplar is None or ms > self._exemplar[0]
            ):
                self._exemplar = (ms, request_id)
            if trace is not None:
                for stage, dur_ms in trace.stage_ms().items():
                    self._stage_sums_ms[stage] = (
                        self._stage_sums_ms.get(stage, 0.0) + dur_ms
                    )
                self._stage_reqs += 1
        if self.burn is not None:
            self.burn.record(ms <= self.slo_ms)

    def record_flush(self, executed: list[tuple[int, int]]) -> None:
        with self._lock:
            for bucket, valid in executed:
                self._bucket_counts[bucket] = self._bucket_counts.get(bucket, 0) + 1
                self._padded_rows += bucket
                self._valid_rows += valid

    def payload(self) -> dict:
        """`serve/*` fields; qps is computed over the window since the
        previous payload() call (the sink-flush cadence), falling back
        to the lifetime rate on the first call."""
        with self._lock:
            now = time.perf_counter()
            dt = max(now - self._win_t0, 1e-9)
            qps = self._win_completed / dt
            self._win_t0, self._win_completed = now, 0
            lat = sorted(self._latencies_ms)
            pct = lambda p: (
                lat[min(int(p * (len(lat) - 1) + 0.5), len(lat) - 1)] if lat else None
            )
            out = {
                "serve/p50_ms": pct(0.50),
                "serve/p99_ms": pct(0.99),
                "serve/qps": qps,
                "serve/occupancy": (
                    self._valid_rows / self._padded_rows if self._padded_rows else None
                ),
                "serve/requests": self._completed,
                "serve/slo_violations": self._violations,
                "serve/slo_ms": self.slo_ms,
                # sampled-online recall of the approximate tier vs the
                # exact oracle; null until the first sample (or with the
                # estimator off / exact-only serving)
                "serve/recall_estimate": (
                    sum(self._recalls) / len(self._recalls) if self._recalls else None
                ),
                # cumulative latency histogram (lifetime, per-bucket
                # counts — the Prometheus sink cumulates at render) with
                # the window's worst request attached as the exemplar
                "serve/latency_hist": {
                    "le": list(self._hist_le),
                    "counts": list(self._hist_counts),
                    "sum": round(self._hist_sum_ms, 3),
                    "count": self._hist_count,
                    **(
                        {
                            "exemplar": {
                                "request_id": self._exemplar[1],
                                "latency_ms": round(self._exemplar[0], 3),
                            }
                        }
                        if self._exemplar is not None
                        else {}
                    ),
                },
                # the p99 exemplar: WHICH request the latency gauges
                # blame (the window's worst; null with tracing off)
                "serve/p99_exemplar": (
                    self._exemplar[1] if self._exemplar is not None else None
                ),
                "serve/p99_exemplar_ms": (
                    round(self._exemplar[0], 3) if self._exemplar is not None else None
                ),
            }
            # stage waterfall means over the window (request tracing on)
            if self._stage_reqs:
                for stage, total in sorted(self._stage_sums_ms.items()):
                    out[f"serve/trace_{stage}_ms"] = round(
                        total / self._stage_reqs, 3
                    )
                out["serve/trace_requests"] = self._stage_reqs
            self._exemplar = None
            self._stage_sums_ms = {}
            self._stage_reqs = 0
            for bucket, count in sorted(self._bucket_counts.items()):
                out[f"serve/bucket_{bucket}"] = count
            # cumulative per-tier counts, like the bucket histogram
            for m, count in sorted(self._mode_counts.items()):
                out[f"serve/mode_{m}"] = count
        if self.burn is not None:
            out.update(self.burn.payload())
        return out


class ContinuousBatcher:
    """Micro-batch coalescing front end over an engine-shaped callable
    (module docstring).

    `run_batch(images, want_neighbors) -> (dict of row-arrays, executed)`
    — the server wires this to `engine.embed` / `engine.embed_and_query`;
    every returned array's rows align with the input rows so the scatter
    is a pure slice. `max_batch` defaults to the engine's largest bucket.
    """

    def __init__(
        self,
        run_batch: Callable,
        max_batch: int,
        slo_ms: float = 100.0,
        queue_depth: int = 256,
        metrics: Optional[ServeMetrics] = None,
        reqtrace: bool = False,
        replica_index: int = 0,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._run_batch = run_batch
        # a run_batch with >= 3 POSITIONAL params additionally receives
        # the sorted tuple of per-request neighbor modes in the
        # micro-batch (the IVF server path); 2-arg callables keep the
        # original contract. A keyword-only `stages` param opts into
        # per-stage timing (the engine splits engine_execute /
        # index_query there) — keyword-only so a stages-aware 2-arg
        # callable is not mistaken for the modes contract.
        try:
            params = inspect.signature(run_batch).parameters
            positional = [
                p for p in params.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            ]
            self._pass_modes = len(positional) >= 3
            self._pass_stages = "stages" in params
        except (TypeError, ValueError):
            self._pass_modes = False
            self._pass_stages = False
        # reqtrace=True: allocate a RequestTrace for trace-less submits
        # (standalone batcher use — bench A/B, tests); the server passes
        # traces explicitly so the ingress stage is already stamped
        self._ids = RequestIdAllocator(replica_index) if reqtrace else None
        self.max_batch = int(max_batch)
        self.slo_ms = float(slo_ms)
        # half the SLO budget may be spent coalescing; the rest belongs
        # to the compute + scatter
        self.deadline_s = self.slo_ms / 2e3
        self.metrics = metrics or ServeMetrics(slo_ms)
        self._q: queue.Queue = queue.Queue(maxsize=queue_depth)
        self._stop = threading.Event()
        # graceful-drain pair: _draining gates intake (submit raises,
        # accepted work still flushes), _drained flips when the loop has
        # flushed everything and exited — drain() waits on it
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="serve_batcher", daemon=True
        )
        self._thread.start()

    # -- client side -----------------------------------------------------

    def submit(
        self,
        images: np.ndarray,
        want_neighbors: bool = False,
        mode: Optional[str] = None,
        trace: Optional[RequestTrace] = None,
    ) -> ServeFuture:
        """Enqueue an (n, H, W, C) uint8 request; returns its future.
        `mode` names the neighbor tier this rider wants (exact/ivf/...;
        None = the server default); `trace` is an optional ingress
        -stamped RequestTrace (auto-allocated under reqtrace=True).
        Raises BatcherClosedError when the batcher is shut (including a
        producer that was blocked on a full queue during close)."""
        images = np.asarray(images, np.uint8)
        if images.ndim != 4 or images.shape[0] < 1:
            raise ValueError(f"request must be (n>=1, H, W, C) uint8, got {images.shape}")
        if trace is None and self._ids is not None:
            trace = self._ids.new_trace(images.shape[0])
        fut = ServeFuture(
            images.shape[0], time.perf_counter(), want_neighbors, mode, trace
        )
        if self._draining.is_set():
            raise BatcherClosedError("batcher is draining")
        if self._stop.is_set() or not _responsive_put(self._q, self._stop, (images, fut)):
            raise BatcherClosedError("batcher is closed")
        return fut

    # -- batcher thread --------------------------------------------------

    def _flush(self, pending: list) -> None:
        if not pending:
            return
        # queue_wait closes for every rider the moment its flush begins;
        # the remaining stages are flush-shared (reqtrace.py semantics)
        t_flush = time.perf_counter()
        tracing = any(f.trace is not None for _, f in pending)
        if tracing:
            for _, fut in pending:
                if fut.trace is not None:
                    fut.trace.stamp("queue_wait", fut.submitted_at, t_flush)
        faults.maybe_slow("serve.batch_assemble")
        images = np.concatenate([img for img, _ in pending])
        t_assembled = time.perf_counter()
        want_neighbors = any(f.want_neighbors for _, f in pending)
        stages: Optional[dict] = {} if (tracing and self._pass_stages) else None
        try:
            t_run0 = time.perf_counter()
            if self._pass_modes:
                modes = tuple(sorted(
                    {f.mode for _, f in pending if f.want_neighbors and f.mode}
                ))
                if stages is not None:
                    results, executed = self._run_batch(
                        images, want_neighbors, modes, stages=stages
                    )
                else:
                    results, executed = self._run_batch(images, want_neighbors, modes)
            elif stages is not None:
                results, executed = self._run_batch(
                    images, want_neighbors, stages=stages
                )
            else:
                results, executed = self._run_batch(images, want_neighbors)
            t_run1 = time.perf_counter()
        except BaseException as e:
            for _, fut in pending:
                fut._fail(e)
            return
        self.metrics.record_flush(executed)
        if tracing:
            # synthesize contiguous engine/query intervals from the run
            # window: durations are exact, starts are stacked (the real
            # device work interleaves per chunk — reqtrace.py docstring)
            if stages:
                engine_s = stages.get("engine_execute", 0.0)
                query_s = stages.get("index_query", 0.0)
                untimed = max((t_run1 - t_run0) - engine_s - query_s, 0.0)
                engine_s += untimed  # residual host work rides the engine stage
            else:
                engine_s, query_s = t_run1 - t_run0, 0.0
        faults.maybe_slow("serve.scatter")
        t_scatter = time.perf_counter()
        offset = 0
        for _, fut in pending:
            rows = slice(offset, offset + fut.num_rows)
            if fut.trace is not None:
                tr = fut.trace
                tr.stamp("batch_assemble", t_flush, t_assembled)
                tr.stamp("engine_execute", t_run0, t_run0 + engine_s)
                if query_s > 0.0:
                    tr.stamp(
                        "index_query", t_run0 + engine_s, t_run0 + engine_s + query_s
                    )
                # scatter closes at THIS request's resolve, so the
                # per-request stage sum tracks its measured latency
                tr.stamp("scatter", t_scatter, time.perf_counter())
            fut._resolve({k: v[rows] for k, v in results.items()})
            offset += fut.num_rows
            self.metrics.record_request(
                fut.latency_s,
                request_id=fut.trace.req_id if fut.trace is not None else None,
                trace=fut.trace,
                mode=fut.mode,
            )

    def _loop(self) -> None:
        pending: list = []
        rows = 0
        while not self._stop.is_set():
            draining = self._draining.is_set()
            if pending:
                timeout = self.deadline_s - (
                    time.perf_counter() - pending[0][1].submitted_at
                )
                # draining with an empty queue: intake is shut, nobody
                # else is coming — flush now instead of idling out the
                # coalescing deadline on riders already in hand
                if timeout <= 0 or rows >= self.max_batch or (
                    draining and self._q.empty()
                ):
                    self._flush(pending)
                    pending, rows = [], 0
                    continue
            elif draining and self._q.empty():
                break  # graceful exit: everything accepted was flushed
            else:
                timeout = 0.05  # idle poll so close() never waits long
            try:
                # the get poll is capped so a drain()/close() raised while
                # the thread is blocked here is noticed within 50ms, not
                # after the full coalescing deadline
                item = self._q.get(timeout=min(timeout, 0.05))
            except queue.Empty:
                continue
            images, fut = item
            pending.append((images, fut))
            rows += fut.num_rows
            if rows >= self.max_batch:
                self._flush(pending)
                pending, rows = [], 0
        # drain-on-stop: everything still queued or pending fails fast
        # so no client blocks on a future that will never resolve
        for _, fut in pending:
            fut._fail(BatcherClosedError("batcher closed with request pending"))
        while True:
            try:
                _, fut = self._q.get_nowait()
            except queue.Empty:
                break
            fut._fail(BatcherClosedError("batcher closed with request queued"))
        self._drained.set()

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown: stop intake (new submits raise
        BatcherClosedError), flush every already-accepted rider, then
        close. Returns True when the flush finished inside `timeout`
        (False = close() fell back to failing the stragglers). Safe
        from any thread, idempotent, and close()-compatible."""
        self._draining.set()
        drained = self._drained.wait(timeout)
        self.close()
        return drained

    def close(self, timeout: float = 10.0) -> None:
        """Stop coalescing, fail all pending/queued futures, join the
        thread. Safe from any thread, idempotent; put-blocked producers
        unblock via their responsive-put stop poll."""
        self._stop.set()
        self._thread.join(timeout=timeout)
        # a producer may have enqueued between the thread's drain and
        # its exit — fail those too (the thread is gone; nobody else
        # will ever take them)
        while True:
            try:
                _, fut = self._q.get_nowait()
            except queue.Empty:
                break
            fut._fail(BatcherClosedError("batcher is closed"))

    @property
    def closed(self) -> bool:
        return self._stop.is_set()

    def __del__(self):
        self._stop.set()


__all__ = [
    "BatcherClosedError",
    "ContinuousBatcher",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "ServeFuture",
    "ServeMetrics",
]
