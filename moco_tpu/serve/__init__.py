"""moco_tpu.serve — the embedding inference service.

The "millions of users" leg of the north star: after training the MoCo
dictionary at scale, this package serves it. Four parts, one request
path (see each module's docstring):

- `index`   the dictionary as a reusable store: shared FIFO-write +
            top-k-cosine kernels (core/queue.py and knn.py rehost on
            them) and the P(data)-shardable `EmbeddingIndex` with
            AOT-bucketed queries in six tiers — exact, IVF (jitted
            k-means cells, sub-linear probe scan), the FUSED IVF
            gather-scan (one kernel, running top-k, no materialized
            candidate gather; Pallas cell-DMA lowering on real chips),
            and int8 twins (symmetric per-row quantized scoring)
- `engine`  AOT-compiled (`jit().lower().compile()`) bf16 encoder
            inference, one executable per padded batch bucket
            {1, 8, 32, 128}, donation-audited, key (EMA) encoder by
            default — the stable representation per arXiv:2307.13813;
            `engine_quant` selects off/w8/w8a8 quantization
- `quant`   activation-quantized int8 (w8a8): calibration observer at
            the preprocessing seam, symmetric scale fitting, the JSON
            calibration artifact, and the int8×int8→int32 forward
            (true int8 kernels on tpu/gpu; bit-faithful scaled-integer
            emulation on CPU — the bf16 story, measured)
- `batcher` continuous batching: micro-batch coalescing under a latency
            SLO (flush at max_batch or slo_ms/2), pad to the next
            bucket, scatter per-request; p50/p99/qps/occupancy metrics
- `server`  stdlib HTTP endpoint (`/embed`, `/neighbors`, `/stats`,
            `/healthz`) feeding the `serve/*` metric family into the
            obs sinks (JSONL schema + Prometheus gauges)
- `router`  the fleet front door: health/load-aware dispatch over N
            replicas with per-replica circuit breakers, bounded retry,
            p99-hedging, load shedding, and graceful drain/restart —
            exports the `fleet_serve/*` gauge family
- `fleet`   ReplicaSupervisor: spawns/watches `replica_main` replica
            processes, auto-restarts crashes with backoff, re-warms a
            reborn replica's index via `/ingest`

Everything resolves lazily so `import moco_tpu.serve` stays cheap and
jax-free until a component is actually built.
"""

_LAZY = {
    "EmbeddingIndex": "index",
    "IndexRecompileError": "index",
    "QUERY_MODES": "index",
    "fifo_write": "index",
    "kmeans_fit": "index",
    "topk_cosine": "index",
    "InferenceEngine": "engine",
    "EngineRecompileError": "engine",
    "load_serving_encoder": "engine",
    "quantize_params_int8": "engine",
    "dequantize_params": "engine",
    "QUANT_MODES": "quant",
    "ActivationObserver": "quant",
    "calibrate_encoder": "quant",
    "calibration_path": "quant",
    "load_calibration": "quant",
    "save_calibration": "quant",
    "quantized_apply": "quant",
    "ContinuousBatcher": "batcher",
    "BatcherClosedError": "batcher",
    "ServeMetrics": "batcher",
    "DEFAULT_LATENCY_BUCKETS_MS": "batcher",
    "ServeServer": "server",
    "resolve_serve_port": "server",
    "CircuitBreaker": "router",
    "FleetRouter": "router",
    "ReplicaAttemptError": "router",
    "ReplicaUnavailableError": "router",
    "RouterMetrics": "router",
    "ReplicaSupervisor": "fleet",
    "default_replica_argv": "fleet",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(f"moco_tpu.serve.{_LAZY[name]}"), name)
    raise AttributeError(f"module 'moco_tpu.serve' has no attribute {name!r}")


__all__ = sorted(_LAZY)
