"""Serving fleet front door: a fault-tolerant HTTP router over N
replicas (`ServeServer` processes — usually spawned by
`serve/fleet.py`'s ReplicaSupervisor).

One replica is one SIGKILL away from a total outage; the router is the
robustness layer the ROADMAP's "Serving fleet" item asks for. Same
stdlib idiom as `serve/server.py` (ThreadingHTTPServer, tsan-traced
locks, a metrics flusher on the obs sinks), plus the four classic
front-door behaviors:

- **Health/load-aware dispatch** — a poller thread reads each replica's
  `/healthz` + `/stats`; requests go to the admitted (healthy, not
  draining) replica with the fewest in-flight dispatches.
- **Per-replica circuit breakers** — `fail_threshold` consecutive
  transport/5xx failures trip a replica OPEN; after an (exponentially
  growing) cooldown exactly ONE half-open probe request is admitted,
  and its outcome closes or re-trips the breaker. A dead replica costs
  one connection-refused per cooldown, not one per request.
- **Bounded retry + hedging** — `/embed` and `/neighbors` are
  idempotent, so a failed dispatch re-routes through `utils/retry.py`
  (sites `router.embed` / `router.neighbors`, counted in the io_retries
  ledger), and a request that outlives the p99-derived hedge delay is
  duplicated to a second replica, first success wins (`hedges` /
  `hedge_wins` counters; the losing attempt is discarded on arrival —
  stdlib urlopen cannot be aborted mid-flight).
- **Load shedding + graceful drain** — past `max_inflight` concurrent
  requests the router answers 503 with a `Retry-After` header (counted,
  never a silent drop). `POST /admin/drain?replica=i` stops new
  dispatch to i, waits out its in-flight requests, restarts it through
  the supervisor (SIGTERM → the replica's batcher drain → respawn →
  warm re-ingest), and re-admits it on healthy — zero dropped requests.

Endpoints: `POST /embed`, `POST /neighbors` (proxied; the response
gains a `"replica": i` field next to the replica-scoped `request_id`,
so a flight-recorder dump blames the right process), `GET /healthz`,
`GET /stats` (the `fleet_serve/*` gauge line), `GET /admin/replicas`
(fleet topology — `scripts/serve_ingest.py --fanout` discovers the
replica URLs here), `GET /debug/flight` (the fleet flight ring),
`POST /admin/drain?replica=i[&restart=0]`,
`POST /admin/undrain?replica=i`, and
`POST /admin/promote?replica=i&ckpt_dir=<path>` (one staged-rollout
step: retarget the supervisor's checkpoint dir, then drain/restart that
replica into the candidate encoder — `serve/promote.py` drives it
replica-by-replica, watching burn gauges between steps, and
`fleet_serve/model_skew` counts the distinct served versions so a
half-finished rollout is a visible gauge, not a silent mix).

Observability rides the PR 10 rails: the router's own client-observed
`SLOBurnTracker` exports `fleet_serve/burn_rate_<w>s` (the chaos leg's
acceptance gauge), and each replica's `serve/burn_rate_<w>s` gauges are
aggregated min/mean/max (the `obs/fleet.py` pattern) alongside
`fleet_serve/replicas_healthy`, per-replica dispatch counts, and the
hedge/retry/shed/breaker counters.

**Distributed tracing** (the fleet's request-level answer): every
proxied request gets a `RouterRequestTrace` — ingress/admission/respond
stamps plus one record per dispatch ATTEMPT (replica, retry round,
primary/hedge lane, breaker state at acquisition, outcome). Each
attempt mints a span id and propagates `X-Trace-Id`/`X-Parent-Span`
(obs/ctxprop.py) to the replica, whose stage waterfall comes BACK
in-band as the response's `trace` block — so the router holds the
complete multi-hop picture without an offline merge: network send/recv
split around the replica's own total, every failed attempt, and the
hedge loser's cancelled lane (its cost lands in
`fleet_serve/hedge_wasted_ms`, never in the latency histogram). The
stitched trace feeds three consumers: a fleet-level FlightRecorder
(dumped at the burn-alert edge and on `GET /debug/flight`), the
obs/critpath.py analyzer backing the `fleet_serve/critpath_<hop>_ms`
gauge family, and — when a workdir is given — a per-router Perfetto
stream (`trace_events.r<i>.jsonl` + `heartbeat.r<i>.json` anchor) that
scripts/trace_merge.py joins with the replica streams by trace id.

Threading (JX011/JX012/JX013 discipline): ONE fleet lock
(`router.fleet`, tsan factory) guards every replica handle and breaker
— no per-replica locks, so there is no order to invert — and one
metrics lock (`router.metrics`) inside RouterMetrics; the two are never
nested. All network I/O happens strictly outside both locks. The
health poller, the metrics flusher, and the single drain worker are
joined in `close()`.
"""

from __future__ import annotations

import concurrent.futures
import http.server
import itertools
import json
import os
import queue
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import Counter, deque
from typing import Optional

from moco_tpu.analysis import tsan
from moco_tpu.analysis.contracts import record_route
from moco_tpu.obs import critpath, ctxprop
from moco_tpu.obs.alerts import AlertEngine, parse_rules
from moco_tpu.obs.flight import FlightRecorder
from moco_tpu.obs.reqtrace import REQUEST_LANE_TID_BASE, REQUEST_LANES
from moco_tpu.obs.slo import DEFAULT_WINDOWS, SLOBurnTracker, serve_alert_spec
from moco_tpu.obs.trace import Tracer
from moco_tpu.utils import retry as retry_mod

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class ReplicaAttemptError(OSError):
    """One dispatch attempt failed (transport error, timeout, or a 5xx
    from the replica). An OSError so the `utils/retry.py` default
    `retry_on` covers it — the request is idempotent, re-route it."""


class ReplicaUnavailableError(OSError):
    """No admitted replica could take (or answer) the request this
    round. Also an OSError: the retry layer backs off and re-polls the
    fleet, because a replica may be seconds from rejoining."""


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probe recovery.

    NOT internally locked: the router serializes every call under its
    fleet lock (one lock for all fleet state — no order to invert).
    `try_acquire()` both asks AND claims: in OPEN past the cooldown it
    transitions to HALF_OPEN and hands the caller the single probe
    slot, so two racing dispatchers cannot double-probe. Cooldown grows
    exponentially with consecutive trips (capped) and resets on any
    recovery. `now` is injectable for tests.
    """

    def __init__(
        self,
        fail_threshold: int = 3,
        cooldown_s: float = 2.0,
        cooldown_cap_s: float = 30.0,
        now=time.monotonic,
    ):
        self.fail_threshold = int(fail_threshold)
        self.cooldown_s = float(cooldown_s)
        self.cooldown_cap_s = float(cooldown_cap_s)
        self._now = now
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.trips = 0  # lifetime trip count (fleet_serve/breaker_trips)
        self._trip_streak = 0  # trips since the last recovery → backoff
        self._open_until = 0.0
        self._probe_inflight = False

    def try_acquire(self) -> bool:
        """May the caller dispatch to this replica right now? Claims
        the half-open probe slot when it says yes from OPEN."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_OPEN:
            if self._now() >= self._open_until:
                self.state = BREAKER_HALF_OPEN
                self._probe_inflight = True
                return True
            return False
        # HALF_OPEN: exactly one probe at a time
        if not self._probe_inflight:
            self._probe_inflight = True
            return True
        return False

    def record_success(self) -> None:
        if self.state == BREAKER_OPEN:
            # a straggler from before the trip; recovery goes through
            # the half-open probe, not a stale success
            return
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self._trip_streak = 0
        self._probe_inflight = False

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == BREAKER_HALF_OPEN:
            self._probe_inflight = False
            self._trip()
        elif (
            self.state == BREAKER_CLOSED
            and self.consecutive_failures >= self.fail_threshold
        ):
            self._trip()

    def reset(self) -> None:
        """Back to pristine CLOSED — the router calls this when a
        drained replica is re-admitted after a supervised restart."""
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self._trip_streak = 0
        self._probe_inflight = False

    def _trip(self) -> None:
        self.state = BREAKER_OPEN
        self.trips += 1
        self._trip_streak += 1
        cooldown = min(
            self.cooldown_cap_s, self.cooldown_s * (2 ** (self._trip_streak - 1))
        )
        self._open_until = self._now() + cooldown


class ReplicaHandle:
    """Router-side state for one replica. Every field is read and
    written ONLY under the router's fleet lock."""

    def __init__(self, index: int, url: str, breaker: CircuitBreaker):
        self.index = int(index)
        self.url = url.rstrip("/")
        self.breaker = breaker
        self.healthy = False
        self.warm = False
        self.draining = False
        self.drain_phase: Optional[str] = None
        self.inflight = 0
        self.dispatched = 0
        self.stats: dict = {}  # last /stats payload the poller saw

    @property
    def admitted(self) -> bool:
        return self.healthy and not self.draining

    def snapshot(self) -> dict:
        return {
            "index": self.index,
            "url": self.url,
            "healthy": self.healthy,
            "warm": self.warm,
            "draining": self.draining,
            "drain_phase": self.drain_phase,
            "breaker": self.breaker.state,
            "breaker_trips": self.breaker.trips,
            "inflight": self.inflight,
            "dispatched": self.dispatched,
            # served-model identity from the last /stats poll: the
            # version-skew gauge and the promotion rollout both watch
            # these (None until the poller has seen the replica)
            "model_step": self.stats.get("serve/model_step"),
            "model_digest": self.stats.get("serve/model_digest"),
        }


class RouterRequestTrace:
    """One proxied request's distributed trace, router side: the
    ingress/admission/respond stamps plus a record per dispatch attempt
    (obs/critpath.py stitched schema is `stitched()`'s output).

    Threading: the handler thread creates the trace and its attempt
    records; each attempt is FINALIZED on the dispatch-pool thread that
    ran it (`outcome` is written last, so any reader seeing a non-
    "pending" outcome sees a complete record); the router's flusher
    reads completed traces. Same GIL-atomic append/assign discipline as
    obs/reqtrace.py — no per-request lock."""

    __slots__ = (
        "trace_id", "span_id", "parent_span", "path", "t0", "wall_t0",
        "ingress_ms", "admission_ms", "respond_ms", "status",
        "request_id", "t_end", "attempts", "_round",
    )

    def __init__(self, path: str, t0: float, ctx=None):
        now = time.perf_counter()
        self.t0 = float(t0)
        self.wall_t0 = time.time() - (now - self.t0)
        self.path = path
        # adopt a client-carried trace id (an upstream gateway);
        # otherwise the router is the trace root and mints one
        self.trace_id = ctx.trace_id if ctx is not None else ctxprop.new_trace_id()
        self.parent_span = ctx.span_id if ctx is not None else None
        self.span_id = ctxprop.new_span_id()
        self.ingress_ms = None
        self.admission_ms = None
        self.respond_ms = None
        self.status = None
        self.request_id = None
        self.t_end = None
        self.attempts: list[dict] = []
        self._round = 0

    def next_round(self) -> int:
        """The retry-round index for the next `_attempt_hedged` call —
        handler-thread only (retry rounds are sequential)."""
        rnd = self._round
        self._round += 1
        return rnd

    def new_attempt(self, replica: int, retry_index: int, lane: str,
                    breaker: str) -> dict:
        att = {
            "trace_id": self.trace_id,
            "span_id": ctxprop.new_span_id(),
            "replica": int(replica),
            "retry_index": int(retry_index),
            "lane": lane,  # "primary" | "hedge"
            "breaker": breaker,  # breaker state at acquisition
            "origin_t0": self.t0,  # perf_counter origin for start_ms
            "t0": None, "t1": None,  # perf_counter, set by the dispatcher
            "start_ms": None, "dur_ms": None,
            "net_send_ms": None, "net_recv_ms": None,
            "wasted_ms": None,  # a discarded hedge lane's cost
            "winner": False,
            "remote": None,  # the replica's in-band stage waterfall
            "error": None,
            "outcome": "pending",  # -> ok | failed | cancelled; set LAST
        }
        self.attempts.append(att)
        return att

    def done(self, status: int, request_id=None) -> None:
        self.t_end = time.perf_counter()
        self.status = int(status)
        self.request_id = request_id

    def complete(self) -> bool:
        """Every attempt finalized (a hedge loser may still be in
        flight after the client got its answer)."""
        return all(a["outcome"] != "pending" for a in self.attempts)

    def total_ms(self) -> float:
        end = self.t_end if self.t_end is not None else time.perf_counter()
        return (end - self.t0) * 1e3

    def stitched(self) -> dict:
        """The obs/critpath.py stitched-trace record (private perf-
        counter fields stripped)."""
        attempts = []
        for a in self.attempts:
            pub = {k: v for k, v in a.items()
                   if k not in ("origin_t0", "t0", "t1")}
            attempts.append(pub)
        return {
            "trace_id": self.trace_id,
            "request_id": self.request_id,
            "path": self.path,
            "status": self.status,
            "wall_t0": self.wall_t0,
            "total_ms": round(self.total_ms(), 3),
            "router": {
                "ingress_ms": self.ingress_ms,
                "admission_ms": self.admission_ms,
                "respond_ms": self.respond_ms,
            },
            "attempts": attempts,
        }


def _emit_router_spans(tracer, rtrace: RouterRequestTrace, lane: int) -> None:
    """Render one completed router trace onto the Perfetto stream: a
    `request` parent, the router stage children, and one
    `router/attempt` span per dispatch lane (with its net send/recv
    split when the replica's waterfall came back). Runs on the flusher
    thread; the `request` lanes round-robin like obs/reqtrace.py."""
    if tracer is None:
        return
    lane = lane % REQUEST_LANES
    tid = REQUEST_LANE_TID_BASE + lane
    thread = f"requests-{lane}"
    t_end = rtrace.t_end if rtrace.t_end is not None else time.perf_counter()
    tracer.emit_span(
        "request",
        rtrace.t0,
        t_end,
        tid=tid,
        thread=thread,
        trace_id=rtrace.trace_id,
        span_id=rtrace.span_id,
        path=rtrace.path,
        status=rtrace.status,
        request_id=rtrace.request_id,
    )
    cursor = rtrace.t0
    for name, ms in (("router/ingress", rtrace.ingress_ms),
                     ("router/admission", rtrace.admission_ms)):
        if ms is None:
            continue
        tracer.emit_span(name, cursor, cursor + ms / 1e3, tid=tid,
                         thread=thread, trace_id=rtrace.trace_id)
        cursor += ms / 1e3
    for att in rtrace.attempts:
        if att["t0"] is None:
            continue
        t1 = att["t1"] if att["t1"] is not None else t_end
        tracer.emit_span(
            "router/attempt",
            att["t0"],
            t1,
            tid=tid,
            thread=thread,
            trace_id=rtrace.trace_id,
            span_id=att["span_id"],
            replica=att["replica"],
            retry_index=att["retry_index"],
            lane=att["lane"],
            breaker=att["breaker"],
            outcome=att["outcome"],
            winner=att["winner"],
            wasted_ms=att["wasted_ms"],
            error=att["error"],
        )
        if att["net_send_ms"] is not None:
            tracer.emit_span(
                "router/net_send", att["t0"],
                att["t0"] + att["net_send_ms"] / 1e3,
                tid=tid, thread=thread, trace_id=rtrace.trace_id,
            )
        if att["net_recv_ms"] is not None and att["t1"] is not None:
            tracer.emit_span(
                "router/net_recv", att["t1"] - att["net_recv_ms"] / 1e3,
                att["t1"],
                tid=tid, thread=thread, trace_id=rtrace.trace_id,
            )
    if rtrace.respond_ms is not None:
        tracer.emit_span(
            "router/respond", t_end - rtrace.respond_ms / 1e3, t_end,
            tid=tid, thread=thread, trace_id=rtrace.trace_id,
        )


def _finalize_attempt(
    attempt: Optional[dict], outcome: str, error: Optional[str] = None,
    remote: Optional[dict] = None, t_wall0: Optional[float] = None,
) -> None:
    """Close out one attempt record on the dispatch thread that ran it.
    With the replica's in-band waterfall (`remote`) the wall clocks
    split the attempt into network send (our send wall -> the replica's
    wall_t0) and receive (whatever the replica's own total cannot
    explain — its post-response respond write and the socket read land
    here). `outcome` is written LAST (the reader contract)."""
    if attempt is None:
        return
    t1 = time.perf_counter()
    attempt["t1"] = t1
    dur = (t1 - (attempt["t0"] or t1)) * 1e3
    attempt["dur_ms"] = round(dur, 3)
    if remote is not None and isinstance(remote, dict):
        attempt["remote"] = {
            "request_id": remote.get("request_id"),
            "replica": remote.get("replica"),
            "span_id": remote.get("span_id"),
            "stages": remote.get("stages") or [],
        }
        rw0 = remote.get("wall_t0")
        if t_wall0 is not None and isinstance(rw0, (int, float)):
            send = max(0.0, (rw0 - t_wall0) * 1e3)
            attempt["net_send_ms"] = round(send, 3)
            rtot = max(0.0, float(remote.get("total_ms") or 0.0))
            attempt["net_recv_ms"] = round(max(0.0, dur - send - rtot), 3)
    attempt["error"] = error
    attempt["outcome"] = outcome


class RouterMetrics:
    """Thread-safe router gauges; `payload()` is the `fleet_serve/*`
    core (the router's OWN client-observed latency/burn — the
    per-replica aggregation joins in FleetRouter.stats())."""

    def __init__(
        self,
        slo_ms: float,
        objective: float = 0.99,
        windows=DEFAULT_WINDOWS,
        window: int = 2048,
    ):
        self.slo_ms = float(slo_ms)
        self._lock = tsan.make_lock("router.metrics")
        self.burn = SLOBurnTracker(slo_ms, objective=objective, windows=windows)
        self._latencies_ms: deque = deque(maxlen=window)
        self._counters: Counter = Counter()
        self._completed = 0
        self._win_completed = 0
        self._win_t0 = time.perf_counter()
        # recent critical-path attributions (obs/critpath.py) — the
        # aggregation window behind fleet_serve/critpath_<hop>_ms
        self._critpath: deque = deque(maxlen=512)

    def count(self, name: str, n=1) -> None:
        with self._lock:
            self._counters[name] += n

    def record_request(self, latency_s: float, ok: bool) -> None:
        # NOTE: only CLIENT-OBSERVED completions land here — a
        # cancelled hedge lane's latency must never enter the p99
        # histogram it exists to protect (it is accounted in the
        # hedge_wasted_ms counter instead)
        ms = latency_s * 1e3
        with self._lock:
            self._latencies_ms.append(ms)
            self._completed += 1
            self._win_completed += 1
        self.burn.record(ok and ms <= self.slo_ms)

    def record_critpath(self, attribution: dict) -> None:
        with self._lock:
            self._critpath.append(attribution)

    def record_failure(self) -> None:
        """A request the fleet failed (retries exhausted) or shed —
        burns error budget; never a silent drop."""
        self.burn.record(False)

    def p99_ms(self) -> Optional[float]:
        with self._lock:
            lat = sorted(self._latencies_ms)
        if not lat:
            return None
        return lat[min(int(0.99 * (len(lat) - 1) + 0.5), len(lat) - 1)]

    def payload(self) -> dict:
        with self._lock:
            now = time.perf_counter()
            dt = max(now - self._win_t0, 1e-9)
            qps = self._win_completed / dt
            self._win_t0, self._win_completed = now, 0
            lat = sorted(self._latencies_ms)
            pct = lambda p: (
                lat[min(int(p * (len(lat) - 1) + 0.5), len(lat) - 1)] if lat else None
            )
            counters = dict(self._counters)
            completed = self._completed
            attrs = list(self._critpath)
            out = {
                "fleet_serve/requests": completed,
                "fleet_serve/qps": qps,
                "fleet_serve/p50_ms": pct(0.50),
                "fleet_serve/p99_ms": pct(0.99),
                "fleet_serve/slo_ms": self.slo_ms,
            }
        for name in (
            "hedges",
            "hedge_wins",
            "shed",
            "failed",
            "drains",
            # staged-rollout steps accepted (promote_replica): the
            # promotion audit trail's fleet-side counter
            "promotions",
        ):
            out[f"fleet_serve/{name}"] = counters.get(name, 0)
        # hedge-loser accounting: the cumulative cost of every cancelled
        # lane (the latency that used to vanish with the discarded
        # response)
        out["fleet_serve/hedge_wasted_ms"] = round(
            float(counters.get("hedge_wasted_ms", 0.0)), 3
        )
        # the burn family under the fleet prefix: the ROUTER's own
        # client-observed burn — the chaos leg's acceptance gauge
        for k, v in self.burn.payload().items():
            out["fleet_serve/" + k.split("/", 1)[1]] = v
        agg = critpath.aggregate(attrs)
        if agg["traces"]:
            out.update(critpath.metrics_payload(agg))
        return out


class FleetRouter:
    """The fleet front door (module docstring). `replica_urls` lists
    the replica base URLs; alternatively pass a started
    `ReplicaSupervisor` and the URLs are taken from it (and drain can
    restart replicas). `port=0` binds ephemeral; `self.port` is real.
    """

    def __init__(
        self,
        replica_urls=None,
        supervisor=None,
        host: str = "127.0.0.1",
        port: int = 0,
        slo_ms: float = 1000.0,
        slo_objective: float = 0.99,
        burn_windows=DEFAULT_WINDOWS,
        sink=None,
        metrics_flush_s: float = 1.0,
        health_interval_s: float = 0.5,
        health_timeout_s: float = 2.0,
        replica_timeout_s: float = 30.0,
        retry_attempts: int = 3,
        retry_base_delay_s: float = 0.05,
        retry_max_delay_s: float = 1.0,
        hedge: bool = True,
        hedge_min_ms: float = 250.0,
        hedge_p99_factor: float = 1.0,
        max_inflight: int = 64,
        shed_retry_after_s: float = 1.0,
        breaker_fail_threshold: int = 3,
        breaker_cooldown_s: float = 2.0,
        breaker_cooldown_cap_s: float = 30.0,
        drain_timeout_s: float = 60.0,
        readmit_timeout_s: float = 300.0,
        workdir: str = None,
        router_index: int = 0,
        reqtrace: bool = True,
        flight_requests: int = 256,
        alert_spec: str = "fleet_default",
    ):
        if replica_urls is None:
            if supervisor is None:
                raise ValueError("need replica_urls or a supervisor")
            replica_urls = supervisor.urls()
        if not replica_urls:
            raise ValueError("a fleet needs at least one replica")
        self._supervisor = supervisor
        self.health_interval_s = float(health_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.replica_timeout_s = float(replica_timeout_s)
        self.retry_attempts = int(retry_attempts)
        self.retry_base_delay_s = float(retry_base_delay_s)
        self.retry_max_delay_s = float(retry_max_delay_s)
        self.hedge = bool(hedge)
        self.hedge_min_ms = float(hedge_min_ms)
        self.hedge_p99_factor = float(hedge_p99_factor)
        self.max_inflight = int(max_inflight)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.readmit_timeout_s = float(readmit_timeout_s)
        self.metrics = RouterMetrics(
            slo_ms, objective=slo_objective, windows=burn_windows
        )
        self._sink = sink
        # distributed-tracing consumers (module docstring): the fleet
        # flight ring of stitched multi-hop waterfalls, the burn-rate
        # alert engine that dumps it at the firing edge, and the
        # per-router Perfetto stream when a workdir is given
        self.workdir = workdir
        self.router_index = int(router_index)
        self._reqtrace = bool(reqtrace)
        self.flight = FlightRecorder(
            max_requests=flight_requests, replica=self.router_index
        )
        spec = (
            serve_alert_spec(
                slo_ms, windows=self.metrics.burn.windows, prefix="fleet_serve"
            )
            if alert_spec == "fleet_default"
            else alert_spec
        )
        self._alerts = (
            AlertEngine(
                parse_rules(spec),
                workdir=workdir,
                process_index=self.router_index,
                on_fire=self._on_alert,
            )
            if spec
            else None
        )
        self._tracer = None
        if workdir and self._reqtrace:
            self._tracer = Tracer(
                jsonl_path=os.path.join(
                    workdir, f"trace_events.r{self.router_index}.jsonl"
                ),
                process_index=self.router_index,
            )
            self._write_router_anchor()
        # completed router traces awaiting stitching + span emission —
        # drained by the metrics flusher (bounded: a stalled flusher
        # degrades to dropped traces, never unbounded memory)
        self._trace_pending: deque = deque(maxlen=4 * flight_requests)
        # itertools.count is GIL-atomic: the flusher and a
        # /debug/flight handler may drain traces concurrently
        self._lane = itertools.count()
        self._flush_step = 0
        # ONE lock for all fleet state (handles + breakers + the
        # admission counter): no per-replica locks, no order to invert
        self._fleet_lock = tsan.make_lock("router.fleet")
        self._replicas = [
            ReplicaHandle(
                i,
                url,
                CircuitBreaker(
                    fail_threshold=breaker_fail_threshold,
                    cooldown_s=breaker_cooldown_s,
                    cooldown_cap_s=breaker_cooldown_cap_s,
                ),
            )
            for i, url in enumerate(replica_urls)
        ]
        self._active = 0  # router-wide in-flight count (shed budget)
        # dispatch pool: primary + hedge attempts run here so the
        # handler thread can time out the primary without abandoning it
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2 * self.max_inflight + 4,
            thread_name_prefix="router_dispatch",
        )
        self._stop = threading.Event()
        self._drain_q: queue.Queue = queue.Queue()
        # one synchronous poll before serving: dispatch works from the
        # first request instead of waiting out a poller interval
        self._poll_health()
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                record_route("GET", path)
                if path == "/healthz":
                    with server._fleet_lock:
                        healthy = sum(1 for r in server._replicas if r.admitted)
                        total = len(server._replicas)
                    self._json(200, {
                        "ok": healthy > 0,
                        "replicas": total,
                        "replicas_healthy": healthy,
                    })
                elif path == "/stats":
                    self._json(200, server.stats())
                elif path == "/admin/replicas":
                    with server._fleet_lock:
                        snaps = [r.snapshot() for r in server._replicas]
                    self._json(200, {"replicas": snaps})
                elif path == "/debug/flight":
                    # on-demand fleet flight dump: the ring of stitched
                    # multi-hop waterfalls (the router-side twin of the
                    # replica's /debug/flight)
                    server._drain_traces()
                    body = server.flight.snapshot()
                    if server.workdir:
                        body["dump_path"] = server.flight.dump(
                            server.workdir, reason="debug_request",
                            extra={
                                "slo_ms": server.metrics.slo_ms,
                                "role": "router",
                            },
                        )
                    self._json(200, body)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                t0 = time.perf_counter()
                path, _, query = self.path.partition("?")
                record_route("POST", path)
                if path == "/admin/drain":
                    self._handle_admin_drain(query)
                    return
                if path == "/admin/undrain":
                    self._handle_admin_undrain(query)
                    return
                if path == "/admin/promote":
                    self._handle_admin_promote(query)
                    return
                if path not in ("/embed", "/neighbors"):
                    self.send_error(404)
                    return
                body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
                headers = {}
                shape = self.headers.get("X-Image-Shape")
                if shape:
                    headers["X-Image-Shape"] = shape
                # a client-carried trace context (an upstream gateway's
                # X-Trace-Id/X-Parent-Span) is adopted; absent one the
                # router mints the trace id — either way every dispatch
                # attempt below propagates it to the replica
                ctx_in = ctxprop.parse(
                    self.headers.get("X-Trace-Id"),
                    self.headers.get("X-Parent-Span"),
                )
                t_ing = time.perf_counter()
                if not server._admit():
                    # load shedding: a counted 503 + Retry-After, never
                    # a silent drop (and it burns error budget)
                    server.metrics.count("shed")
                    server.metrics.record_failure()
                    self._json(
                        503,
                        {"error": "router at max_inflight budget", "shed": True},
                        extra_headers={
                            "Retry-After": str(
                                max(1, round(server.shed_retry_after_s))
                            )
                        },
                    )
                    return
                rtrace = None
                if server._reqtrace:
                    # backdated to handler entry so ingress covers the
                    # body read; shed requests stay untraced (no
                    # dispatch hops to attribute)
                    rtrace = RouterRequestTrace(path, t0, ctx=ctx_in)
                    rtrace.ingress_ms = round((t_ing - t0) * 1e3, 3)
                    rtrace.admission_ms = round(
                        (time.perf_counter() - t_ing) * 1e3, 3
                    )
                try:
                    status, payload, rep_index = retry_mod.retry_call(
                        server._attempt_hedged,
                        self.path,
                        body,
                        headers,
                        rtrace,
                        site="router." + path.strip("/"),
                        attempts=server.retry_attempts,
                        base_delay=server.retry_base_delay_s,
                        max_delay=server.retry_max_delay_s,
                        retry_on=(ReplicaAttemptError, ReplicaUnavailableError),
                    )
                except OSError as e:
                    # retries exhausted across the fleet: loud 503
                    server.metrics.count("failed")
                    server.metrics.record_failure()
                    err_body = {"error": f"fleet dispatch failed: {e}"}
                    if rtrace is not None:
                        err_body["trace_id"] = rtrace.trace_id
                    t_resp = time.perf_counter()
                    self._json(
                        503,
                        err_body,
                        extra_headers={"Retry-After": "1"},
                    )
                    if rtrace is not None:
                        # the failed trace is still a trace: every dead
                        # attempt attributed, no winner
                        rtrace.respond_ms = round(
                            (time.perf_counter() - t_resp) * 1e3, 3
                        )
                        rtrace.done(503)
                        server._trace_complete(rtrace)
                    return
                finally:
                    server._release()
                server.metrics.record_request(
                    time.perf_counter() - t0, ok=status == 200
                )
                if isinstance(payload, dict):
                    # replica attribution next to the replica-scoped
                    # request_id (r<i>-<seq>) the replica minted
                    payload.setdefault("replica", rep_index)
                    if rtrace is not None:
                        payload["trace_id"] = rtrace.trace_id
                t_resp = time.perf_counter()
                self._json(status, payload)
                if rtrace is not None:
                    rtrace.respond_ms = round(
                        (time.perf_counter() - t_resp) * 1e3, 3
                    )
                    rtrace.done(
                        status,
                        payload.get("request_id")
                        if isinstance(payload, dict) else None,
                    )
                    server._trace_complete(rtrace)

            def _handle_admin_drain(self, query):
                idx = _parse_replica(query, len(server._replicas))
                if idx is None:
                    self._json(400, {"error": "need replica=<index>"})
                    return
                restart = _query_flag(query, "restart", default=None)
                started = server.drain_replica(idx, restart=restart)
                with server._fleet_lock:
                    snap = server._replicas[idx].snapshot()
                self._json(202, {"accepted": started, "replica": snap})

            def _handle_admin_promote(self, query):
                # one staged-rollout step: point the supervisor at the
                # candidate checkpoint dir and drain/restart ONE replica
                # into it (the promotion controller drives this per
                # replica, watching burn gauges between steps)
                idx = _parse_replica(query, len(server._replicas))
                if idx is None:
                    self._json(400, {"error": "need replica=<index>"})
                    return
                ckpt_dir = _query_param(query, "ckpt_dir")
                if ckpt_dir is None:
                    self._json(400, {"error": "need ckpt_dir=<path>"})
                    return
                try:
                    started = server.promote_replica(
                        idx, urllib.parse.unquote(ckpt_dir)
                    )
                except RuntimeError as e:
                    self._json(409, {"error": str(e)})
                    return
                with server._fleet_lock:
                    snap = server._replicas[idx].snapshot()
                self._json(202, {"accepted": started, "replica": snap})

            def _handle_admin_undrain(self, query):
                idx = _parse_replica(query, len(server._replicas))
                if idx is None:
                    self._json(400, {"error": "need replica=<index>"})
                    return
                server.undrain_replica(idx)
                with server._fleet_lock:
                    snap = server._replicas[idx].snapshot()
                self._json(200, {"replica": snap})

            def _json(self, code, obj, extra_headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr lines
                pass

        from moco_tpu.serve.server import _QuietHTTPServer

        self._server = _QuietHTTPServer((host, int(port)), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="router_http", daemon=True
        )
        self._thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="router_health", daemon=True
        )
        self._health_thread.start()
        self._drainer = threading.Thread(
            target=self._drain_loop, name="router_drain", daemon=True
        )
        self._drainer.start()
        self._flusher = threading.Thread(
            target=self._flush_loop, args=(float(metrics_flush_s),),
            name="router_metrics_flush", daemon=True,
        )
        self._flusher.start()

    # -- dispatch ---------------------------------------------------------

    def _admit(self) -> bool:
        with self._fleet_lock:
            if self._active >= self.max_inflight:
                return False
            self._active += 1
            return True

    def _release(self) -> None:
        with self._fleet_lock:
            self._active -= 1

    def _acquire(self, exclude=()) -> Optional[ReplicaHandle]:
        """Claim a replica for one attempt: admitted (healthy, not
        draining), breaker willing, fewest in-flight first. Books the
        in-flight/dispatch counters under the fleet lock."""
        with self._fleet_lock:
            cands = sorted(
                (
                    r for r in self._replicas
                    if r.admitted and r.index not in exclude
                ),
                key=lambda r: (r.inflight, r.dispatched, r.index),
            )
            # a breaker due for its half-open probe takes the request
            # first: recovery needs live traffic, a failed probe is
            # retried on a closed replica anyway, and try_acquire gates
            # this to one probe per cooldown — an OPEN breaker inside
            # its cooldown says no and the request flows to the closed
            # replicas below
            for r in cands:
                if r.breaker.state != BREAKER_CLOSED and r.breaker.try_acquire():
                    r.inflight += 1
                    r.dispatched += 1
                    return r
            for r in cands:
                if r.breaker.state == BREAKER_CLOSED and r.breaker.try_acquire():
                    r.inflight += 1
                    r.dispatched += 1
                    return r
        return None

    def _finish(self, rep: ReplicaHandle, ok: bool) -> None:
        with self._fleet_lock:
            rep.inflight = max(0, rep.inflight - 1)
            if ok:
                rep.breaker.record_success()
            else:
                rep.breaker.record_failure()

    def _try_replica(
        self, rep: ReplicaHandle, path_q: str, body: bytes, headers: dict,
        attempt: Optional[dict] = None,
    ):
        """One attempt against one replica (runs on the dispatch pool;
        no locks held across the network I/O). Returns (status, payload,
        replica_index); raises ReplicaAttemptError on anything worth
        re-routing. `attempt` is this lane's RouterRequestTrace record:
        its span id rides downstream as X-Parent-Span, and the record is
        finalized here — on the thread that ran the attempt — with the
        outcome, the network send/recv split, and the replica's in-band
        stage waterfall (popped off the payload)."""
        hdrs = dict(headers)
        t_wall0 = time.time()
        if attempt is not None:
            ctxprop.inject(
                hdrs,
                ctxprop.TraceContext(attempt["trace_id"], attempt["span_id"]),
            )
            attempt["t0"] = time.perf_counter()
            attempt["start_ms"] = round(
                (attempt["t0"] - attempt["origin_t0"]) * 1e3, 3
            )
        req = urllib.request.Request(rep.url + path_q, data=body, headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=self.replica_timeout_s) as resp:
                payload = json.loads(resp.read())
                status = resp.status
        except urllib.error.HTTPError as e:
            if 400 <= e.code < 500:
                # the replica is alive and judged the request itself: a
                # client error passes through un-retried (breaker success)
                try:
                    payload = json.loads(e.read())
                except ValueError:
                    payload = {"error": f"replica {rep.index}: HTTP {e.code}"}
                self._finish(rep, ok=True)
                _finalize_attempt(attempt, "ok", error=f"HTTP {e.code}")
                return e.code, payload, rep.index
            self._finish(rep, ok=False)
            _finalize_attempt(attempt, "failed", error=f"HTTP {e.code}")
            raise ReplicaAttemptError(f"replica {rep.index}: HTTP {e.code}") from e
        except (OSError, TimeoutError) as e:  # URLError/socket resets/timeouts
            self._finish(rep, ok=False)
            _finalize_attempt(attempt, "failed", error=repr(e))
            raise ReplicaAttemptError(f"replica {rep.index}: {e!r}") from e
        except ValueError as e:  # torn/garbled response body
            self._finish(rep, ok=False)
            _finalize_attempt(attempt, "failed", error=repr(e))
            raise ReplicaAttemptError(
                f"replica {rep.index}: bad response ({e!r})"
            ) from e
        self._finish(rep, ok=True)
        remote = (
            payload.pop("trace", None) if isinstance(payload, dict) else None
        )
        _finalize_attempt(attempt, "ok", remote=remote, t_wall0=t_wall0)
        return status, payload, rep.index

    def _hedge_delay_s(self) -> Optional[float]:
        if not self.hedge:
            return None
        p99 = self.metrics.p99_ms()
        ms = max(self.hedge_min_ms, (p99 or 0.0) * self.hedge_p99_factor)
        return ms / 1e3

    def _attempt_hedged(
        self, path_q: str, body: bytes, headers: dict,
        rtrace: Optional[RouterRequestTrace] = None,
    ):
        """One retry-round: dispatch to the best replica; if it outlives
        the hedge delay, duplicate to a second one and take the first
        success (first-winner — the loser's response is discarded when
        it lands; urlopen cannot be cancelled mid-flight, so the loser
        lane is marked CANCELLED when it completes and its full cost is
        booked to `hedge_wasted_ms` rather than vanishing). Raises an
        OSError subclass when the round produced no success, which is
        what the retry layer backs off on."""
        rep = self._acquire()
        if rep is None:
            raise ReplicaUnavailableError("no admitted replica to dispatch to")
        rnd = rtrace.next_round() if rtrace is not None else 0
        att = (
            rtrace.new_attempt(rep.index, rnd, "primary", rep.breaker.state)
            if rtrace is not None else None
        )
        primary = self._pool.submit(
            self._try_replica, rep, path_q, body, headers, att
        )
        delay = self._hedge_delay_s()
        if delay is None:
            result = primary.result()
            if att is not None:
                att["winner"] = True
            return result
        try:
            result = primary.result(timeout=delay)
        except concurrent.futures.TimeoutError:
            pass  # primary is slow, not failed: hedge it
        else:
            if att is not None:
                att["winner"] = True
            return result
        second = self._acquire(exclude=(rep.index,))
        lanes = [(primary, att, time.perf_counter() - delay)]
        if second is not None:
            self.metrics.count("hedges")
            att2 = (
                rtrace.new_attempt(
                    second.index, rnd, "hedge", second.breaker.state
                )
                if rtrace is not None else None
            )
            lanes.append((
                self._pool.submit(
                    self._try_replica, second, path_q, body, headers, att2
                ),
                att2,
                time.perf_counter(),
            ))
        pending = {fut for fut, _, _ in lanes}
        errors = []
        while pending:
            done, pending = concurrent.futures.wait(
                pending, return_when=concurrent.futures.FIRST_COMPLETED
            )
            for fut in done:
                err = fut.exception()
                if err is None:
                    if len(lanes) == 2 and fut is lanes[1][0]:
                        self.metrics.count("hedge_wins")
                    for lfut, latt, lt0 in lanes:
                        if lfut is fut:
                            if latt is not None:
                                latt["winner"] = True
                        else:
                            self._cancel_lane(lfut, latt, lt0)
                    return fut.result()
                errors.append(err)
        raise ReplicaUnavailableError(
            "all attempts failed this round: "
            + "; ".join(repr(e) for e in errors)
        )

    def _cancel_lane(self, fut, att: Optional[dict], t_lane0: float) -> None:
        """Hedge-loser accounting: when the discarded lane completes
        (urlopen can't be aborted mid-flight), mark its span cancelled
        and book its full duration to the `hedge_wasted_ms` counter.
        The lane's latency never reaches the p99 histogram — only
        client-observed completions do (`RouterMetrics.record_request`)."""

        def _book(f):
            wasted = max(0.0, (time.perf_counter() - t_lane0) * 1e3)
            if att is not None:
                if att["dur_ms"] is not None:
                    wasted = att["dur_ms"]
                att["wasted_ms"] = round(wasted, 3)
                if att["outcome"] in ("ok", "pending"):
                    att["outcome"] = "cancelled"  # after wasted_ms (reader contract)
            self.metrics.count("hedge_wasted_ms", round(wasted, 3))

        fut.add_done_callback(_book)

    # -- health -----------------------------------------------------------

    def _probe(self, url: str):
        """(ok, warm, stats) for one replica — network I/O, call with
        no locks held."""
        try:
            with urllib.request.urlopen(
                url + "/healthz", timeout=self.health_timeout_s
            ) as r:
                h = json.loads(r.read())
        except (OSError, ValueError):
            return False, False, None
        stats = None
        try:
            with urllib.request.urlopen(
                url + "/stats", timeout=self.health_timeout_s
            ) as r:
                stats = json.loads(r.read())
        except (OSError, ValueError):
            pass
        return bool(h.get("ok")), bool(h.get("warm")), stats

    def _poll_health(self) -> None:
        with self._fleet_lock:
            targets = [(r.index, r.url) for r in self._replicas]
        for index, url in targets:
            ok, warm, stats = self._probe(url)
            with self._fleet_lock:
                rep = self._replicas[index]
                if rep.url != url:
                    continue  # replica moved mid-poll; drop the stale probe
                rep.healthy = ok
                rep.warm = warm
                if stats is not None:
                    rep.stats = stats

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            self._poll_health()

    # -- drain ------------------------------------------------------------

    def drain_replica(self, index: int, restart: Optional[bool] = None) -> bool:
        """Stop dispatching to replica `index`, wait out its in-flight
        requests, then (default, when a supervisor is attached) restart
        it and re-admit on healthy. Asynchronous: returns immediately
        (False = already draining); poll `/admin/replicas` for phase."""
        if restart is None:
            restart = self._supervisor is not None
        with self._fleet_lock:
            rep = self._replicas[index]
            if rep.draining:
                return False
            rep.draining = True
            rep.drain_phase = "waiting_inflight"
        self.metrics.count("drains")
        self._drain_q.put((index, bool(restart)))
        return True

    def promote_replica(self, index: int, ckpt_dir: str) -> bool:
        """One promotion step: retarget the supervisor's checkpoint dir
        at `ckpt_dir`, then drain/restart replica `index` so it comes
        back serving the candidate encoder. Asynchronous like
        `drain_replica` (False = that replica is already draining);
        the caller polls `/admin/replicas` for the swap landing (the
        replica's `model_digest` changes when it re-admits)."""
        if self._supervisor is None:
            raise RuntimeError(
                "promotion needs a supervisor-backed fleet "
                "(no supervisor attached to this router)"
            )
        self._supervisor.set_ckpt_dir(ckpt_dir)
        self.metrics.count("promotions")
        return self.drain_replica(index, restart=True)

    def undrain_replica(self, index: int) -> None:
        with self._fleet_lock:
            rep = self._replicas[index]
            rep.draining = False
            rep.drain_phase = None
            rep.breaker.reset()

    def _set_phase(self, rep: ReplicaHandle, phase: Optional[str]) -> None:
        with self._fleet_lock:
            rep.drain_phase = phase

    def _drain_loop(self) -> None:
        """The single drain worker: serializes drain/restart jobs (one
        replica leaves the fleet at a time — a fleet-wide drain storm
        cannot empty the rotation)."""
        while not self._stop.is_set():
            try:
                index, restart = self._drain_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._do_drain(index, restart)
            except Exception as e:  # a failed drain must not kill the worker
                print(f"router: drain of replica {index} failed: {e!r}", flush=True)
                self._set_phase(self._replicas[index], "drain_failed")

    def _do_drain(self, index: int, restart: bool) -> None:
        rep = self._replicas[index]
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            with self._fleet_lock:
                inflight = rep.inflight
            if inflight == 0:
                break
            time.sleep(0.05)
        if restart and self._supervisor is not None:
            self._set_phase(rep, "restarting")
            self._supervisor.restart_replica(index)
            self._set_phase(rep, "readmitting")
            deadline = time.monotonic() + self.readmit_timeout_s
            ok = False
            while time.monotonic() < deadline and not self._stop.is_set():
                ok, warm, stats = self._probe(rep.url)
                if ok:
                    break
                time.sleep(0.2)
            with self._fleet_lock:
                rep.healthy = ok
                rep.draining = False
                rep.drain_phase = None if ok else "readmit_timeout"
                rep.breaker.reset()
        else:
            # no restart: drain the replica's own batcher (flushes every
            # accepted request) and park it out of rotation
            try:
                req = urllib.request.Request(
                    rep.url + f"/admin/drain?timeout={self.drain_timeout_s:.1f}",
                    data=b"",
                )
                with urllib.request.urlopen(req, timeout=self.drain_timeout_s + 10):
                    pass
            except (OSError, ValueError) as e:
                print(
                    f"router: replica {index} /admin/drain failed: {e!r}", flush=True
                )
            self._set_phase(rep, "drained")

    # -- metrics ----------------------------------------------------------

    def stats(self) -> dict:
        """The `fleet_serve/*` gauge line: the router's own burn/latency
        family plus fleet topology, per-replica dispatch counts, and the
        per-replica burn gauges aggregated min/mean/max (the obs/fleet.py
        pattern). Snapshots fleet state first, THEN takes the metrics
        lock inside payload() — the two locks never nest."""
        with self._fleet_lock:
            snaps = [r.snapshot() for r in self._replicas]
            replica_stats = [dict(r.stats) for r in self._replicas]
            active = self._active
        out = self.metrics.payload()
        out["fleet_serve/replicas"] = len(snaps)
        out["fleet_serve/replicas_healthy"] = sum(
            1 for s in snaps if s["healthy"] and not s["draining"]
        )
        out["fleet_serve/inflight"] = active
        out["fleet_serve/breaker_open"] = sum(
            1 for s in snaps if s["breaker"] == BREAKER_OPEN
        )
        out["fleet_serve/breaker_trips"] = sum(s["breaker_trips"] for s in snaps)
        for s in snaps:
            out[f"fleet_serve/dispatch_{s['index']}"] = s["dispatched"]
        burn_keys = set()
        for st in replica_stats:
            burn_keys |= {
                k
                for k in st
                if k.startswith("serve/burn_rate_")
                or k.startswith("serve/fresh_burn_rate_")
                # the fleet's live online-recall baseline: the promotion
                # pipeline's live_recall gate reads the _max aggregate
                or k == "serve/recall_estimate"
            }
        for k in sorted(burn_keys):
            vals = [
                st[k] for st in replica_stats if st.get(k) is not None
            ]
            base = "fleet_serve/" + k.split("/", 1)[1]
            out[base + "_min"] = min(vals) if vals else None
            out[base + "_mean"] = sum(vals) / len(vals) if vals else None
            out[base + "_max"] = max(vals) if vals else None
        # version-skew gauge: how many DISTINCT encoder versions the
        # fleet is serving, minus one (0 = homogeneous; >0 mid-rollout
        # or a stuck replica). None until any replica reports a digest.
        digests = {
            st.get("serve/model_digest")
            for st in replica_stats
            if st.get("serve/model_digest") is not None
        }
        out["fleet_serve/model_skew"] = len(digests) - 1 if digests else None
        router_retries = {
            k: v
            for k, v in retry_mod.snapshot().items()
            if k.startswith("router.")
        }
        out["fleet_serve/retries"] = sum(router_retries.values())
        if router_retries:
            out["io_retries"] = router_retries
        return out

    # -- distributed-trace emission (off the request path) ---------------

    def _trace_complete(self, rtrace: RouterRequestTrace) -> None:
        """Handler-thread side: O(1) append; stitching, critical-path
        attribution, flight filing, and span rendering all happen on
        the flusher."""
        self._trace_pending.append(rtrace)

    def _drain_traces(self, force: bool = False) -> None:
        """Emit every completed pending trace. A trace whose hedge
        loser is still in flight is HELD BACK (re-queued) so the
        stitched record carries the cancelled lane's real cost — up to
        one replica-timeout of grace, then it goes out as-is. Safe for
        concurrent callers (flusher + a /debug/flight handler): the
        deque pops hand each trace to exactly one emitter."""
        grace = self.replica_timeout_s
        requeue = []
        while True:
            try:
                rt = self._trace_pending.popleft()
            except IndexError:
                break
            if (
                not force
                and not rt.complete()
                and (time.perf_counter() - (rt.t_end or rt.t0)) < grace
            ):
                requeue.append(rt)
                continue
            self._emit_trace(rt)
        for rt in requeue:
            self._trace_pending.append(rt)

    def _emit_trace(self, rtrace: RouterRequestTrace) -> None:
        stitched = rtrace.stitched()
        rec = dict(stitched)
        rec["stages"] = critpath.flatten(stitched)
        self.flight.record_request(rec)
        self.metrics.record_critpath(critpath.attribute(stitched))
        if self._tracer is not None:
            _emit_router_spans(self._tracer, rtrace, next(self._lane))

    def _write_router_anchor(self) -> None:
        """Atomic `heartbeat.r<router_index>.json` with the tracer's
        wall anchor — scripts/trace_merge.py clock-aligns the router
        stream against the replica streams with it."""
        rec = {
            "process": self.router_index,
            "role": "router",
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "time": time.time(),
            "trace_wall_t0": self._tracer.wall_t0,
        }
        path = os.path.join(self.workdir, f"heartbeat.r{self.router_index}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)

    def _on_alert(self, alert: dict) -> None:
        """AlertEngine on_fire hook: a fleet burn-rate (or p99) alert
        dumps the DISTRIBUTED flight ring at the firing edge — the
        postmortem file holds stitched multi-hop waterfalls, not one
        process's view — and lands an in-band alert event line."""
        if self.workdir:
            try:
                self.flight.dump(
                    self.workdir,
                    reason=f"alert:{alert['rule']}",
                    extra={
                        "alert": alert,
                        "slo_ms": self.metrics.slo_ms,
                        "role": "router",
                    },
                )
            except Exception as e:  # the dump must never take the router down
                print(f"WARNING: router flight dump failed: {e!r}", flush=True)
        if self._sink is not None:
            self._sink.write(
                self._flush_step,
                {
                    "event": "alert",
                    "alert": alert["rule"],
                    "severity": alert["severity"],
                    f"alert/{alert['rule']}": 1.0,
                },
            )

    def _flush_loop(self, interval: float) -> None:
        step = 0
        while not self._stop.wait(interval):
            step += 1
            self._write_metrics(step)
        self._write_metrics(step + 1)  # the run's last gauges land too

    def _write_metrics(self, step: int) -> None:
        self._flush_step = step  # mocolint: disable=JX012  (flusher-thread only during the run; close() joins the flusher before its own final drain, so writers are join-serialized)
        try:
            self._drain_traces()
            payload = self.stats()
            self.flight.record_metrics(step, payload)
            if self._alerts is not None:
                self._alerts.observe(step, payload)
            if self._sink is not None:
                self._sink.write(step, payload)
        except Exception as e:  # metrics must never take the router down
            print(f"WARNING: router metrics sink failed: {e!r}", flush=True)

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        """Stop the poller/flusher/drain worker, shut HTTP, join all
        four threads, and retire the dispatch pool (JX011 discipline).
        After the pool drains, force-emit any held-back traces (a hedge
        loser that never completed goes out with its lane pending) and
        close the trace stream."""
        self._stop.set()
        self._health_thread.join(timeout=10.0)
        self._flusher.join(timeout=10.0)
        self._drainer.join(timeout=self.drain_timeout_s + 30.0)
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10.0)
        self._pool.shutdown(wait=True, cancel_futures=True)
        self._drain_traces(force=True)
        if self._alerts is not None:
            self._alerts.close()
        if self._tracer is not None:
            self._tracer.close()


def _query_param(query: str, name: str) -> Optional[str]:
    for part in query.split("&"):
        if part.startswith(name + "="):
            return part[len(name) + 1 :] or None
    return None


def _parse_replica(query: str, num_replicas: int) -> Optional[int]:
    val = _query_param(query, "replica")
    if val is None:
        return None
    try:
        idx = int(val)
    except ValueError:
        return None
    if not 0 <= idx < num_replicas:
        return None
    return idx


def _query_flag(query: str, name: str, default=None):
    val = _query_param(query, name)
    if val is None:
        return default
    return val not in ("0", "false", "no")


__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "CircuitBreaker",
    "FleetRouter",
    "ReplicaAttemptError",
    "ReplicaHandle",
    "ReplicaUnavailableError",
    "RouterMetrics",
    "RouterRequestTrace",
]
