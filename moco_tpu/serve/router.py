"""Serving fleet front door: a fault-tolerant HTTP router over N
replicas (`ServeServer` processes — usually spawned by
`serve/fleet.py`'s ReplicaSupervisor).

One replica is one SIGKILL away from a total outage; the router is the
robustness layer the ROADMAP's "Serving fleet" item asks for. Same
stdlib idiom as `serve/server.py` (ThreadingHTTPServer, tsan-traced
locks, a metrics flusher on the obs sinks), plus the four classic
front-door behaviors:

- **Health/load-aware dispatch** — a poller thread reads each replica's
  `/healthz` + `/stats`; requests go to the admitted (healthy, not
  draining) replica with the fewest in-flight dispatches.
- **Per-replica circuit breakers** — `fail_threshold` consecutive
  transport/5xx failures trip a replica OPEN; after an (exponentially
  growing) cooldown exactly ONE half-open probe request is admitted,
  and its outcome closes or re-trips the breaker. A dead replica costs
  one connection-refused per cooldown, not one per request.
- **Bounded retry + hedging** — `/embed` and `/neighbors` are
  idempotent, so a failed dispatch re-routes through `utils/retry.py`
  (sites `router.embed` / `router.neighbors`, counted in the io_retries
  ledger), and a request that outlives the p99-derived hedge delay is
  duplicated to a second replica, first success wins (`hedges` /
  `hedge_wins` counters; the losing attempt is discarded on arrival —
  stdlib urlopen cannot be aborted mid-flight).
- **Load shedding + graceful drain** — past `max_inflight` concurrent
  requests the router answers 503 with a `Retry-After` header (counted,
  never a silent drop). `POST /admin/drain?replica=i` stops new
  dispatch to i, waits out its in-flight requests, restarts it through
  the supervisor (SIGTERM → the replica's batcher drain → respawn →
  warm re-ingest), and re-admits it on healthy — zero dropped requests.

Endpoints: `POST /embed`, `POST /neighbors` (proxied; the response
gains a `"replica": i` field next to the replica-scoped `request_id`,
so a flight-recorder dump blames the right process), `GET /healthz`,
`GET /stats` (the `fleet_serve/*` gauge line), `GET /admin/replicas`
(fleet topology — `scripts/serve_ingest.py --fanout` discovers the
replica URLs here), `POST /admin/drain?replica=i[&restart=0]`,
`POST /admin/undrain?replica=i`.

Observability rides the PR 10 rails: the router's own client-observed
`SLOBurnTracker` exports `fleet_serve/burn_rate_<w>s` (the chaos leg's
acceptance gauge), and each replica's `serve/burn_rate_<w>s` gauges are
aggregated min/mean/max (the `obs/fleet.py` pattern) alongside
`fleet_serve/replicas_healthy`, per-replica dispatch counts, and the
hedge/retry/shed/breaker counters.

Threading (JX011/JX012/JX013 discipline): ONE fleet lock
(`router.fleet`, tsan factory) guards every replica handle and breaker
— no per-replica locks, so there is no order to invert — and one
metrics lock (`router.metrics`) inside RouterMetrics; the two are never
nested. All network I/O happens strictly outside both locks. The
health poller, the metrics flusher, and the single drain worker are
joined in `close()`.
"""

from __future__ import annotations

import concurrent.futures
import http.server
import json
import queue
import threading
import time
import urllib.error
import urllib.request
from collections import Counter, deque
from typing import Optional

from moco_tpu.analysis import tsan
from moco_tpu.analysis.contracts import record_route
from moco_tpu.obs.slo import DEFAULT_WINDOWS, SLOBurnTracker
from moco_tpu.utils import retry as retry_mod

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class ReplicaAttemptError(OSError):
    """One dispatch attempt failed (transport error, timeout, or a 5xx
    from the replica). An OSError so the `utils/retry.py` default
    `retry_on` covers it — the request is idempotent, re-route it."""


class ReplicaUnavailableError(OSError):
    """No admitted replica could take (or answer) the request this
    round. Also an OSError: the retry layer backs off and re-polls the
    fleet, because a replica may be seconds from rejoining."""


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probe recovery.

    NOT internally locked: the router serializes every call under its
    fleet lock (one lock for all fleet state — no order to invert).
    `try_acquire()` both asks AND claims: in OPEN past the cooldown it
    transitions to HALF_OPEN and hands the caller the single probe
    slot, so two racing dispatchers cannot double-probe. Cooldown grows
    exponentially with consecutive trips (capped) and resets on any
    recovery. `now` is injectable for tests.
    """

    def __init__(
        self,
        fail_threshold: int = 3,
        cooldown_s: float = 2.0,
        cooldown_cap_s: float = 30.0,
        now=time.monotonic,
    ):
        self.fail_threshold = int(fail_threshold)
        self.cooldown_s = float(cooldown_s)
        self.cooldown_cap_s = float(cooldown_cap_s)
        self._now = now
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.trips = 0  # lifetime trip count (fleet_serve/breaker_trips)
        self._trip_streak = 0  # trips since the last recovery → backoff
        self._open_until = 0.0
        self._probe_inflight = False

    def try_acquire(self) -> bool:
        """May the caller dispatch to this replica right now? Claims
        the half-open probe slot when it says yes from OPEN."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_OPEN:
            if self._now() >= self._open_until:
                self.state = BREAKER_HALF_OPEN
                self._probe_inflight = True
                return True
            return False
        # HALF_OPEN: exactly one probe at a time
        if not self._probe_inflight:
            self._probe_inflight = True
            return True
        return False

    def record_success(self) -> None:
        if self.state == BREAKER_OPEN:
            # a straggler from before the trip; recovery goes through
            # the half-open probe, not a stale success
            return
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self._trip_streak = 0
        self._probe_inflight = False

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == BREAKER_HALF_OPEN:
            self._probe_inflight = False
            self._trip()
        elif (
            self.state == BREAKER_CLOSED
            and self.consecutive_failures >= self.fail_threshold
        ):
            self._trip()

    def reset(self) -> None:
        """Back to pristine CLOSED — the router calls this when a
        drained replica is re-admitted after a supervised restart."""
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self._trip_streak = 0
        self._probe_inflight = False

    def _trip(self) -> None:
        self.state = BREAKER_OPEN
        self.trips += 1
        self._trip_streak += 1
        cooldown = min(
            self.cooldown_cap_s, self.cooldown_s * (2 ** (self._trip_streak - 1))
        )
        self._open_until = self._now() + cooldown


class ReplicaHandle:
    """Router-side state for one replica. Every field is read and
    written ONLY under the router's fleet lock."""

    def __init__(self, index: int, url: str, breaker: CircuitBreaker):
        self.index = int(index)
        self.url = url.rstrip("/")
        self.breaker = breaker
        self.healthy = False
        self.warm = False
        self.draining = False
        self.drain_phase: Optional[str] = None
        self.inflight = 0
        self.dispatched = 0
        self.stats: dict = {}  # last /stats payload the poller saw

    @property
    def admitted(self) -> bool:
        return self.healthy and not self.draining

    def snapshot(self) -> dict:
        return {
            "index": self.index,
            "url": self.url,
            "healthy": self.healthy,
            "warm": self.warm,
            "draining": self.draining,
            "drain_phase": self.drain_phase,
            "breaker": self.breaker.state,
            "breaker_trips": self.breaker.trips,
            "inflight": self.inflight,
            "dispatched": self.dispatched,
        }


class RouterMetrics:
    """Thread-safe router gauges; `payload()` is the `fleet_serve/*`
    core (the router's OWN client-observed latency/burn — the
    per-replica aggregation joins in FleetRouter.stats())."""

    def __init__(
        self,
        slo_ms: float,
        objective: float = 0.99,
        windows=DEFAULT_WINDOWS,
        window: int = 2048,
    ):
        self.slo_ms = float(slo_ms)
        self._lock = tsan.make_lock("router.metrics")
        self.burn = SLOBurnTracker(slo_ms, objective=objective, windows=windows)
        self._latencies_ms: deque = deque(maxlen=window)
        self._counters: Counter = Counter()
        self._completed = 0
        self._win_completed = 0
        self._win_t0 = time.perf_counter()

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def record_request(self, latency_s: float, ok: bool) -> None:
        ms = latency_s * 1e3
        with self._lock:
            self._latencies_ms.append(ms)
            self._completed += 1
            self._win_completed += 1
        self.burn.record(ok and ms <= self.slo_ms)

    def record_failure(self) -> None:
        """A request the fleet failed (retries exhausted) or shed —
        burns error budget; never a silent drop."""
        self.burn.record(False)

    def p99_ms(self) -> Optional[float]:
        with self._lock:
            lat = sorted(self._latencies_ms)
        if not lat:
            return None
        return lat[min(int(0.99 * (len(lat) - 1) + 0.5), len(lat) - 1)]

    def payload(self) -> dict:
        with self._lock:
            now = time.perf_counter()
            dt = max(now - self._win_t0, 1e-9)
            qps = self._win_completed / dt
            self._win_t0, self._win_completed = now, 0
            lat = sorted(self._latencies_ms)
            pct = lambda p: (
                lat[min(int(p * (len(lat) - 1) + 0.5), len(lat) - 1)] if lat else None
            )
            counters = dict(self._counters)
            completed = self._completed
            out = {
                "fleet_serve/requests": completed,
                "fleet_serve/qps": qps,
                "fleet_serve/p50_ms": pct(0.50),
                "fleet_serve/p99_ms": pct(0.99),
                "fleet_serve/slo_ms": self.slo_ms,
            }
        for name in ("hedges", "hedge_wins", "shed", "failed", "drains"):
            out[f"fleet_serve/{name}"] = counters.get(name, 0)
        # the burn family under the fleet prefix: the ROUTER's own
        # client-observed burn — the chaos leg's acceptance gauge
        for k, v in self.burn.payload().items():
            out["fleet_serve/" + k.split("/", 1)[1]] = v
        return out


class FleetRouter:
    """The fleet front door (module docstring). `replica_urls` lists
    the replica base URLs; alternatively pass a started
    `ReplicaSupervisor` and the URLs are taken from it (and drain can
    restart replicas). `port=0` binds ephemeral; `self.port` is real.
    """

    def __init__(
        self,
        replica_urls=None,
        supervisor=None,
        host: str = "127.0.0.1",
        port: int = 0,
        slo_ms: float = 1000.0,
        slo_objective: float = 0.99,
        burn_windows=DEFAULT_WINDOWS,
        sink=None,
        metrics_flush_s: float = 1.0,
        health_interval_s: float = 0.5,
        health_timeout_s: float = 2.0,
        replica_timeout_s: float = 30.0,
        retry_attempts: int = 3,
        retry_base_delay_s: float = 0.05,
        retry_max_delay_s: float = 1.0,
        hedge: bool = True,
        hedge_min_ms: float = 250.0,
        hedge_p99_factor: float = 1.0,
        max_inflight: int = 64,
        shed_retry_after_s: float = 1.0,
        breaker_fail_threshold: int = 3,
        breaker_cooldown_s: float = 2.0,
        breaker_cooldown_cap_s: float = 30.0,
        drain_timeout_s: float = 60.0,
        readmit_timeout_s: float = 300.0,
    ):
        if replica_urls is None:
            if supervisor is None:
                raise ValueError("need replica_urls or a supervisor")
            replica_urls = supervisor.urls()
        if not replica_urls:
            raise ValueError("a fleet needs at least one replica")
        self._supervisor = supervisor
        self.health_interval_s = float(health_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.replica_timeout_s = float(replica_timeout_s)
        self.retry_attempts = int(retry_attempts)
        self.retry_base_delay_s = float(retry_base_delay_s)
        self.retry_max_delay_s = float(retry_max_delay_s)
        self.hedge = bool(hedge)
        self.hedge_min_ms = float(hedge_min_ms)
        self.hedge_p99_factor = float(hedge_p99_factor)
        self.max_inflight = int(max_inflight)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.readmit_timeout_s = float(readmit_timeout_s)
        self.metrics = RouterMetrics(
            slo_ms, objective=slo_objective, windows=burn_windows
        )
        self._sink = sink
        # ONE lock for all fleet state (handles + breakers + the
        # admission counter): no per-replica locks, no order to invert
        self._fleet_lock = tsan.make_lock("router.fleet")
        self._replicas = [
            ReplicaHandle(
                i,
                url,
                CircuitBreaker(
                    fail_threshold=breaker_fail_threshold,
                    cooldown_s=breaker_cooldown_s,
                    cooldown_cap_s=breaker_cooldown_cap_s,
                ),
            )
            for i, url in enumerate(replica_urls)
        ]
        self._active = 0  # router-wide in-flight count (shed budget)
        # dispatch pool: primary + hedge attempts run here so the
        # handler thread can time out the primary without abandoning it
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2 * self.max_inflight + 4,
            thread_name_prefix="router_dispatch",
        )
        self._stop = threading.Event()
        self._drain_q: queue.Queue = queue.Queue()
        # one synchronous poll before serving: dispatch works from the
        # first request instead of waiting out a poller interval
        self._poll_health()
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                record_route("GET", path)
                if path == "/healthz":
                    with server._fleet_lock:
                        healthy = sum(1 for r in server._replicas if r.admitted)
                        total = len(server._replicas)
                    self._json(200, {
                        "ok": healthy > 0,
                        "replicas": total,
                        "replicas_healthy": healthy,
                    })
                elif path == "/stats":
                    self._json(200, server.stats())
                elif path == "/admin/replicas":
                    with server._fleet_lock:
                        snaps = [r.snapshot() for r in server._replicas]
                    self._json(200, {"replicas": snaps})
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                t0 = time.perf_counter()
                path, _, query = self.path.partition("?")
                record_route("POST", path)
                if path == "/admin/drain":
                    self._handle_admin_drain(query)
                    return
                if path == "/admin/undrain":
                    self._handle_admin_undrain(query)
                    return
                if path not in ("/embed", "/neighbors"):
                    self.send_error(404)
                    return
                body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
                headers = {}
                shape = self.headers.get("X-Image-Shape")
                if shape:
                    headers["X-Image-Shape"] = shape
                if not server._admit():
                    # load shedding: a counted 503 + Retry-After, never
                    # a silent drop (and it burns error budget)
                    server.metrics.count("shed")
                    server.metrics.record_failure()
                    self._json(
                        503,
                        {"error": "router at max_inflight budget", "shed": True},
                        extra_headers={
                            "Retry-After": str(
                                max(1, round(server.shed_retry_after_s))
                            )
                        },
                    )
                    return
                try:
                    status, payload, rep_index = retry_mod.retry_call(
                        server._attempt_hedged,
                        self.path,
                        body,
                        headers,
                        site="router." + path.strip("/"),
                        attempts=server.retry_attempts,
                        base_delay=server.retry_base_delay_s,
                        max_delay=server.retry_max_delay_s,
                        retry_on=(ReplicaAttemptError, ReplicaUnavailableError),
                    )
                except OSError as e:
                    # retries exhausted across the fleet: loud 503
                    server.metrics.count("failed")
                    server.metrics.record_failure()
                    self._json(
                        503,
                        {"error": f"fleet dispatch failed: {e}"},
                        extra_headers={"Retry-After": "1"},
                    )
                    return
                finally:
                    server._release()
                server.metrics.record_request(
                    time.perf_counter() - t0, ok=status == 200
                )
                if isinstance(payload, dict):
                    # replica attribution next to the replica-scoped
                    # request_id (r<i>-<seq>) the replica minted
                    payload.setdefault("replica", rep_index)
                self._json(status, payload)

            def _handle_admin_drain(self, query):
                idx = _parse_replica(query, len(server._replicas))
                if idx is None:
                    self._json(400, {"error": "need replica=<index>"})
                    return
                restart = _query_flag(query, "restart", default=None)
                started = server.drain_replica(idx, restart=restart)
                with server._fleet_lock:
                    snap = server._replicas[idx].snapshot()
                self._json(202, {"accepted": started, "replica": snap})

            def _handle_admin_undrain(self, query):
                idx = _parse_replica(query, len(server._replicas))
                if idx is None:
                    self._json(400, {"error": "need replica=<index>"})
                    return
                server.undrain_replica(idx)
                with server._fleet_lock:
                    snap = server._replicas[idx].snapshot()
                self._json(200, {"replica": snap})

            def _json(self, code, obj, extra_headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr lines
                pass

        from moco_tpu.serve.server import _QuietHTTPServer

        self._server = _QuietHTTPServer((host, int(port)), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="router_http", daemon=True
        )
        self._thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="router_health", daemon=True
        )
        self._health_thread.start()
        self._drainer = threading.Thread(
            target=self._drain_loop, name="router_drain", daemon=True
        )
        self._drainer.start()
        self._flusher = threading.Thread(
            target=self._flush_loop, args=(float(metrics_flush_s),),
            name="router_metrics_flush", daemon=True,
        )
        self._flusher.start()

    # -- dispatch ---------------------------------------------------------

    def _admit(self) -> bool:
        with self._fleet_lock:
            if self._active >= self.max_inflight:
                return False
            self._active += 1
            return True

    def _release(self) -> None:
        with self._fleet_lock:
            self._active -= 1

    def _acquire(self, exclude=()) -> Optional[ReplicaHandle]:
        """Claim a replica for one attempt: admitted (healthy, not
        draining), breaker willing, fewest in-flight first. Books the
        in-flight/dispatch counters under the fleet lock."""
        with self._fleet_lock:
            cands = sorted(
                (
                    r for r in self._replicas
                    if r.admitted and r.index not in exclude
                ),
                key=lambda r: (r.inflight, r.dispatched, r.index),
            )
            # a breaker due for its half-open probe takes the request
            # first: recovery needs live traffic, a failed probe is
            # retried on a closed replica anyway, and try_acquire gates
            # this to one probe per cooldown — an OPEN breaker inside
            # its cooldown says no and the request flows to the closed
            # replicas below
            for r in cands:
                if r.breaker.state != BREAKER_CLOSED and r.breaker.try_acquire():
                    r.inflight += 1
                    r.dispatched += 1
                    return r
            for r in cands:
                if r.breaker.state == BREAKER_CLOSED and r.breaker.try_acquire():
                    r.inflight += 1
                    r.dispatched += 1
                    return r
        return None

    def _finish(self, rep: ReplicaHandle, ok: bool) -> None:
        with self._fleet_lock:
            rep.inflight = max(0, rep.inflight - 1)
            if ok:
                rep.breaker.record_success()
            else:
                rep.breaker.record_failure()

    def _try_replica(self, rep: ReplicaHandle, path_q: str, body: bytes, headers: dict):
        """One attempt against one replica (runs on the dispatch pool;
        no locks held across the network I/O). Returns (status, payload,
        replica_index); raises ReplicaAttemptError on anything worth
        re-routing."""
        req = urllib.request.Request(rep.url + path_q, data=body, headers=dict(headers))
        try:
            with urllib.request.urlopen(req, timeout=self.replica_timeout_s) as resp:
                payload = json.loads(resp.read())
                status = resp.status
        except urllib.error.HTTPError as e:
            if 400 <= e.code < 500:
                # the replica is alive and judged the request itself: a
                # client error passes through un-retried (breaker success)
                try:
                    payload = json.loads(e.read())
                except ValueError:
                    payload = {"error": f"replica {rep.index}: HTTP {e.code}"}
                self._finish(rep, ok=True)
                return e.code, payload, rep.index
            self._finish(rep, ok=False)
            raise ReplicaAttemptError(f"replica {rep.index}: HTTP {e.code}") from e
        except (OSError, TimeoutError) as e:  # URLError/socket resets/timeouts
            self._finish(rep, ok=False)
            raise ReplicaAttemptError(f"replica {rep.index}: {e!r}") from e
        except ValueError as e:  # torn/garbled response body
            self._finish(rep, ok=False)
            raise ReplicaAttemptError(
                f"replica {rep.index}: bad response ({e!r})"
            ) from e
        self._finish(rep, ok=True)
        return status, payload, rep.index

    def _hedge_delay_s(self) -> Optional[float]:
        if not self.hedge:
            return None
        p99 = self.metrics.p99_ms()
        ms = max(self.hedge_min_ms, (p99 or 0.0) * self.hedge_p99_factor)
        return ms / 1e3

    def _attempt_hedged(self, path_q: str, body: bytes, headers: dict):
        """One retry-round: dispatch to the best replica; if it outlives
        the hedge delay, duplicate to a second one and take the first
        success (first-winner — the loser's response is discarded when
        it lands; urlopen cannot be cancelled mid-flight). Raises an
        OSError subclass when the round produced no success, which is
        what the retry layer backs off on."""
        rep = self._acquire()
        if rep is None:
            raise ReplicaUnavailableError("no admitted replica to dispatch to")
        primary = self._pool.submit(self._try_replica, rep, path_q, body, headers)
        delay = self._hedge_delay_s()
        if delay is None:
            return primary.result()
        try:
            return primary.result(timeout=delay)
        except concurrent.futures.TimeoutError:
            pass  # primary is slow, not failed: hedge it
        second = self._acquire(exclude=(rep.index,))
        attempts = [primary]
        if second is not None:
            self.metrics.count("hedges")
            attempts.append(
                self._pool.submit(self._try_replica, second, path_q, body, headers)
            )
        pending = set(attempts)
        errors = []
        while pending:
            done, pending = concurrent.futures.wait(
                pending, return_when=concurrent.futures.FIRST_COMPLETED
            )
            for fut in done:
                err = fut.exception()
                if err is None:
                    if len(attempts) == 2 and fut is attempts[1]:
                        self.metrics.count("hedge_wins")
                    return fut.result()
                errors.append(err)
        raise ReplicaUnavailableError(
            "all attempts failed this round: "
            + "; ".join(repr(e) for e in errors)
        )

    # -- health -----------------------------------------------------------

    def _probe(self, url: str):
        """(ok, warm, stats) for one replica — network I/O, call with
        no locks held."""
        try:
            with urllib.request.urlopen(
                url + "/healthz", timeout=self.health_timeout_s
            ) as r:
                h = json.loads(r.read())
        except (OSError, ValueError):
            return False, False, None
        stats = None
        try:
            with urllib.request.urlopen(
                url + "/stats", timeout=self.health_timeout_s
            ) as r:
                stats = json.loads(r.read())
        except (OSError, ValueError):
            pass
        return bool(h.get("ok")), bool(h.get("warm")), stats

    def _poll_health(self) -> None:
        with self._fleet_lock:
            targets = [(r.index, r.url) for r in self._replicas]
        for index, url in targets:
            ok, warm, stats = self._probe(url)
            with self._fleet_lock:
                rep = self._replicas[index]
                if rep.url != url:
                    continue  # replica moved mid-poll; drop the stale probe
                rep.healthy = ok
                rep.warm = warm
                if stats is not None:
                    rep.stats = stats

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            self._poll_health()

    # -- drain ------------------------------------------------------------

    def drain_replica(self, index: int, restart: Optional[bool] = None) -> bool:
        """Stop dispatching to replica `index`, wait out its in-flight
        requests, then (default, when a supervisor is attached) restart
        it and re-admit on healthy. Asynchronous: returns immediately
        (False = already draining); poll `/admin/replicas` for phase."""
        if restart is None:
            restart = self._supervisor is not None
        with self._fleet_lock:
            rep = self._replicas[index]
            if rep.draining:
                return False
            rep.draining = True
            rep.drain_phase = "waiting_inflight"
        self.metrics.count("drains")
        self._drain_q.put((index, bool(restart)))
        return True

    def undrain_replica(self, index: int) -> None:
        with self._fleet_lock:
            rep = self._replicas[index]
            rep.draining = False
            rep.drain_phase = None
            rep.breaker.reset()

    def _set_phase(self, rep: ReplicaHandle, phase: Optional[str]) -> None:
        with self._fleet_lock:
            rep.drain_phase = phase

    def _drain_loop(self) -> None:
        """The single drain worker: serializes drain/restart jobs (one
        replica leaves the fleet at a time — a fleet-wide drain storm
        cannot empty the rotation)."""
        while not self._stop.is_set():
            try:
                index, restart = self._drain_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._do_drain(index, restart)
            except Exception as e:  # a failed drain must not kill the worker
                print(f"router: drain of replica {index} failed: {e!r}", flush=True)
                self._set_phase(self._replicas[index], "drain_failed")

    def _do_drain(self, index: int, restart: bool) -> None:
        rep = self._replicas[index]
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            with self._fleet_lock:
                inflight = rep.inflight
            if inflight == 0:
                break
            time.sleep(0.05)
        if restart and self._supervisor is not None:
            self._set_phase(rep, "restarting")
            self._supervisor.restart_replica(index)
            self._set_phase(rep, "readmitting")
            deadline = time.monotonic() + self.readmit_timeout_s
            ok = False
            while time.monotonic() < deadline and not self._stop.is_set():
                ok, warm, stats = self._probe(rep.url)
                if ok:
                    break
                time.sleep(0.2)
            with self._fleet_lock:
                rep.healthy = ok
                rep.draining = False
                rep.drain_phase = None if ok else "readmit_timeout"
                rep.breaker.reset()
        else:
            # no restart: drain the replica's own batcher (flushes every
            # accepted request) and park it out of rotation
            try:
                req = urllib.request.Request(
                    rep.url + f"/admin/drain?timeout={self.drain_timeout_s:.1f}",
                    data=b"",
                )
                with urllib.request.urlopen(req, timeout=self.drain_timeout_s + 10):
                    pass
            except (OSError, ValueError) as e:
                print(
                    f"router: replica {index} /admin/drain failed: {e!r}", flush=True
                )
            self._set_phase(rep, "drained")

    # -- metrics ----------------------------------------------------------

    def stats(self) -> dict:
        """The `fleet_serve/*` gauge line: the router's own burn/latency
        family plus fleet topology, per-replica dispatch counts, and the
        per-replica burn gauges aggregated min/mean/max (the obs/fleet.py
        pattern). Snapshots fleet state first, THEN takes the metrics
        lock inside payload() — the two locks never nest."""
        with self._fleet_lock:
            snaps = [r.snapshot() for r in self._replicas]
            replica_stats = [dict(r.stats) for r in self._replicas]
            active = self._active
        out = self.metrics.payload()
        out["fleet_serve/replicas"] = len(snaps)
        out["fleet_serve/replicas_healthy"] = sum(
            1 for s in snaps if s["healthy"] and not s["draining"]
        )
        out["fleet_serve/inflight"] = active
        out["fleet_serve/breaker_open"] = sum(
            1 for s in snaps if s["breaker"] == BREAKER_OPEN
        )
        out["fleet_serve/breaker_trips"] = sum(s["breaker_trips"] for s in snaps)
        for s in snaps:
            out[f"fleet_serve/dispatch_{s['index']}"] = s["dispatched"]
        burn_keys = set()
        for st in replica_stats:
            burn_keys |= {k for k in st if k.startswith("serve/burn_rate_")}
        for k in sorted(burn_keys):
            vals = [
                st[k] for st in replica_stats if st.get(k) is not None
            ]
            base = "fleet_serve/" + k.split("/", 1)[1]
            out[base + "_min"] = min(vals) if vals else None
            out[base + "_mean"] = sum(vals) / len(vals) if vals else None
            out[base + "_max"] = max(vals) if vals else None
        router_retries = {
            k: v
            for k, v in retry_mod.snapshot().items()
            if k.startswith("router.")
        }
        out["fleet_serve/retries"] = sum(router_retries.values())
        if router_retries:
            out["io_retries"] = router_retries
        return out

    def _flush_loop(self, interval: float) -> None:
        step = 0
        while not self._stop.wait(interval):
            step += 1
            self._write_metrics(step)
        self._write_metrics(step + 1)  # the run's last gauges land too

    def _write_metrics(self, step: int) -> None:
        try:
            payload = self.stats()
            if self._sink is not None:
                self._sink.write(step, payload)
        except Exception as e:  # metrics must never take the router down
            print(f"WARNING: router metrics sink failed: {e!r}", flush=True)

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        """Stop the poller/flusher/drain worker, shut HTTP, join all
        four threads, and retire the dispatch pool (JX011 discipline)."""
        self._stop.set()
        self._health_thread.join(timeout=10.0)
        self._flusher.join(timeout=10.0)
        self._drainer.join(timeout=self.drain_timeout_s + 30.0)
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10.0)
        self._pool.shutdown(wait=True, cancel_futures=True)


def _query_param(query: str, name: str) -> Optional[str]:
    for part in query.split("&"):
        if part.startswith(name + "="):
            return part[len(name) + 1 :] or None
    return None


def _parse_replica(query: str, num_replicas: int) -> Optional[int]:
    val = _query_param(query, "replica")
    if val is None:
        return None
    try:
        idx = int(val)
    except ValueError:
        return None
    if not 0 <= idx < num_replicas:
        return None
    return idx


def _query_flag(query: str, name: str, default=None):
    val = _query_param(query, name)
    if val is None:
        return default
    return val not in ("0", "false", "no")


__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "CircuitBreaker",
    "FleetRouter",
    "ReplicaAttemptError",
    "ReplicaHandle",
    "ReplicaUnavailableError",
    "RouterMetrics",
]
