"""AOT-compiled embedding inference over the exported encoder.

Training compiles one step shape and amortizes it over an epoch;
serving sees arbitrary request sizes, and a `jax.jit` that traces per
shape would recompile on live traffic — exactly the
recompile-after-warmup class mocolint's JX004 and the runtime
`RecompileGuard` exist to abort. The engine therefore compiles *ahead
of time*: one executable per padded batch bucket
(`jit(...).lower(shapes).compile()`, default buckets {1, 8, 32, 128}),
requests pad up to the next bucket, and after :meth:`mark_warm` any
shape that would need a fresh trace raises :class:`EngineRecompileError`
instead of silently compiling. `recompiles_after_warmup` is the gauge
the serve smoke asserts at zero across mixed request sizes.

Graph: uint8 images → /255 → per-channel normalize (the eval recipe
`knn.extract_features` uses) → module forward in bf16 (the serving
default — inference tolerates bf16 activations; params stay f32) →
f32 cast → L2-normalize. `engine_quant` selects the quantization tier
at this same seam (`int8=True` is the back-compat spelling of "w8"):

- **w8** — weight-only PTQ: the encoder's matmul/conv kernels are
  stored int8 (symmetric per-output-channel,
  :func:`quantize_params_int8`) and dequantized inside each bucket's
  executable; matmuls still run f32. ~4x at-rest param memory.
- **w8a8** — activation-quantized int8 end-to-end (serve/quant.py):
  a calibration artifact (per-tensor activation ranges from a held-out
  sample run through the f32 encoder at this exact preprocessing seam)
  supplies symmetric input scales, and every plain conv/dense runs
  int8×int8→int32 (`preferred_element_type=jnp.int32`) with one f32
  rescale at the layer boundary. True int8 kernels are tpu/gpu-only;
  CPU runs the bit-faithful scaled-integer emulation (quant.py module
  docstring — the bf16 story again), so cosine/recall are testable on
  the CPU smoke while the arithmetic factor is an accelerator claim.

All quantized trees (int8 params, weight scales, activation scales)
are passed as call ARGUMENTS to the per-bucket executables, never
closure constants — XLA would constant-fold `int8 · scale` straight
back into f32 constants and silently undo the at-rest saving. The
module is whatever representation the
deployment serves: the FULL encoder (backbone + projection head, the
`load_serving_encoder` default) embeds into the negative queue's space
so the index can hold the trained dictionary, while a bare backbone
serves kNN-style features. Input buffers are donated on backends with
donation support and the donation is *audited*: :meth:`donation_audit`
verifies post-hoc that each bucket's input buffer was actually consumed
(deleted) by its call, so a silent donation regression (e.g. a wrapper
holding a reference) shows up as a boolean, not a slow leak. On the
quantized tiers the audit extends to the quantized parameter trees:
the donated input must still be consumed per bucket exactly as on the
f32 path, while the int8 param/scale trees — reused by every later
call — must SURVIVE it (`qtree:<bucket>` audit entries; an accidental
donation there would be a use-after-free on the next request, and
serve_smoke fails loudly on any False entry).

Encoder side: the *key* (EMA) encoder by default — serving wants the
slow-moving stable representation ("How to Scale Your EMA",
arXiv:2307.13813), while probes/export keep the query side. The loader
reuses `lincls.load_pretrained_backbone` (side="k"), so ZeRO-2/3
checkpoints unshard through the same one-shot host gather as every
other eval tool.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import time

from moco_tpu.obs.trace import span as obs_span
from moco_tpu.ops.losses import l2_normalize
from moco_tpu.utils import faults

DEFAULT_BUCKETS = (1, 8, 32, 128)


class EngineRecompileError(RuntimeError):
    """A batch shape arrived after warmup that has no AOT executable —
    the serving mirror of analysis/runtime.py's RecompileError."""


def quantize_params_int8(params):
    """Weight-only int8 PTQ of the encoder's matmul/conv kernels:
    symmetric per-output-channel scales (`s = max|w| / 127` over all
    but the last axis) on every floating leaf with ndim >= 2; biases,
    scalars, and BN stats pass through untouched. Returns
    (int8_tree, scale_tree) sharing the params treedef — unquantized
    leaves ride along with a scalar scale of 1 so the two trees always
    zip. Dequantization happens *inside* the jitted forward with the
    quantized tree passed as a call ARGUMENT, not a closure constant:
    XLA constant-folds a baked `int8_const * scale` straight back into
    an f32 constant, which would silently undo the ~4x at-rest saving
    the PTQ exists for."""
    flat, treedef = jax.tree_util.tree_flatten(params)
    q_flat, s_flat = [], []
    for leaf in flat:
        leaf = jnp.asarray(leaf)
        if leaf.ndim >= 2 and jnp.issubdtype(leaf.dtype, jnp.floating):
            axes = tuple(range(leaf.ndim - 1))
            s = jnp.max(jnp.abs(leaf).astype(jnp.float32), axis=axes, keepdims=True) / 127.0
            s = jnp.where(s <= 0, jnp.float32(1.0), s)
            q_flat.append(
                jnp.clip(jnp.round(leaf.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
            )
            s_flat.append(s)
        else:
            q_flat.append(leaf)
            s_flat.append(jnp.ones((), jnp.float32))
    return (
        jax.tree_util.tree_unflatten(treedef, q_flat),
        jax.tree_util.tree_unflatten(treedef, s_flat),
    )


def dequantize_params(qparams, scales):
    """The in-graph inverse of `quantize_params_int8` (int8 leaves
    rescale to f32; pass-through leaves come back untouched)."""
    return jax.tree_util.tree_map(
        lambda w, s: w.astype(jnp.float32) * s if w.dtype == jnp.int8 else w,
        qparams,
        scales,
    )


def load_serving_encoder(
    workdir: str, config=None, side: str = "k"
) -> tuple[Any, Any, Any, np.ndarray, int, Any]:
    """(encoder_module, params, batch_stats, queue, queue_ptr, config)
    for serving from a pretraining checkpoint — the key (EMA) side by
    default, and the FULL encoder (backbone + projection head): serving
    embeds into the same space the negative queue lives in, so the
    checkpoint's dictionary rows load straight into an EmbeddingIndex
    (`EmbeddingIndex.from_train_queue`) and `/neighbors` is literally
    the training look-up as a product. On accelerator backends the
    encoder is rebuilt in bf16 regardless of the training compute dtype
    (the serving default; params stay f32); CPU keeps f32 — XLA:CPU
    *emulates* bf16 at a measured ~50x slowdown, which would poison the
    CPU smoke and the bench serving leg. ZeRO-2/3 checkpoints unshard
    through `lincls.restore_pretrain_state`, the shared eval-side
    path."""
    from moco_tpu.core.moco import build_encoder
    from moco_tpu.lincls import restore_pretrain_state

    if side not in ("q", "k"):
        raise ValueError(f"side must be 'q' or 'k', got {side!r}")
    state, config = restore_pretrain_state(workdir, config, unshard=(side,))
    serve_dtype = (
        "bfloat16" if jax.default_backend() in ("tpu", "gpu") else "float32"
    )
    encoder = build_encoder(dataclasses.replace(config.moco, compute_dtype=serve_dtype))
    params = state.params_k if side == "k" else state.params_q
    stats = state.batch_stats_k if side == "k" else state.batch_stats_q
    return (
        encoder,
        jax.device_get(params),
        jax.device_get(stats),
        np.asarray(state.queue),
        int(state.queue_ptr),
        config,
    )


class InferenceEngine:
    """Bucketed AOT inference: `embed` (and `embed_and_query` against an
    `EmbeddingIndex`) over uint8 image batches of any size ≤ the largest
    bucket × chunking (module docstring).

    `mesh=None` runs single-device (the serving replica unit — scale-out
    is N processes behind a balancer, not one sharded forward; the
    *index* shards instead, see serve/index.py).
    """

    def __init__(
        self,
        module,
        params: Any,
        batch_stats: Any,
        image_size: int,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        donate: Optional[bool] = None,
        int8: bool = False,
        engine_quant: Optional[str] = None,
        calibration: Optional[dict] = None,
        calib_sample: Optional[np.ndarray] = None,
        int8_compute: Optional[bool] = None,
    ):
        from moco_tpu.serve import quant as quant_mod

        if not buckets or sorted(set(int(b) for b in buckets)) != sorted(
            int(b) for b in buckets
        ):
            raise ValueError(f"buckets must be unique and non-empty, got {buckets}")
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.image_size = int(image_size)
        self.num_features = getattr(module, "num_features", None)
        if donate is None:
            # CPU lacks donation support (jit would only warn and keep the
            # buffer) — same backend gate as make_train_step's donate_nums
            donate = jax.default_backend() in ("tpu", "gpu")
        self.donate = bool(donate)
        # tier resolution: engine_quant wins; int8=True is the PR-9
        # spelling of "w8" (kept so existing callers/tests read the same)
        if engine_quant is None:
            engine_quant = "w8" if int8 else "off"
        if engine_quant not in quant_mod.QUANT_MODES:
            raise ValueError(
                f"engine_quant must be one of {quant_mod.QUANT_MODES}, "
                f"got {engine_quant!r}"
            )
        self.quant = engine_quant
        self.int8 = engine_quant != "off"  # back-compat gauge (serve/int8)
        self._variables = {"params": params, "batch_stats": batch_stats}
        self._qparams = self._qscales = None
        self._act_scales = None
        self.calibration: Optional[dict] = None
        # true int8 kernels only where the backend has them (quant.py
        # docstring: XLA:CPU emulates, measured ~45x — the bf16 story)
        self.int8_compute = (
            quant_mod.default_int8_compute() if int8_compute is None else bool(int8_compute)
        )

        from moco_tpu.data.augment import get_recipe, normalize

        recipe = get_recipe(False, self.image_size)

        if self.quant != "off":
            # PTQ slots into the same per-bucket AOT seam: the forward
            # takes the quantized trees as ARGUMENTS (quantize_params_int8
            # docstring explains why a closure constant would constant-fold
            # the saving away) and dequantizes in-graph before apply
            self._qparams, self._qscales = quantize_params_int8(params)
            self._qparams = jax.device_put(self._qparams)
            self._qscales = jax.device_put(self._qscales)

        if self.quant == "w8a8":
            # calibration: an explicit artifact wins; else fit one from
            # the held-out sample at this exact preprocessing seam
            if calibration is None:
                if calib_sample is None:
                    raise ValueError(
                        "engine_quant='w8a8' needs a calibration artifact "
                        "(calibration=...) or a held-out sample (calib_sample=...)"
                    )
                calibration = quant_mod.calibrate_encoder(
                    module, params, batch_stats, calib_sample, self.image_size
                )
            quant_mod.validate_calibration(calibration, params, self.image_size)
            self.calibration = calibration
            self._act_scales = jax.device_put(
                quant_mod.activation_scales(calibration)
            )
            int8_compute_flag = self.int8_compute

            def forward(raw, qparams, qscales, act_scales):  # (b,H,W,C) uint8
                x = raw.astype(jnp.float32) / 255.0
                x = normalize(x, recipe.mean, recipe.std)
                feats = quant_mod.quantized_apply(
                    module, qparams, qscales, batch_stats, act_scales, x,
                    int8_compute=int8_compute_flag,
                )
                return l2_normalize(feats.astype(jnp.float32))

        elif self.quant == "w8":

            def forward(raw, qparams, qscales):  # (b, H, W, C) uint8
                x = raw.astype(jnp.float32) / 255.0
                x = normalize(x, recipe.mean, recipe.std)
                variables = {
                    "params": dequantize_params(qparams, qscales),
                    "batch_stats": batch_stats,
                }
                feats = module.apply(variables, x, train=False)
                return l2_normalize(feats.astype(jnp.float32))

        else:

            def forward(raw):  # (b, H, W, C) uint8
                x = raw.astype(jnp.float32) / 255.0
                x = normalize(x, recipe.mean, recipe.std)
                feats = module.apply(self._variables, x, train=False)
                return l2_normalize(feats.astype(jnp.float32))

        self._forward = forward
        self._compiled: dict[int, object] = {}
        self._frozen = False
        self.aot_compiles = 0
        self._warm_compiles: Optional[int] = None
        self._donation_audit: dict = {}
        for b in self.buckets:
            self._compile(b)

    def _quant_args(self) -> tuple:
        """The quantized trees each executable takes as arguments —
        () / (qparams, qscales) / (qparams, qscales, act_scales)."""
        if self.quant == "w8a8":
            return (self._qparams, self._qscales, self._act_scales)
        if self.quant == "w8":
            return (self._qparams, self._qscales)
        return ()

    # -- compilation -----------------------------------------------------

    def _compile(self, bucket: int):
        if self._frozen:
            raise EngineRecompileError(
                f"batch bucket {bucket} has no AOT executable and the engine "
                "is warm — pad requests to a compiled bucket "
                f"{self.buckets} instead of tracing on live traffic"
            )
        jitted = jax.jit(
            self._forward, donate_argnums=(0,) if self.donate else ()
        )
        shape = jax.ShapeDtypeStruct(
            (bucket, self.image_size, self.image_size, 3), jnp.uint8
        )
        args = (shape,) + self._quant_args()
        with obs_span("serve_aot_compile", bucket=bucket, quant=self.quant):
            compiled = jitted.lower(*args).compile()
        self.aot_compiles += 1
        self._compiled[bucket] = compiled
        return compiled

    def warmup(self) -> None:
        """Execute every bucket once (primes allocator/layout work the
        compile alone doesn't) and freeze: from here on an uncompiled
        shape raises instead of tracing. Blocks until the warmup work
        actually ran — otherwise the async dispatches queue up and the
        FIRST real request pays for all of them (observed: ~20s of
        deferred bucket executions landing on one request)."""
        for b in self.buckets:
            out = self._run_bucket(
                np.zeros((b, self.image_size, self.image_size, 3), np.uint8)
            )
            out.block_until_ready()
        self.mark_warm()

    def mark_warm(self) -> None:
        self._frozen = True
        self._warm_compiles = self.aot_compiles

    @property
    def recompiles_after_warmup(self) -> int:
        if self._warm_compiles is None:
            return 0
        return self.aot_compiles - self._warm_compiles

    def donation_audit(self) -> dict:
        """Per-bucket: True = the donated input buffer was consumed by
        the call (deleted — donation is real), False = donation was
        requested but the buffer survived (a reference leak would
        double peak memory per request), None = donation disabled
        (backend without support). Populated lazily as buckets run.

        Quantized tiers add `"qtree:<bucket>"` entries auditing the
        quantized parameter trees (int8 params + scales + activation
        scales): True = every tree buffer SURVIVED the call (they are
        reused by every later request; an accidental donation would be
        a use-after-free on the next one), False = some buffer was
        consumed. serve_smoke fails loudly on any False in the map."""
        return dict(self._donation_audit)

    # -- execution -------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest compiled bucket holding n rows (n ≤ max bucket)."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"batch of {n} exceeds the largest bucket {self.buckets[-1]}")

    def _run_bucket(self, padded: np.ndarray) -> jax.Array:
        """One compiled call on an exactly-bucket-shaped uint8 batch."""
        # deterministic tail injection (slow@site=serve.engine_execute):
        # the sleep lands inside the engine_execute stage's stamped
        # interval, so the flight recorder attributes it correctly
        faults.maybe_slow("serve.engine_execute")
        bucket = padded.shape[0]
        compiled = self._compiled.get(bucket)
        if compiled is None:
            compiled = self._compile(bucket)
        staged = jax.device_put(jnp.asarray(padded, jnp.uint8))
        quant_args = self._quant_args()
        out = compiled(staged, *quant_args)
        if bucket not in self._donation_audit:
            if self.donate:
                out.block_until_ready()
                self._donation_audit[bucket] = bool(staged.is_deleted())
            else:
                self._donation_audit[bucket] = None
            if quant_args:
                # the quantized trees are call arguments on EVERY bucket
                # execution — they must all survive (donation_audit
                # docstring); checked once per bucket like the input
                out.block_until_ready()
                self._donation_audit[f"qtree:{bucket}"] = not any(
                    getattr(leaf, "is_deleted", lambda: False)()
                    for leaf in jax.tree_util.tree_leaves(quant_args)
                )
        return out

    def _padded_chunks(self, images: np.ndarray):
        """Yield (padded_uint8, valid_rows, bucket): chunk at the
        largest bucket, pad each chunk with zero rows to its bucket."""
        images = np.asarray(images, np.uint8)
        if images.ndim != 4 or images.shape[1:] != (self.image_size, self.image_size, 3):
            raise ValueError(
                f"expected (n, {self.image_size}, {self.image_size}, 3) uint8, "
                f"got {images.shape}"
            )
        max_b = self.buckets[-1]
        for start in range(0, images.shape[0], max_b):
            chunk = images[start : start + max_b]
            bucket = self.bucket_for(chunk.shape[0])
            padded = chunk
            if bucket != chunk.shape[0]:
                padded = np.zeros((bucket,) + chunk.shape[1:], np.uint8)
                padded[: chunk.shape[0]] = chunk
            yield padded, chunk.shape[0], bucket

    def embed(
        self, images: np.ndarray, stages: Optional[dict] = None
    ) -> tuple[np.ndarray, list[Tuple[int, int]]]:
        """L2-normalized (n, num_features) f32 embeddings of an
        (n, H, W, C) uint8 batch, plus the executed (bucket, valid_rows)
        pairs for occupancy accounting. Oversized batches chunk at the
        largest bucket; padding rows are zeros and their outputs are
        sliced away before anything downstream sees them. `stages` (the
        request-trace contract) accumulates per-stage seconds; timing a
        stage forces device readiness inside its window, so the split is
        honest under async dispatch — that sync is the tracing cost the
        bench reports as `serve/trace_overhead_pct`."""
        outs, executed = [], []
        for padded, n, bucket in self._padded_chunks(images):
            with obs_span("serve_embed", bucket=bucket, valid=n):
                if stages is None:
                    feats = self._run_bucket(padded)
                else:
                    t0 = time.perf_counter()
                    feats = self._run_bucket(padded)
                    feats.block_until_ready()
                    stages["engine_execute"] = (
                        stages.get("engine_execute", 0.0) + time.perf_counter() - t0
                    )
            outs.append(np.asarray(feats)[:n])
            executed.append((bucket, n))
        return np.concatenate(outs), executed

    def embed_and_query(
        self, images: np.ndarray, index, k: int, stages: Optional[dict] = None
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, list[Tuple[int, int]]]:
        """(embeddings, scores, indices, executed) — the `/neighbors`
        path against the exact tier. The index query runs on the PADDED
        bucket rows (the same shapes `index.prepare(self.buckets, k)`
        AOT-compiled), so mixed request sizes never trace; padding rows'
        neighbors are sliced away with their embeddings."""
        emb, per_mode, executed = self.embed_and_query_modes(
            images, index, k, stages=stages
        )
        scores, idx = per_mode["exact"]
        return emb, scores, idx, executed

    def embed_and_query_modes(
        self,
        images: np.ndarray,
        index,
        k: int,
        modes: Sequence[str] = ("exact",),
        nprobe: Optional[int] = None,
        stages: Optional[dict] = None,
    ) -> tuple[np.ndarray, dict, list[Tuple[int, int]]]:
        """(embeddings, {mode: (scores, indices)}, executed): one encoder
        forward per padded chunk, then one index query PER REQUESTED TIER
        on the same device features — how the server answers a micro-batch
        mixing `?mode=ivf` and `?mode=exact` riders, and how the sampled
        recall estimator gets its IVF/oracle pair from a single forward.
        Every (mode, bucket, k, nprobe) must be prepared once frozen.
        `stages` splits engine_execute/index_query seconds for the
        request-trace waterfall (see `embed` on the forced readiness)."""
        outs, executed = [], []
        per_mode: dict = {mode: ([], []) for mode in modes}
        for padded, n, bucket in self._padded_chunks(images):
            with obs_span("serve_embed", bucket=bucket, valid=n):
                if stages is None:
                    feats = self._run_bucket(padded)  # (bucket, d) on device
                else:
                    t0 = time.perf_counter()
                    feats = self._run_bucket(padded)
                    feats.block_until_ready()
                    stages["engine_execute"] = (
                        stages.get("engine_execute", 0.0) + time.perf_counter() - t0
                    )
            for mode in modes:
                with obs_span("serve_query", bucket=bucket, k=k, mode=mode):
                    if stages is None:
                        scores, idx = index.query(feats, k, mode=mode, nprobe=nprobe)
                    else:
                        t0 = time.perf_counter()
                        scores, idx = index.query(feats, k, mode=mode, nprobe=nprobe)
                        jax.block_until_ready((scores, idx))
                        stages["index_query"] = (
                            stages.get("index_query", 0.0) + time.perf_counter() - t0
                        )
                per_mode[mode][0].append(scores[:n])
                per_mode[mode][1].append(idx[:n])
            outs.append(np.asarray(feats)[:n])
            executed.append((bucket, n))
        return (
            np.concatenate(outs),
            {m: (np.concatenate(s), np.concatenate(i)) for m, (s, i) in per_mode.items()},
            executed,
        )


__all__ = [
    "DEFAULT_BUCKETS",
    "EngineRecompileError",
    "InferenceEngine",
    "dequantize_params",
    "load_serving_encoder",
    "quantize_params_int8",
]
