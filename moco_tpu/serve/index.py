"""The embedding index — MoCo's dictionary, factored out of the queue.

MoCo's framing is "contrastive learning as dictionary look-up"
(arXiv:1911.05722): training scores queries against a FIFO dictionary
of key embeddings, and serving scores user queries against the same
kind of store. Until this module those two look-ups were separate
implementations — `core/queue.py` owned the FIFO write, `knn.py` owned
its own cosine top-k scan, and nothing served either. Both now rehost
on the two kernels here:

- :func:`fifo_write` — the FIFO block write (`dynamic_update_slice` at
  `ptr`, no wrap because callers keep K % block == 0). `core/queue.py`'s
  `enqueue` delegates here bit-for-bit, so the train-time queue IS the
  train-time instance of the index (the equivalence test in
  tests/test_serve.py pins this).
- :func:`topk_cosine` — the top-k cosine scan (one matmul + lax.top_k,
  optional valid-row mask). `knn.py`'s classifier and the serving
  `/neighbors` endpoint both call it.

:class:`EmbeddingIndex` wraps the kernels into the serving-side store:
rows live on device — optionally P(data)-sharded over a mesh, so the
scan's (m, K) matmul shards its contraction over the data axis exactly
like the model-sharded queue shards InfoNCE logits — with FIFO and
snapshot ingest, and an AOT-compiled query per padded query bucket so
serving traffic can never trigger a recompile (mocolint JX004 /
RecompileGuard discipline; serve/engine.py's bucket set is reused).

Two query tiers, one freeze() contract:

- **exact** (`topk_cosine`): brute-force top-k over every valid row —
  one (m, K) matmul. O(K) per query; below ~10^7 rows it is one small
  matmul next to the encoder forward, and it stays the correctness
  ORACLE for the approximate tier (the online recall estimator and the
  recall property tests both score IVF against it).
- **IVF** (`train_ivf` + `mode="ivf"`): an inverted-file structure.
  A jitted spherical k-means (:func:`kmeans_fit`, Lloyd iterations on
  device) partitions rows into `nlist` cells around L2-normalized
  centroids; a query scores the `nprobe` nearest centroids (one
  (m, nlist) matmul) and scans ONLY those cells. TPU-natively the cells
  are *dense padded* id lists — a static (nlist, cell_cap) int32 table,
  padded slots holding the sentinel id `capacity` — so the probe scan
  is a static-shape gather of (m, nprobe·cell_cap) candidate rows plus
  one batched matmul, and the executable is AOT-bucketed per
  (m, k, nprobe) exactly like the exact scan. Cost per query drops from
  O(K) to O(nprobe·K/nlist): the sub-linear unlock for the 10^7-row
  dictionaries the north star implies. Cell membership follows FIFO
  ingest incrementally (evicted rows swap-removed, fresh rows assigned
  to their nearest — or second-nearest, when full — cell), so a
  streaming replica never rebuilds.

An **int8 scoring path** (`enable_int8`) layers on both tiers:
symmetric per-row quantization (`q = round(127·x / max|x|)`, one f32
scale per row) of the stored rows, queries quantized the same way
in-graph, scores accumulated in int8→int32 and rescaled to f32 — ~4×
less score-stage memory traffic, bounded error (the recall tests pin
int8 recall and rescale error against the f32 oracle).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.ops.losses import l2_normalize
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.utils import faults

DEFAULT_KMEANS_ITERS = 10
# modes query()/prepare() understand; "*_i8" score in int8 (enable_int8),
# "ivf_fused*" run the fused gather-scan (no materialized candidate
# gather — _ivf_topk_fused) instead of the composed three-hop scan
QUERY_MODES = ("exact", "ivf", "exact_i8", "ivf_i8", "ivf_fused", "ivf_fused_i8")


def fifo_write(
    rows: jax.Array, ptr: jax.Array, values: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """FIFO block write of `values` (N, dim) at `ptr`; returns
    (rows, new_ptr). The write never wraps — callers maintain
    K % N == 0 (the reference queue invariant, `moco/builder.py:~L70`),
    so one `dynamic_update_slice` suffices. Bit-identical to the
    pre-refactor `core/queue.enqueue` body, which now delegates here."""
    num_rows = rows.shape[0]
    values = jax.lax.stop_gradient(values).astype(rows.dtype)
    rows = jax.lax.dynamic_update_slice(rows, values, (ptr, jnp.zeros_like(ptr)))
    new_ptr = (ptr + values.shape[0]) % num_rows
    return rows, new_ptr


def topk_cosine(
    queries: jax.Array,  # (m, dim) L2-normalized
    rows: jax.Array,  # (K, dim) L2-normalized
    k: int,
    valid_count: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k cosine scores + row indices of `queries` against `rows`.

    One (m, K) matmul + `lax.top_k` — the shared scan `knn.knn_classify`
    and the serving `/neighbors` path both rehost on. `valid_count`
    (dynamic scalar) masks rows at index >= count to -inf so a
    partially-filled index never surfaces uninitialized rows; passing it
    as a traced value means fill level changes never recompile."""
    sims = queries @ rows.T  # cosine: inputs are L2-normalized
    if valid_count is not None:
        invalid = jnp.arange(rows.shape[0]) >= valid_count
        sims = jnp.where(invalid[None, :], -jnp.inf, sims)
    return jax.lax.top_k(sims, k)


# -- IVF kernels ----------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("nlist", "iters"))
def kmeans_fit(rows: jax.Array, nlist: int, iters: int = DEFAULT_KMEANS_ITERS):
    """Spherical k-means on L2-normalized `rows` (n, d): `iters` Lloyd
    iterations entirely on device, returning (nlist, d) L2-normalized
    centroids. Deterministic strided init (every n//nlist-th row), so
    the coarse quantizer is reproducible without threading a PRNG key.
    Empty cells keep their previous centroid (the standard Lloyd
    degenerate-cell fix). All shapes static: one executable per
    (n, d, nlist, iters)."""
    n = rows.shape[0]
    if nlist > n:
        raise ValueError(f"nlist={nlist} exceeds the {n} training rows")
    stride = max(n // nlist, 1)
    init = l2_normalize(jax.lax.slice(rows, (0, 0), (stride * nlist, rows.shape[1]), (stride, 1)))

    def body(_, cent):
        sims = rows @ cent.T  # (n, nlist)
        onehot = jax.nn.one_hot(jnp.argmax(sims, axis=1), nlist, dtype=rows.dtype)
        sums = onehot.T @ rows  # (nlist, d) — the segment-sum as one matmul
        counts = jnp.sum(onehot, axis=0)[:, None]
        cent = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), cent)
        return l2_normalize(cent)

    return jax.lax.fori_loop(0, iters, body, init)


@jax.jit
def _assign_top2(rows: jax.Array, centroids: jax.Array):
    """(first, second) nearest-centroid ids per row — the second choice
    is the overflow fallback when a dense padded cell is already full.
    Two argmax passes, NOT `lax.top_k(sims, 2)`: top_k sorts the whole
    (n, nlist) score matrix, which measured ~6x slower than the matmul
    itself on XLA:CPU and dominated the 2^20-row build."""
    sims = rows @ centroids.T
    first = jnp.argmax(sims, axis=1).astype(jnp.int32)
    masked = jnp.where(
        jnp.arange(sims.shape[1])[None, :] == first[:, None], -jnp.inf, sims
    )
    return first, jnp.argmax(masked, axis=1).astype(jnp.int32)


@jax.jit
def _quantize_rows_int8(x: jax.Array):
    """Symmetric per-row int8: q = round(127·x / max|x|), one f32 scale
    per row (zero rows get scale 1 so padding stays exactly zero)."""
    s = jnp.max(jnp.abs(x), axis=-1).astype(jnp.float32) / 127.0
    s = jnp.where(s <= 0, jnp.float32(1.0), s)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s[..., None]), -127, 127)
    return q.astype(jnp.int8), s


def _ivf_topk(
    queries,  # (m, d) f32 L2-normalized
    rows,  # (K, d) f32 — or (K, d) int8 when row_scale is given
    centroids,  # (nlist, d) f32
    cell_ids,  # (nlist, cell_cap) int32, sentinel id == K on padded slots
    valid_count,  # traced scalar: rows at id >= valid are masked
    k: int,
    nprobe: int,
    row_scale=None,  # (K,) f32 per-row dequant scales (int8 path)
):
    """The IVF probe scan, all shapes static per (m, k, nprobe):
    coarse (m, nlist) matmul → top-nprobe cells per query → ONE dense
    gather of the probed cells' candidate ids (m, nprobe·cell_cap) →
    candidate row gather + one batched matmul → top-k over candidates,
    mapped back to global row ids. Padded slots carry the sentinel id
    (== capacity), which the valid mask sends to -inf, so partial cells
    and partial fills never surface junk rows and never recompile."""
    m = queries.shape[0]
    num_rows = rows.shape[0]
    coarse = queries @ centroids.T  # (m, nlist)
    _, probes = jax.lax.top_k(coarse, nprobe)  # (m, nprobe)
    cand_ids = cell_ids[probes].reshape(m, -1)  # (m, nprobe*cell_cap)
    safe = jnp.minimum(cand_ids, num_rows - 1)
    cand = rows[safe]  # (m, L, d) dense padded-cell gather
    if row_scale is None:
        sims = jax.lax.dot_general(
            queries, cand, (((1,), (2,)), ((0,), (0,)))
        )  # (m, L): one small matmul per probe batch
    else:
        q8, qs = _quantize_rows_int8(queries)
        acc = jax.lax.dot_general(
            q8, cand, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.int32,
        )
        sims = acc.astype(jnp.float32) * qs[:, None] * row_scale[safe]
    sims = jnp.where(cand_ids >= valid_count, -jnp.inf, sims)
    scores, local = jax.lax.top_k(sims, k)
    return scores, jnp.take_along_axis(cand_ids, local, axis=1)


def _ivf_topk_fused(
    queries,  # (m, d) f32 L2-normalized
    rows,  # (K, d) f32 — or (K, d) int8 when row_scale is given
    centroids,  # (nlist, d) f32
    cell_ids,  # (nlist, cell_cap) int32, sentinel id == K on padded slots
    valid_count,  # traced scalar: rows at id >= valid are masked
    k: int,
    nprobe: int,
    row_scale=None,  # (K,) f32 per-row dequant scales (int8 path)
):
    """The fused IVF gather-scan: one kernel instead of the composed
    centroid-score → cell-gather → score → top-k hops. A hand-tiled
    `lax.fori_loop` over the nprobe probed cells scores ONE dense padded
    cell per query in place each step and folds it into a running top-k
    (concat the k carried best with the cell's cell_cap scores, re-top-k)
    — the composed path's (m, nprobe·cell_cap, d) candidate gather never
    materializes; peak live candidate memory drops nprobe-fold to
    (m, cell_cap, d). On the 1-core CPU smoke that cache residency is
    worth ~3.7x queries/s at identical results; on TPU the same shape
    maps onto the Pallas variant (`_fused_cell_scores_pallas`, one
    scalar-prefetched cell DMA per grid step). Results: the exact same
    candidate set as `_ivf_topk` (top_k probes are distinct, each row
    lives in one cell — no duplicates), so on ties-free data the top-k
    ids are identical and the scores allclose (the oracle test pins
    both). -inf-scored tail slots (k exceeding the valid candidates)
    carry the sentinel id `K` where the composed scan surfaces an
    arbitrary masked row — neither is a valid neighbor."""
    m = queries.shape[0]
    num_rows = rows.shape[0]
    coarse = queries @ centroids.T  # (m, nlist): the only dense hop kept
    _, probes = jax.lax.top_k(coarse, nprobe)  # (m, nprobe)
    if row_scale is not None:
        q8, qs = _quantize_rows_int8(queries)

    def body(j, carry):
        best_s, best_i = carry
        cell_j = jax.lax.dynamic_slice_in_dim(probes, j, 1, axis=1)[:, 0]  # (m,)
        ids = cell_ids[cell_j]  # (m, cell_cap): this step's cells only
        safe = jnp.minimum(ids, num_rows - 1)
        cand = rows[safe]  # (m, cell_cap, d) — the whole live gather
        if row_scale is None:
            sims = jax.lax.dot_general(
                queries, cand, (((1,), (2,)), ((0,), (0,)))
            )  # (m, cell_cap) scored in place
        else:
            acc = jax.lax.dot_general(
                q8, cand, (((1,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.int32,
            )
            sims = acc.astype(jnp.float32) * qs[:, None] * row_scale[safe]
        sims = jnp.where(ids >= valid_count, -jnp.inf, sims)
        merged_s = jnp.concatenate([best_s, sims], axis=1)
        merged_i = jnp.concatenate([best_i, ids], axis=1)
        s, loc = jax.lax.top_k(merged_s, k)  # running top-k, O(k + cell_cap)
        return s, jnp.take_along_axis(merged_i, loc, axis=1)

    init = (
        jnp.full((m, k), -jnp.inf, jnp.float32),
        jnp.full((m, k), num_rows, jnp.int32),
    )
    return jax.lax.fori_loop(0, nprobe, body, init)


def _fused_cell_scores_kernel(probes_ref, q_ref, cell_rows_ref, out_ref):
    """Pallas body for one (query, probe) grid step: the BlockSpec index
    map already DMA'd this query's j-th probed cell (scalar-prefetched
    `probes` pick the block), so the kernel is a single (1, d) ×
    (cell_cap, d)^T dot — the cell is scored straight out of its DMA
    tile, and the (m, nprobe·cell_cap, d) gather never exists in HBM."""
    out_ref[0] = jax.lax.dot_general(
        q_ref[...],
        cell_rows_ref[0],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _fused_cell_scores_pallas(queries, cell_rows, probes, interpret=False):
    """(m, nprobe, cell_cap) candidate scores via a Pallas grid over
    (query, probe): `cell_rows` is the cell-major (nlist, cell_cap, d)
    row layout (built lazily per IVF epoch, like the device cell table)
    and `probes` rides the scalar-prefetch channel so each grid step's
    BlockSpec selects the right cell tile to DMA. Real chips only
    (capability probe `_pallas_fused_default`); `interpret=True` runs
    the same kernel on CPU for the equivalence tests."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, d = queries.shape
    nlist, cell_cap, _ = cell_rows.shape
    nprobe = probes.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m, nprobe),
        in_specs=[
            pl.BlockSpec((1, d), lambda i, j, p: (i, 0)),
            pl.BlockSpec((1, cell_cap, d), lambda i, j, p: (p[i, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, cell_cap), lambda i, j, p: (i, j, 0)),
    )
    return pl.pallas_call(
        _fused_cell_scores_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, nprobe, cell_cap), jnp.float32),
        interpret=interpret,
    )(probes, queries.astype(jnp.float32), cell_rows)


def _ivf_topk_fused_pallas(
    queries,
    rows,
    centroids,
    cell_ids,
    cell_rows,  # (nlist, cell_cap, d) cell-major row copy (f32)
    valid_count,
    k: int,
    nprobe: int,
    interpret: bool = False,
):
    """Fused scan with the cell scoring in Pallas: coarse matmul →
    top-nprobe probes → `_fused_cell_scores_pallas` (per-cell DMA +
    dot, no candidate-row gather) → mask + one top-k over the scores.
    Same candidate set and mask as `_ivf_topk`, so ids/scores match the
    composed oracle on ties-free data. `rows` is unused (the cell-major
    copy carries the vectors) but kept in the signature so query()'s
    argument plumbing stays uniform across fused variants."""
    del rows
    m = queries.shape[0]
    coarse = queries @ centroids.T
    _, probes = jax.lax.top_k(coarse, nprobe)
    sims = _fused_cell_scores_pallas(queries, cell_rows, probes, interpret=interpret)
    sims = sims.reshape(m, -1)  # (m, nprobe*cell_cap) — scores, not rows
    cand_ids = cell_ids[probes].reshape(m, -1)
    sims = jnp.where(cand_ids >= valid_count, -jnp.inf, sims)
    scores, local = jax.lax.top_k(sims, k)
    return scores, jnp.take_along_axis(cand_ids, local, axis=1)


def _exact_topk_int8(queries, rows_i8, row_scale, valid_count, k: int):
    """The exact scan's int8 twin: per-row quantized queries against the
    per-row quantized store, int32 accumulation, f32 rescale — same
    mask/top-k contract as `topk_cosine`."""
    q8, qs = _quantize_rows_int8(queries)
    acc = jax.lax.dot_general(
        q8, rows_i8, (((1,), (1,)), ((), ())), preferred_element_type=jnp.int32
    )
    sims = acc.astype(jnp.float32) * qs[:, None] * row_scale[None, :]
    invalid = jnp.arange(rows_i8.shape[0]) >= valid_count
    sims = jnp.where(invalid[None, :], -jnp.inf, sims)
    return jax.lax.top_k(sims, k)


def _pallas_fused_default() -> tuple[bool, bool]:
    """(use_pallas, interpret) for the fused scan: the Pallas cell-DMA
    kernel runs on real TPUs by default (the capability probe is the
    backend itself — Mosaic has no CPU lowering); `MOCO_IVF_PALLAS`
    overrides: `0` forces the portable lax fori_loop variant on a chip,
    `1` forces Pallas, `interpret` runs the kernel in interpret mode on
    any backend (the CPU equivalence tests)."""
    env = os.environ.get("MOCO_IVF_PALLAS", "").strip().lower()
    if env in ("0", "off", "false"):
        return False, False
    if env == "interpret":
        return True, True
    if env in ("1", "on", "true"):
        return True, False
    return jax.default_backend() == "tpu", False


class IndexRecompileError(RuntimeError):
    """A query shape arrived that was not AOT-compiled at prepare()
    time — serving must pad to a prepared bucket, never trace anew."""


class EmbeddingIndex:
    """Device-resident embedding store with FIFO/snapshot ingest and
    AOT-bucketed top-k cosine queries — exact, IVF approximate, and
    int8 variants of both (module docstring).

    `mesh` shards the rows P(data, None) — capacity is padded up to a
    multiple of the data-axis width so the shard is rectangular; padded
    rows sit above `count` and are masked out of every query. Without a
    mesh the rows live replicated on the default device.
    """

    def __init__(
        self,
        capacity: int,
        dim: int,
        mesh=None,
        dtype=jnp.float32,
    ):
        if capacity < 1:
            raise ValueError(f"index capacity must be >= 1, got {capacity}")
        self.dim = int(dim)
        self.mesh = mesh
        self._n_data = mesh.shape[DATA_AXIS] if mesh is not None else 1
        # rectangular shard: pad capacity up to a multiple of the axis
        self.capacity = -(-int(capacity) // self._n_data) * self._n_data
        self.requested_capacity = int(capacity)
        self.count = 0  # valid rows (host-side; queries read a device copy)
        self._ptr = 0  # FIFO write head (host-side mirror)
        # wall-clock ingest stamps (freshness SLO): one host-side float
        # per row slot, NaN = never written. The training queue_age
        # gauge is STEP-denominated; serving staleness must be wall
        # seconds — `row_age_stats()` reads these, the serve flusher
        # feeds them to the FreshnessBurnTracker.
        self._row_time = np.full(self.capacity, np.nan, np.float64)
        self._row_sharding = None
        self._rep_sharding = None
        self._scale_sharding = None
        rows = jnp.zeros((self.capacity, self.dim), dtype)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._row_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
            self._rep_sharding = NamedSharding(mesh, P())
            self._scale_sharding = NamedSharding(mesh, P(DATA_AXIS))
            rows = jax.device_put(rows, self._row_sharding)
        self.rows = rows
        self._compiled: dict[tuple, object] = {}
        self._ingest_jits: dict[tuple, object] = {}
        self._frozen = False
        self.aot_compiles = 0
        self._warm_compiles: Optional[int] = None
        # int8 scoring state (enable_int8): per-row quantized rows + scales
        self._rows_i8: Optional[jax.Array] = None
        self._row_scale: Optional[jax.Array] = None
        # IVF state (train_ivf): device arrays + host mirrors for
        # incremental FIFO maintenance
        self._ivf: Optional[dict] = None
        # fused-scan lowering: Pallas cell-DMA kernel on real chips,
        # hand-tiled lax fori_loop everywhere else (_pallas_fused_default)
        self._fused_pallas, self._fused_interpret = _pallas_fused_default()

    # -- ingest ----------------------------------------------------------

    def snapshot(
        self, embeddings: np.ndarray, normalized: bool = True,
        now: Optional[float] = None,
    ) -> None:
        """Bulk (re)load: replace the store's contents with `embeddings`
        (n <= capacity rows) — the "load the trained dictionary" path
        (e.g. a checkpoint's queue). Resets the FIFO head. Invalidates a
        trained IVF structure (cell membership is content-derived —
        retrain with `train_ivf` after a bulk reload); the int8 mirror
        is requantized in place. Every loaded row is ingest-stamped at
        `now` (wall clock by default; injectable for tests)."""
        embs = np.asarray(embeddings)
        n = embs.shape[0]
        if n > self.capacity or embs.shape[1] != self.dim:
            raise ValueError(
                f"snapshot shape {embs.shape} exceeds index ({self.capacity}, {self.dim})"
            )
        if not normalized:
            embs = np.asarray(l2_normalize(jnp.asarray(embs)))
        full = np.zeros((self.capacity, self.dim), self.rows.dtype)
        full[:n] = embs
        rows = jnp.asarray(full)
        if self._row_sharding is not None:
            rows = jax.device_put(rows, self._row_sharding)
        self.rows = rows
        self.count = n
        self._ptr = n % self.capacity
        self._row_time[:] = np.nan
        self._row_time[:n] = time.time() if now is None else now
        self._ivf = None  # content replaced wholesale: cells are stale
        if self._rows_i8 is not None:
            self._requantize_all()

    def _fifo_jit(self, n: int):
        """Donated jitted FIFO write for an n-row block: the update runs
        in place on device, the P(data) sharding (when meshed) is pinned
        by in/out shardings, and NO host round-trip or re-shard happens
        — the pre-IVF `add()` rebuilt rows via host `device_put` every
        block. `ptr` is traced, so the write head never recompiles."""
        key = ("fifo", n)
        fn = self._ingest_jits.get(key)
        if fn is None:
            donate = (0,) if jax.default_backend() in ("tpu", "gpu") else ()
            kwargs = {}
            if self._row_sharding is not None:
                kwargs = dict(
                    in_shardings=(self._row_sharding, self._rep_sharding, self._rep_sharding),
                    out_shardings=(self._row_sharding, self._rep_sharding),
                )
            fn = jax.jit(fifo_write, donate_argnums=donate, **kwargs)
            self._ingest_jits[key] = fn
        return fn

    def _int8_write_jit(self, n: int):
        key = ("int8", n)
        fn = self._ingest_jits.get(key)
        if fn is None:

            def write(rows_i8, scale, values, ptr):
                q, s = _quantize_rows_int8(values)
                rows_i8 = jax.lax.dynamic_update_slice(
                    rows_i8, q, (ptr, jnp.zeros_like(ptr))
                )
                scale = jax.lax.dynamic_update_slice(scale, s, (ptr,))
                return rows_i8, scale

            donate = (0, 1) if jax.default_backend() in ("tpu", "gpu") else ()
            kwargs = {}
            if self._row_sharding is not None:
                kwargs = dict(
                    in_shardings=(
                        self._row_sharding, self._scale_sharding,
                        self._rep_sharding, self._rep_sharding,
                    ),
                    out_shardings=(self._row_sharding, self._scale_sharding),
                )
            fn = jax.jit(write, donate_argnums=donate, **kwargs)
            self._ingest_jits[key] = fn
        return fn

    def _write_block(self, values: jax.Array, ptr: int) -> None:
        """One no-wrap block write at `ptr` through the donated jitted
        updates (rows, then the int8 mirror when enabled)."""
        p = jnp.int32(ptr)
        self.rows, _ = self._fifo_jit(values.shape[0])(self.rows, p, values)
        if self._rows_i8 is not None:
            self._rows_i8, self._row_scale = self._int8_write_jit(values.shape[0])(
                self._rows_i8, self._row_scale, values.astype(jnp.float32), p
            )

    def add(self, embeddings: np.ndarray, now: Optional[float] = None) -> None:
        """FIFO ingest of an (N, dim) block at the write head — the
        serving-side mirror of the training enqueue. A block crossing
        the capacity boundary splits into two no-wrap writes (training
        keeps its K % N == 0 invariant and never takes the split). The
        write is a donated jitted device update that keeps the P(data)
        sharding in place; the int8 mirror and IVF cell membership (when
        enabled/trained) follow incrementally. Overwritten slots get a
        fresh ingest stamp at `now` — FIFO eviction is what keeps the
        freshness SLO honest (the oldest stamp leaves with its row)."""
        embs = jnp.asarray(embeddings, self.rows.dtype)
        n = embs.shape[0]
        if n == 0:
            return
        if n > self.capacity:
            raise ValueError(
                f"FIFO block of {n} rows exceeds capacity {self.capacity}; "
                "use snapshot() for bulk loads"
            )
        start = self._ptr
        head = min(n, self.capacity - start)
        written = [(start, embs[:head])]
        if head < n:
            written.append((0, embs[head:]))
        overwritten = np.concatenate(
            [np.arange(p, p + b.shape[0]) for p, b in written]
        )
        for p, block in written:
            self._write_block(block, p)
        if self._ivf is not None:
            self._ivf_reassign(overwritten, np.asarray(embs, np.float32))
        self._row_time[overwritten] = time.time() if now is None else now
        self._ptr = (self._ptr + n) % self.capacity
        self.count = min(self.count + n, self.capacity)

    @classmethod
    def from_train_queue(
        cls, queue: jax.Array, queue_ptr=0, count: Optional[int] = None, mesh=None
    ) -> "EmbeddingIndex":
        """The train-time queue as an index: wrap a checkpoint's
        (K, dim) queue rows (already L2-normalized by `init_queue`/
        `enqueue`). `count=None` treats every row as valid — after
        warmup the training queue is always full."""
        rows = np.asarray(queue)
        idx = cls(rows.shape[0], rows.shape[1], mesh=mesh, dtype=rows.dtype)
        idx.snapshot(rows)
        idx.count = rows.shape[0] if count is None else int(count)
        idx._ptr = int(queue_ptr)
        return idx

    def row_age_stats(self, now: Optional[float] = None) -> dict:
        """Wall-clock staleness of the valid rows: max/mean seconds
        since each row's ingest stamp. `{"row_age_max_s": None, ...}`
        while no stamped rows exist (empty index). The serve flusher
        exports these as `serve/row_age_max_s`/`serve/row_age_mean_s`
        and feeds the max to the freshness burn tracker; `now` is
        injectable so the burn math is unit-testable."""
        now = time.time() if now is None else now
        stamps = self._row_time[: self.count]
        valid = stamps[np.isfinite(stamps)]
        if valid.size == 0:
            return {"row_age_max_s": None, "row_age_mean_s": None}
        ages = np.maximum(now - valid, 0.0)
        return {
            "row_age_max_s": float(ages.max()),
            "row_age_mean_s": float(ages.mean()),
        }

    # -- int8 scoring path ----------------------------------------------

    def enable_int8(self) -> None:
        """Build the symmetric per-row int8 mirror of the store. From
        here on `exact_i8`/`ivf_i8` modes are available and every FIFO
        write keeps the mirror fresh (quantized on device, in the same
        donated update)."""
        if self._rows_i8 is None:
            self._requantize_all()

    @property
    def int8_enabled(self) -> bool:
        return self._rows_i8 is not None

    def _requantize_all(self) -> None:
        q, s = _quantize_rows_int8(self.rows.astype(jnp.float32))
        if self._row_sharding is not None:
            q = jax.device_put(q, self._row_sharding)
            s = jax.device_put(s, self._scale_sharding)
        self._rows_i8, self._row_scale = q, s

    # -- IVF build + maintenance -----------------------------------------

    def train_ivf(
        self,
        nlist: Optional[int] = None,
        iters: int = DEFAULT_KMEANS_ITERS,
        cell_cap: Optional[int] = None,
        sample_rows: int = 65536,
        nprobe: Optional[int] = None,
        assign_chunk: int = 65536,
    ) -> dict:
        """Fit the coarse quantizer and build the inverted file over the
        current contents. k-means runs on device (`kmeans_fit`) over a
        strided sample of ≤ `sample_rows` valid rows (the standard IVF
        train/add split: Lloyd cost is O(sample·nlist·d), not O(K)),
        then every valid row is assigned to its nearest centroid in
        `assign_chunk` blocks. Cells are DENSE PADDED id lists of width
        `cell_cap` (default 2× the balanced fill, so mild imbalance
        never spills): a row whose first-choice cell is full falls to
        its second choice; only a doubly-full row is left out of the IVF
        (still served by the exact tier — `ivf_stats()['spilled']`
        counts them and the recall gate catches pathological skew).
        `nprobe` sets the default probe width for `mode="ivf"` queries.
        Returns `ivf_stats()`."""
        if self.count < 2:
            raise ValueError("train_ivf needs at least 2 valid rows")
        if nlist is None:
            nlist = max(2, int(np.sqrt(self.count)))
        valid = np.asarray(self.rows[: self.count].astype(jnp.float32))
        stride = max(self.count // int(sample_rows), 1)
        sample = jnp.asarray(valid[::stride][: int(sample_rows)])
        # top-2 fallback assignment needs >= 2 cells; the sample bounds
        # the fit, so nlist can never exceed it
        nlist = int(max(2, min(nlist, sample.shape[0])))
        centroids = kmeans_fit(sample, nlist=nlist, iters=int(iters))
        if cell_cap is None:
            cell_cap = max(2 * -(-self.count // nlist), 8)
        cell_cap = int(min(cell_cap, self.capacity))
        # chunked top-2 assignment of every valid row (one executable:
        # the tail chunk is zero-padded up to assign_chunk)
        first = np.empty(self.count, np.int32)
        second = np.empty(self.count, np.int32)
        chunk = int(min(assign_chunk, self.count))
        for lo in range(0, self.count, chunk):
            block = valid[lo : lo + chunk]
            pad = chunk - block.shape[0]
            if pad:
                block = np.concatenate([block, np.zeros((pad, self.dim), np.float32)])
            a1, a2 = _assign_top2(jnp.asarray(block), centroids)
            first[lo : lo + chunk - pad] = np.asarray(a1)[: chunk - pad]
            second[lo : lo + chunk - pad] = np.asarray(a2)[: chunk - pad]
        # host build of the dense padded cells (vectorized first choice,
        # loop only over the overflow tail)
        cells = np.full((nlist, cell_cap), self.capacity, np.int32)
        counts = np.zeros(nlist, np.int32)
        row_cell = np.full(self.capacity, -1, np.int32)
        row_slot = np.full(self.capacity, -1, np.int32)
        order = np.argsort(first, kind="stable")
        sorted_cells = first[order]
        starts = np.searchsorted(sorted_cells, np.arange(nlist), side="left")
        pos = np.arange(self.count) - starts[sorted_cells]
        ok = pos < cell_cap
        cells[sorted_cells[ok], pos[ok]] = order[ok]
        row_cell[order[ok]] = sorted_cells[ok]
        row_slot[order[ok]] = pos[ok]
        np.add.at(counts, sorted_cells[ok], 1)
        spilled = 0
        for rid in order[~ok]:  # overflow: second-choice fallback
            c2 = second[rid]
            if counts[c2] < cell_cap:
                cells[c2, counts[c2]] = rid
                row_cell[rid], row_slot[rid] = c2, counts[c2]
                counts[c2] += 1
            else:
                spilled += 1
        self._ivf = {
            "nlist": nlist,
            "cell_cap": cell_cap,
            "nprobe": int(nprobe) if nprobe else max(1, nlist // 16),
            "centroids": centroids,
            "cells_dev": None,  # lazily pushed (dirty)
            "cell_rows_dev": None,  # cell-major copy (Pallas fused scan)
            "cells": cells,
            "counts": counts,
            "row_cell": row_cell,
            "row_slot": row_slot,
            "spilled": int(spilled),
            "dirty": True,
        }
        return self.ivf_stats()

    def ivf_stats(self) -> dict:
        """Coarse-quantizer health: cell-occupancy spread and spill
        count (rows absent from the IVF, still served exactly)."""
        if self._ivf is None:
            return {"trained": False}
        c = self._ivf["counts"]
        return {
            "trained": True,
            "nlist": self._ivf["nlist"],
            "cell_cap": self._ivf["cell_cap"],
            "nprobe": self._ivf["nprobe"],
            "spilled": self._ivf["spilled"],
            "cell_count_min": int(c.min()),
            "cell_count_mean": float(c.mean()),
            "cell_count_max": int(c.max()),
            # mean cell fill over capacity — with `spilled`, the re-fit
            # trigger the fleet roadmap names (exported as
            # serve/ivf_occupancy + serve/ivf_spill by the server)
            "occupancy": float(c.mean()) / self._ivf["cell_cap"],
        }

    def _ivf_reassign(self, overwritten: np.ndarray, fresh: np.ndarray) -> None:
        """Incremental inverted-file maintenance for one FIFO block:
        swap-remove every overwritten row from its cell, then insert the
        fresh rows at their (first-, else second-) nearest centroid.
        Host-side on the small mirrors; the device table re-uploads
        lazily before the next IVF query."""
        ivf = self._ivf
        cells, counts = ivf["cells"], ivf["counts"]
        row_cell, row_slot = ivf["row_cell"], ivf["row_slot"]
        for rid in overwritten:
            c = row_cell[rid]
            if c < 0:
                continue
            slot, last = row_slot[rid], counts[c] - 1
            mover = cells[c, last]
            cells[c, slot] = mover
            row_slot[mover] = slot
            cells[c, last] = self.capacity
            counts[c] = last
            row_cell[rid] = row_slot[rid] = -1
        a1, a2 = _assign_top2(jnp.asarray(fresh), ivf["centroids"])
        a1, a2 = np.asarray(a1), np.asarray(a2)
        for i, rid in enumerate(overwritten):
            for c in (a1[i], a2[i]):
                if counts[c] < ivf["cell_cap"]:
                    cells[c, counts[c]] = rid
                    row_cell[rid], row_slot[rid] = c, counts[c]
                    counts[c] += 1
                    break
            else:
                ivf["spilled"] += 1
        ivf["dirty"] = True

    def _ivf_device_cells(self) -> jax.Array:
        ivf = self._ivf
        if ivf["dirty"] or ivf["cells_dev"] is None:
            cells = jnp.asarray(ivf["cells"])
            if self._rep_sharding is not None:
                cells = jax.device_put(cells, self._rep_sharding)
            ivf["cells_dev"] = cells
            ivf["cell_rows_dev"] = None  # cell-major copy went stale too
            ivf["dirty"] = False
        return ivf["cells_dev"]

    def _ivf_device_cell_rows(self) -> jax.Array:
        """Cell-major (nlist, cell_cap, d) f32 row copy for the Pallas
        fused scan: each grid step DMAs one cell tile straight from this
        layout instead of gathering candidate rows per query. Built
        lazily per IVF epoch (one gather) like the id table; ~2x the
        row memory at the default 2x cell_cap padding — the canonical
        IVF-on-TPU trade."""
        ivf = self._ivf
        cells = self._ivf_device_cells()
        if ivf.get("cell_rows_dev") is None:
            safe = jnp.minimum(cells, self.capacity - 1)
            cell_rows = self.rows.astype(jnp.float32)[safe]
            if self._rep_sharding is not None:
                cell_rows = jax.device_put(cell_rows, self._rep_sharding)
            ivf["cell_rows_dev"] = cell_rows
        return ivf["cell_rows_dev"]

    # -- query -----------------------------------------------------------

    def _require(self, mode: str, nprobe: Optional[int]) -> int:
        if mode not in QUERY_MODES:
            raise ValueError(f"unknown query mode {mode!r}; one of {QUERY_MODES}")
        if mode.endswith("_i8") and self._rows_i8 is None:
            raise ValueError(f"mode {mode!r} needs enable_int8() first")
        if mode.startswith("ivf"):
            if self._ivf is None:
                raise ValueError(f"mode {mode!r} needs train_ivf() first")
            return int(nprobe or self._ivf["nprobe"])
        return 0

    def _compile(self, m: int, k: int, mode: str = "exact", nprobe: int = 0):
        if self._frozen:
            raise IndexRecompileError(
                f"query shape (mode={mode}, m={m}, k={k}, nprobe={nprobe}) was "
                "not prepared before freeze() — serving must pad to a prepared "
                "bucket (engine bucket set); compiling now would be the "
                "recompile-after-warmup class RecompileGuard aborts on"
            )
        rep = self._rep_sharding
        shard_kw: dict = {}
        q_s = jax.ShapeDtypeStruct((m, self.dim), jnp.float32)
        valid_s = jax.ShapeDtypeStruct((), jnp.int32)
        if mode == "exact":
            fn = lambda q, rows, valid: topk_cosine(q, rows, k, valid_count=valid)
            args = (q_s, jax.ShapeDtypeStruct(self.rows.shape, self.rows.dtype), valid_s)
            if rep is not None:
                shard_kw = dict(
                    in_shardings=(rep, self._row_sharding, rep), out_shardings=rep
                )
        elif mode == "exact_i8":
            fn = lambda q, r8, sc, valid: _exact_topk_int8(q, r8, sc, valid, k)
            args = (
                q_s,
                jax.ShapeDtypeStruct(self._rows_i8.shape, jnp.int8),
                jax.ShapeDtypeStruct(self._row_scale.shape, jnp.float32),
                valid_s,
            )
            if rep is not None:
                shard_kw = dict(
                    in_shardings=(rep, self._row_sharding, self._scale_sharding, rep),
                    out_shardings=rep,
                )
        else:  # ivf / ivf_i8 / ivf_fused / ivf_fused_i8
            ivf = self._ivf
            if k > nprobe * ivf["cell_cap"]:
                raise ValueError(
                    f"k={k} exceeds the candidate pool nprobe*cell_cap="
                    f"{nprobe * ivf['cell_cap']}; raise nprobe"
                )
            cent_s = jax.ShapeDtypeStruct(ivf["centroids"].shape, jnp.float32)
            cells_s = jax.ShapeDtypeStruct((ivf["nlist"], ivf["cell_cap"]), jnp.int32)
            if mode == "ivf_fused" and self._fused_pallas:
                # Pallas lowering: scores come from per-cell DMA tiles
                # out of the cell-major row copy (an extra argument)
                interp = self._fused_interpret
                fn = lambda q, rows, cent, cells, cell_rows, valid: (
                    _ivf_topk_fused_pallas(
                        q, rows, cent, cells, cell_rows, valid,
                        k=k, nprobe=nprobe, interpret=interp,
                    )
                )
                args = (
                    q_s,
                    jax.ShapeDtypeStruct(self.rows.shape, self.rows.dtype),
                    cent_s, cells_s,
                    jax.ShapeDtypeStruct(
                        (ivf["nlist"], ivf["cell_cap"], self.dim), jnp.float32
                    ),
                    valid_s,
                )
                if rep is not None:
                    shard_kw = dict(
                        in_shardings=(rep, self._row_sharding, rep, rep, rep, rep),
                        out_shardings=rep,
                    )
            elif mode in ("ivf", "ivf_fused"):
                kernel = _ivf_topk_fused if mode == "ivf_fused" else _ivf_topk
                fn = lambda q, rows, cent, cells, valid: kernel(
                    q, rows, cent, cells, valid, k=k, nprobe=nprobe
                )
                args = (
                    q_s,
                    jax.ShapeDtypeStruct(self.rows.shape, self.rows.dtype),
                    cent_s, cells_s, valid_s,
                )
                if rep is not None:
                    shard_kw = dict(
                        in_shardings=(rep, self._row_sharding, rep, rep, rep),
                        out_shardings=rep,
                    )
            else:
                kernel = _ivf_topk_fused if mode == "ivf_fused_i8" else _ivf_topk
                fn = lambda q, r8, sc, cent, cells, valid: kernel(
                    q, r8, cent, cells, valid, k=k, nprobe=nprobe, row_scale=sc
                )
                args = (
                    q_s,
                    jax.ShapeDtypeStruct(self._rows_i8.shape, jnp.int8),
                    jax.ShapeDtypeStruct(self._row_scale.shape, jnp.float32),
                    cent_s, cells_s, valid_s,
                )
                if rep is not None:
                    shard_kw = dict(
                        in_shardings=(
                            rep, self._row_sharding, self._scale_sharding, rep, rep, rep,
                        ),
                        out_shardings=rep,
                    )
        compiled = jax.jit(fn, **shard_kw).lower(*args).compile()
        self.aot_compiles += 1
        self._compiled[(mode, m, k, nprobe)] = compiled
        return compiled

    def prepare(
        self,
        buckets: Sequence[int],
        k: int,
        nprobe: Optional[int] = None,
        modes: Sequence[str] = ("exact",),
    ) -> None:
        """AOT-compile the query for every padded bucket shape — one
        executable per (mode, m, k, nprobe); serve traffic then never
        traces. IVF modes need `train_ivf` first (nprobe defaults to the
        trained one), int8 modes `enable_int8`."""
        for mode in modes:
            np_eff = self._require(mode, nprobe)
            for m in buckets:
                if (mode, int(m), int(k), np_eff) not in self._compiled:
                    self._compile(int(m), int(k), mode, np_eff)

    def freeze(self) -> None:
        """End of warmup: any later unprepared shape raises
        IndexRecompileError instead of silently compiling."""
        self._frozen = True
        self._warm_compiles = self.aot_compiles

    @property
    def recompiles_after_warmup(self) -> int:
        if self._warm_compiles is None:
            return 0
        return self.aot_compiles - self._warm_compiles

    def query(
        self,
        queries,
        k: int,
        mode: str = "exact",
        nprobe: Optional[int] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """(scores, indices), each (m, k), of the top-k valid rows per
        query. `m` must be a prepared bucket once frozen; `k` is capped
        by the caller to `count` if exact-rank semantics matter (indices
        past the fill level never appear — their scores are -inf-masked
        and top_k orders them last only when k > count). `mode` selects
        the tier: "exact" (the oracle), "ivf" (sub-linear probe scan,
        `nprobe` cells — defaults to the trained width), "ivf_fused"
        (the same scan as ONE kernel — running top-k over per-cell
        scores, no materialized candidate gather; Pallas cell-DMA
        lowering on real chips), and their int8 twins
        "exact_i8"/"ivf_i8"/"ivf_fused_i8"."""
        # deterministic tail injection for the request-trace waterfall's
        # index_query stage (slow@site=serve.index_query)
        faults.maybe_slow("serve.index_query")
        q = jnp.asarray(queries, jnp.float32)
        m, k = q.shape[0], int(k)
        np_eff = self._require(mode, nprobe)
        compiled = self._compiled.get((mode, m, k, np_eff))
        if compiled is None:
            compiled = self._compile(m, k, mode, np_eff)
        valid = jnp.int32(self.count)
        if mode == "exact":
            scores, idx = compiled(q, self.rows, valid)
        elif mode == "exact_i8":
            scores, idx = compiled(q, self._rows_i8, self._row_scale, valid)
        elif mode == "ivf_fused" and self._fused_pallas:
            scores, idx = compiled(
                q, self.rows, self._ivf["centroids"], self._ivf_device_cells(),
                self._ivf_device_cell_rows(), valid,
            )
        elif mode in ("ivf", "ivf_fused"):
            scores, idx = compiled(
                q, self.rows, self._ivf["centroids"], self._ivf_device_cells(), valid
            )
        else:  # ivf_i8 / ivf_fused_i8
            scores, idx = compiled(
                q, self._rows_i8, self._row_scale,
                self._ivf["centroids"], self._ivf_device_cells(), valid,
            )
        return np.asarray(scores), np.asarray(idx)


__all__ = [
    "DEFAULT_KMEANS_ITERS",
    "EmbeddingIndex",
    "IndexRecompileError",
    "QUERY_MODES",
    "fifo_write",
    "kmeans_fit",
    "topk_cosine",
]

