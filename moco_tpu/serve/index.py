"""The embedding index — MoCo's dictionary, factored out of the queue.

MoCo's framing is "contrastive learning as dictionary look-up"
(arXiv:1911.05722): training scores queries against a FIFO dictionary
of key embeddings, and serving scores user queries against the same
kind of store. Until this module those two look-ups were separate
implementations — `core/queue.py` owned the FIFO write, `knn.py` owned
its own cosine top-k scan, and nothing served either. Both now rehost
on the two kernels here:

- :func:`fifo_write` — the FIFO block write (`dynamic_update_slice` at
  `ptr`, no wrap because callers keep K % block == 0). `core/queue.py`'s
  `enqueue` delegates here bit-for-bit, so the train-time queue IS the
  train-time instance of the index (the equivalence test in
  tests/test_serve.py pins this).
- :func:`topk_cosine` — the top-k cosine scan (one matmul + lax.top_k,
  optional valid-row mask). `knn.py`'s classifier and the serving
  `/neighbors` endpoint both call it.

:class:`EmbeddingIndex` wraps the kernels into the serving-side store:
rows live on device — optionally P(data)-sharded over a mesh, so the
scan's (m, K) matmul shards its contraction over the data axis exactly
like the model-sharded queue shards InfoNCE logits — with FIFO and
snapshot ingest, and an AOT-compiled query per padded query bucket so
serving traffic can never trigger a recompile (mocolint JX004 /
RecompileGuard discipline; serve/engine.py's bucket set is reused).

The scan is exact (brute-force top-k over every valid row), which at
MoCo dictionary sizes (K ≤ 65536, dim ≤ 256) is one small matmul —
far below the engine's encoder forward. Approximate structures only
pay above ~10^7 rows; the class is the seam where one would slot in.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.ops.losses import l2_normalize
from moco_tpu.parallel.mesh import DATA_AXIS


def fifo_write(
    rows: jax.Array, ptr: jax.Array, values: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """FIFO block write of `values` (N, dim) at `ptr`; returns
    (rows, new_ptr). The write never wraps — callers maintain
    K % N == 0 (the reference queue invariant, `moco/builder.py:~L70`),
    so one `dynamic_update_slice` suffices. Bit-identical to the
    pre-refactor `core/queue.enqueue` body, which now delegates here."""
    num_rows = rows.shape[0]
    values = jax.lax.stop_gradient(values).astype(rows.dtype)
    rows = jax.lax.dynamic_update_slice(rows, values, (ptr, jnp.zeros_like(ptr)))
    new_ptr = (ptr + values.shape[0]) % num_rows
    return rows, new_ptr


def topk_cosine(
    queries: jax.Array,  # (m, dim) L2-normalized
    rows: jax.Array,  # (K, dim) L2-normalized
    k: int,
    valid_count: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Top-k cosine scores + row indices of `queries` against `rows`.

    One (m, K) matmul + `lax.top_k` — the shared scan `knn.knn_classify`
    and the serving `/neighbors` path both rehost on. `valid_count`
    (dynamic scalar) masks rows at index >= count to -inf so a
    partially-filled index never surfaces uninitialized rows; passing it
    as a traced value means fill level changes never recompile."""
    sims = queries @ rows.T  # cosine: inputs are L2-normalized
    if valid_count is not None:
        invalid = jnp.arange(rows.shape[0]) >= valid_count
        sims = jnp.where(invalid[None, :], -jnp.inf, sims)
    return jax.lax.top_k(sims, k)


class IndexRecompileError(RuntimeError):
    """A query shape arrived that was not AOT-compiled at prepare()
    time — serving must pad to a prepared bucket, never trace anew."""


class EmbeddingIndex:
    """Device-resident embedding store with FIFO/snapshot ingest and an
    AOT-bucketed exact top-k cosine query (module docstring).

    `mesh` shards the rows P(data, None) — capacity is padded up to a
    multiple of the data-axis width so the shard is rectangular; padded
    rows sit above `count` and are masked out of every query. Without a
    mesh the rows live replicated on the default device.
    """

    def __init__(
        self,
        capacity: int,
        dim: int,
        mesh=None,
        dtype=jnp.float32,
    ):
        if capacity < 1:
            raise ValueError(f"index capacity must be >= 1, got {capacity}")
        self.dim = int(dim)
        self.mesh = mesh
        self._n_data = mesh.shape[DATA_AXIS] if mesh is not None else 1
        # rectangular shard: pad capacity up to a multiple of the axis
        self.capacity = -(-int(capacity) // self._n_data) * self._n_data
        self.requested_capacity = int(capacity)
        self.count = 0  # valid rows (host-side; queries read a device copy)
        self._ptr = 0  # FIFO write head (host-side mirror)
        self._row_sharding = None
        rows = jnp.zeros((self.capacity, self.dim), dtype)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._row_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
            rows = jax.device_put(rows, self._row_sharding)
        self.rows = rows
        self._compiled: dict[tuple[int, int], object] = {}
        self._frozen = False
        self.aot_compiles = 0
        self._warm_compiles: Optional[int] = None

    # -- ingest ----------------------------------------------------------

    def snapshot(self, embeddings: np.ndarray, normalized: bool = True) -> None:
        """Bulk (re)load: replace the store's contents with `embeddings`
        (n <= capacity rows) — the "load the trained dictionary" path
        (e.g. a checkpoint's queue). Resets the FIFO head."""
        embs = np.asarray(embeddings)
        n = embs.shape[0]
        if n > self.capacity or embs.shape[1] != self.dim:
            raise ValueError(
                f"snapshot shape {embs.shape} exceeds index ({self.capacity}, {self.dim})"
            )
        if not normalized:
            embs = np.asarray(l2_normalize(jnp.asarray(embs)))
        full = np.zeros((self.capacity, self.dim), self.rows.dtype)
        full[:n] = embs
        rows = jnp.asarray(full)
        if self._row_sharding is not None:
            rows = jax.device_put(rows, self._row_sharding)
        self.rows = rows
        self.count = n
        self._ptr = n % self.capacity

    def add(self, embeddings: np.ndarray) -> None:
        """FIFO ingest of an (N, dim) block at the write head — the
        serving-side mirror of the training enqueue. N must divide the
        capacity (the same no-wrap invariant `fifo_write` relies on)."""
        embs = jnp.asarray(embeddings, self.rows.dtype)
        n = embs.shape[0]
        if n == 0:
            return
        if self.capacity % n:
            raise ValueError(
                f"FIFO block of {n} rows does not divide capacity {self.capacity} "
                "(the no-wrap invariant); use snapshot() for arbitrary sizes"
            )
        rows, _ = fifo_write(self.rows, jnp.int32(self._ptr), embs)
        if self._row_sharding is not None:
            rows = jax.device_put(rows, self._row_sharding)
        self.rows = rows
        self._ptr = (self._ptr + n) % self.capacity
        self.count = min(self.count + n, self.capacity)

    @classmethod
    def from_train_queue(
        cls, queue: jax.Array, queue_ptr=0, count: Optional[int] = None, mesh=None
    ) -> "EmbeddingIndex":
        """The train-time queue as an index: wrap a checkpoint's
        (K, dim) queue rows (already L2-normalized by `init_queue`/
        `enqueue`). `count=None` treats every row as valid — after
        warmup the training queue is always full."""
        rows = np.asarray(queue)
        idx = cls(rows.shape[0], rows.shape[1], mesh=mesh, dtype=rows.dtype)
        idx.snapshot(rows)
        idx.count = rows.shape[0] if count is None else int(count)
        idx._ptr = int(queue_ptr)
        return idx

    # -- query -----------------------------------------------------------

    def _compile(self, m: int, k: int):
        if self._frozen:
            raise IndexRecompileError(
                f"query shape (m={m}, k={k}) was not prepared before freeze() — "
                "serving must pad queries to a prepared bucket (engine bucket "
                "set); compiling now would be the recompile-after-warmup class "
                "RecompileGuard aborts on"
            )
        fn = lambda q, rows, valid: topk_cosine(q, rows, k, valid_count=valid)
        q_s = jax.ShapeDtypeStruct((m, self.dim), self.rows.dtype)
        rows_s = jax.ShapeDtypeStruct(self.rows.shape, self.rows.dtype)
        valid_s = jax.ShapeDtypeStruct((), jnp.int32)
        if self._row_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self.mesh, P())
            jitted = jax.jit(
                fn,
                in_shardings=(rep, self._row_sharding, rep),
                out_shardings=rep,
            )
        else:
            jitted = jax.jit(fn)
        compiled = jitted.lower(q_s, rows_s, valid_s).compile()
        self.aot_compiles += 1
        self._compiled[(m, k)] = compiled
        return compiled

    def prepare(self, buckets: Sequence[int], k: int) -> None:
        """AOT-compile the query for every padded bucket shape (one
        executable per (m, k)); serve traffic then never traces."""
        for m in buckets:
            if (int(m), int(k)) not in self._compiled:
                self._compile(int(m), int(k))

    def freeze(self) -> None:
        """End of warmup: any later unprepared shape raises
        IndexRecompileError instead of silently compiling."""
        self._frozen = True
        self._warm_compiles = self.aot_compiles

    @property
    def recompiles_after_warmup(self) -> int:
        if self._warm_compiles is None:
            return 0
        return self.aot_compiles - self._warm_compiles

    def query(
        self, queries, k: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """(scores, indices), each (m, k), of the top-k valid rows per
        query. `m` must be a prepared bucket once frozen; `k` is capped
        by the caller to `count` if exact-rank semantics matter (indices
        past the fill level never appear — their scores are -inf-masked
        and top_k orders them last only when k > count)."""
        q = jnp.asarray(queries, self.rows.dtype)
        m = q.shape[0]
        k = int(k)
        compiled = self._compiled.get((m, k))
        if compiled is None:
            compiled = self._compile(m, k)
        scores, idx = compiled(q, self.rows, jnp.int32(self.count))
        return np.asarray(scores), np.asarray(idx)


__all__ = [
    "EmbeddingIndex",
    "IndexRecompileError",
    "fifo_write",
    "topk_cosine",
]
