"""Checkpoint promotion for the serving fleet: gates, audit ledger,
staged rollout.

Training keeps writing checkpoints; the fleet keeps serving an encoder
whose index rows were embedded by SOME checkpoint. MoCo's dictionary
consistency (He et al., arXiv:1911.05722) makes the handoff dangerous:
a candidate encoder can be healthy in isolation yet incompatible with
the live embedding space, and recall degrades with no error and no
5xx. This module closes the train→serve loop as an *auditable
pipeline* instead of a copy-the-checkpoint convention:

- **Gate battery** (`run_gate_battery`) — the candidate must clear
  declared floors before it touches traffic: `compat_cosine` and
  `recall_overlap` from `obs/quality.py` (embedding-space compatibility
  against the LIVE encoder and index), `feature_std` (the PR 3
  dimensional-collapse gauge on the candidate's probe embeddings,
  normalized so 1.0 ≈ uniform-sphere spread), and — when the
  candidate's query/key param trees are supplied — an `ema_drift`
  ceiling (a key encoder that tore away from its query twin does not
  provide consistent dictionary keys). An optional `live_recall` floor
  thresholds the fleet's current `serve/recall_estimate` so a
  promotion never launches from an already-degraded baseline.
- **Audit ledger** (`PromotionLedger`) — every verdict is an
  append-only `promotions.jsonl` line, schema-validated BEFORE it is
  written (`event: "promotion"`, obs/schema.py): the verdict, the
  stage, the candidate digest, and per-gate evidence
  (`promotion/gate/<name>` value vs `promotion/floor/<name>`, with
  `promotion/gate_ok/<name>` as 0/1). A rejected checkpoint names the
  gate that killed it; an accepted one carries the numbers that let it
  through.
- **Staged rollout** (`StagedRollout`) — one replica at a time through
  the PR 16 router: swap (drain → restart onto the candidate → wait
  re-admitted with the candidate's digest), then SOAK watching the
  fleet burn gauges; a breach auto-rolls every swapped replica back to
  the previous checkpoint. The machine takes injectable `swap` /
  `status` / `burn` callables plus a deterministic clock, so the state
  transitions (including the rollback path) are unit-testable without
  a fleet.

`scripts/serve_promote.py` is the CLI that wires real engines, the
router's `/admin/promote` endpoint, and a watch loop around these
pieces; `scripts/fleet_serve_smoke.py` proves the full loop end to end.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from moco_tpu.analysis import tsan
from moco_tpu.obs import quality, schema
from moco_tpu.obs.slo import DEFAULT_FAST_BURN

# Promotion verdicts (obs/schema.py validates the ledger against this
# set): gates either "accepted"/"rejected" a candidate; a rollout ends
# "promoted" or "rolled_back".
VERDICTS = ("accepted", "rejected", "promoted", "rolled_back")

# Default gate floors. `feature_std` is normalized by sqrt(dim) so 1.0
# is the uniform-sphere value (obs/health.py); `ema_drift_max` is a
# CEILING (the gate fails above it); `live_recall` is opt-in (None =
# not gated) because a fleet without online-recall sampling has no
# baseline to threshold.
DEFAULT_FLOORS = {
    "compat_cosine": 0.90,
    "recall_overlap": 0.60,
    "feature_std": 0.25,
    "ema_drift_max": 0.50,
    "live_recall": None,
}


def _gate_floor(value, floor) -> dict:
    v = None if value is None else float(value)
    return {"value": v, "floor": float(floor), "ok": v is not None and v >= float(floor)}


def _gate_ceiling(value, ceiling) -> dict:
    # ledger-side the threshold still lands in `promotion/floor/<name>`
    # (one evidence shape for every gate); the `_max` suffix in the
    # gate's name is what says "fail above, not below"
    v = None if value is None else float(value)
    return {
        "value": v,
        "floor": float(ceiling),
        "ok": v is not None and v <= float(ceiling),
    }


def run_gate_battery(
    live_engine,
    cand_engine,
    probes,
    index=None,
    k: int = 5,
    mode: str = "exact",
    floors: Optional[dict] = None,
    cand_params_q=None,
    cand_params_k=None,
    live_recall: Optional[float] = None,
) -> dict:
    """Evaluate every promotion gate for one candidate encoder.

    Returns `{"ok", "failed_gate", "gates", "compat"}`: `gates` maps
    gate name → `{"value", "floor", "ok"}` (insertion order is the
    evaluation order; `failed_gate` is the FIRST failure, the one the
    ledger names), `compat` is the schema'd
    `serve/compat_cosine`/`serve/recall_overlap` gauge pair. Engines
    are duck-typed (`embed(images) -> (emb, executed)`) so tests drive
    the battery with fakes."""
    f = dict(DEFAULT_FLOORS)
    f.update(floors or {})
    probes = np.asarray(probes)
    live_emb, _ = live_engine.embed(probes)
    cand_emb, _ = cand_engine.embed(probes)
    cosine = quality.compat_cosine(live_emb, cand_emb)
    overlap = None
    gates = {"compat_cosine": _gate_floor(cosine, f["compat_cosine"])}
    if index is not None and getattr(index, "count", 0) > 0:
        overlap = quality.recall_overlap(live_emb, cand_emb, index, k=k, mode=mode)
        gates["recall_overlap"] = _gate_floor(overlap, f["recall_overlap"])
    # dimensional-collapse check on the CANDIDATE's embeddings — the
    # PR 3 health gauge, rescaled so 1.0 ≈ uniform on the sphere
    from moco_tpu.obs import health

    cand_np = np.asarray(cand_emb, np.float32)
    fstd = float(np.asarray(health.feature_stats(cand_np)["feature_std"]))
    gates["feature_std"] = _gate_floor(
        fstd * float(np.sqrt(cand_np.shape[-1])), f["feature_std"]
    )
    if cand_params_q is not None and cand_params_k is not None:
        drift = float(
            np.asarray(health.ema_drift(cand_params_q, cand_params_k)["ema_drift"])
        )
        gates["ema_drift_max"] = _gate_ceiling(drift, f["ema_drift_max"])
    if f.get("live_recall") is not None and live_recall is not None:
        gates["live_recall"] = _gate_floor(live_recall, f["live_recall"])
    failed = next((name for name, g in gates.items() if not g["ok"]), None)
    return {
        "ok": failed is None,
        "failed_gate": failed,
        "gates": gates,
        "compat": quality.compat_payload(cosine, overlap),
    }


def ledger_record(
    step: int,
    verdict: str,
    stage: str,
    digest: Optional[str] = None,
    failed_gate: Optional[str] = None,
    replica: Optional[int] = None,
    gates: Optional[dict] = None,
    compat: Optional[dict] = None,
    now: Optional[float] = None,
) -> dict:
    """One schema'd promotion event line: verdict + stage + candidate
    identity, per-gate evidence flattened to
    `promotion/gate/<name>` / `promotion/floor/<name>` /
    `promotion/gate_ok/<name>`, and the compat gauge pair."""
    if verdict not in VERDICTS:
        raise ValueError(f"verdict must be one of {VERDICTS}, got {verdict!r}")
    rec = {
        "step": int(step),
        "time": time.time() if now is None else float(now),
        "event": "promotion",
        "promotion/step": int(step),
        "promotion/verdict": str(verdict),
        "promotion/stage": str(stage),
        "promotion/digest": digest,
        "promotion/failed_gate": failed_gate,
        "promotion/replica": int(replica) if replica is not None else None,
    }
    for name, g in (gates or {}).items():
        rec[f"promotion/gate/{name}"] = g["value"]
        rec[f"promotion/floor/{name}"] = g["floor"]
        rec[f"promotion/gate_ok/{name}"] = int(bool(g["ok"]))
    rec.update(compat or {})
    return rec


class PromotionLedger:
    """Append-only `promotions.jsonl`: the promotion pipeline's audit
    trail. Every record is validated against the obs schema BEFORE the
    write (an unschema'd verdict never lands on disk) and serialized
    with `allow_nan=False` (the writer-side twin of `loads_strict`).
    Append-only by construction: open(..., "a") under a lock, one line
    per event, never rewritten."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = tsan.make_lock("promote.ledger")

    def append(self, rec: dict) -> dict:
        errors = schema.validate_line(rec)
        if errors:
            raise ValueError(f"promotion ledger record fails schema: {errors}")
        line = json.dumps(rec, allow_nan=False)
        with self._lock:
            with open(self.path, "a") as fh:
                fh.write(line + "\n")
        return rec

    def read(self) -> list:
        if not os.path.exists(self.path):
            return []
        with open(self.path) as fh:
            return [schema.loads_strict(ln) for ln in fh if ln.strip()]


class StagedRollout:
    """One-replica-at-a-time rollout with burn-gauge soak and
    auto-rollback — the state machine behind `serve_promote`'s rollout
    stage, decoupled from HTTP so the transitions are unit-testable.

    Callables (all injectable):

    - `swap(i)` — start moving replica `i` onto the CANDIDATE
      checkpoint (the CLI posts `/admin/promote?replica=i&ckpt_dir=…`).
    - `swap_back(i)` — same, onto the PREVIOUS checkpoint (rollback
      path; defaults to `swap`, which only makes sense in tests).
    - `status(i)` — that replica's `/admin/replicas` snapshot: the
      machine waits for `healthy and not draining` and, when
      `target_digest` is given, for `model_digest` to match it (the
      swap has LANDED, not merely restarted).
    - `burn()` — the fleet gauge to soak on (the CLI reads the max of
      the router's fast-window latency/freshness burn aggregates);
      any reading above `burn_ceiling` during the soak triggers
      rollback. None readings (no traffic yet) are not breaches.

    `run()` returns `{"verdict": "promoted"|"rolled_back", "swapped",
    "replica", "reason", "burn"}` — `replica`/`reason` name the step
    that failed (`swap_timeout` or `burn_breach`)."""

    def __init__(
        self,
        num_replicas: int,
        swap: Callable[[int], object],
        status: Callable[[int], dict],
        burn: Optional[Callable[[], Optional[float]]] = None,
        swap_back: Optional[Callable[[int], object]] = None,
        target_digest: Optional[str] = None,
        soak_s: float = 1.0,
        swap_timeout_s: float = 60.0,
        burn_ceiling: float = DEFAULT_FAST_BURN,
        poll_s: float = 0.2,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        self.num_replicas = int(num_replicas)
        self.swap = swap
        self.swap_back = swap_back if swap_back is not None else swap
        self.status = status
        self.burn = burn
        self.target_digest = target_digest
        self.soak_s = float(soak_s)
        self.swap_timeout_s = float(swap_timeout_s)
        self.burn_ceiling = float(burn_ceiling)
        self.poll_s = float(poll_s)
        self._sleep = sleep
        self._clock = clock

    def _landed(self, snap: dict, digest: Optional[str]) -> bool:
        if not snap.get("healthy") or snap.get("draining"):
            return False
        if snap.get("drain_phase") is not None:
            return False
        return digest is None or snap.get("model_digest") == digest

    def _swap_and_wait(self, index: int, swap_fn, digest: Optional[str]) -> bool:
        swap_fn(index)
        deadline = self._clock() + self.swap_timeout_s
        while self._clock() < deadline:
            if self._landed(self.status(index), digest):
                return True
            self._sleep(self.poll_s)
        return self._landed(self.status(index), digest)

    def _soak(self) -> Optional[float]:
        """None = clean soak; a float = the breaching burn reading."""
        if self.burn is None or self.soak_s <= 0:
            return None
        deadline = self._clock() + self.soak_s
        while True:
            b = self.burn()
            if b is not None and float(b) > self.burn_ceiling:
                return float(b)
            if self._clock() >= deadline:
                return None
            self._sleep(self.poll_s)

    def run(self) -> dict:
        swapped: list = []
        for i in range(self.num_replicas):
            if not self._swap_and_wait(i, self.swap, self.target_digest):
                return self._rollback(swapped, i, "swap_timeout", None)
            swapped.append(i)
            breach = self._soak()
            if breach is not None:
                return self._rollback(swapped, i, "burn_breach", breach)
        return {
            "verdict": "promoted",
            "swapped": swapped,
            "replica": None,
            "reason": None,
            "burn": None,
        }

    def _rollback(
        self, swapped: Sequence[int], failed: int, reason: str, burn: Optional[float]
    ) -> dict:
        # every replica that touched the candidate goes back — including
        # the one whose swap timed out (it may have half-landed); no
        # digest wait on the way back (the previous encoder's digest is
        # unknown here), just healthy re-admission
        for j in dict.fromkeys(list(swapped) + [failed]):
            self._swap_and_wait(j, self.swap_back, None)
        return {
            "verdict": "rolled_back",
            "swapped": list(swapped),
            "replica": int(failed),
            "reason": reason,
            "burn": burn,
        }


__all__ = [
    "DEFAULT_FLOORS",
    "PromotionLedger",
    "StagedRollout",
    "VERDICTS",
    "ledger_record",
    "run_gate_battery",
]
