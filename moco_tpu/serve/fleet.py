"""ReplicaSupervisor: spawn, watch, and resurrect serving replicas.

The router (`serve/router.py`) decides WHERE requests go; the
supervisor decides that there are replicas to send them to. It spawns N
`moco_tpu.serve.replica_main` processes on pre-allocated ports (the
port is claimed in the parent and released just before spawn, so a
replica's URL survives its death — the router's handles never move),
then a monitor thread polls the child processes:

- A replica that EXITS (crash, `kill@replica` chaos fault, OOM) is
  respawned after an exponential per-replica backoff (reset once the
  reborn process reports healthy), its `MOCO_FAULTS` env scrubbed of
  `kill@replica` rules (`utils/faults.strip_replica_kills`) so one
  chaos rule is one death, not a crash loop.
- After every (re)spawn the supervisor waits for `/healthz` (the
  replica binds its port only after AOT warmup, so a connection refused
  means "still compiling") and then re-plays the index bootstrap
  through the replica's `/ingest` endpoint (`warm_rows_fn` supplies the
  canonical dictionary rows) — a reborn replica rejoins with a WARM
  dictionary, not an empty index.
- `restart_replica(i)` is the graceful path the router's drain worker
  calls: SIGTERM (the replica's `replica_main` drains its batcher —
  every accepted request flushes), wait for exit (SIGKILL after a
  timeout), respawn, wait healthy, re-warm.

Every observable transition lands in `events()` (spawn/exit/restart
records with exit codes), which is what the chaos smoke asserts
against: `kill@replica=1` must produce exactly one exit event with
`KILL_EXIT_CODE` and one successful respawn.

Threading: one tsan-traced lock (`fleet.supervisor`) guards the child
table and the event log; process I/O (spawn, wait, HTTP warm-up polls)
happens strictly outside it. The monitor thread is joined in
`close()` (JX011).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Callable, Optional

import numpy as np

from moco_tpu.analysis import tsan
from moco_tpu.utils import faults, retry

WARM_INGEST_BLOCK = 512  # rows per /ingest POST during a warm replay


def free_port(host: str = "127.0.0.1") -> int:
    """Claim an ephemeral port and release it — the classic pre-spawn
    port reservation. Races are possible but vanishingly rare on a
    smoke host, and a lost race surfaces as a loud bind failure in the
    child's log, not a silent misroute."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def default_replica_argv(
    ckpt_dir: str,
    workdir: Optional[str],
    index: int,
    port: int,
    host: str = "127.0.0.1",
    buckets=(1, 8, 32),
    slo_ms: float = 1000.0,
    fresh_max_age_s: float = 0.0,
) -> list:
    """argv for one `moco_tpu.serve.replica_main` child."""
    argv = [
        sys.executable, "-m", "moco_tpu.serve.replica_main",
        "--ckpt-dir", str(ckpt_dir),
        "--host", host,
        "--port", str(port),
        "--replica-index", str(index),
        "--buckets", ",".join(str(b) for b in buckets),
        "--slo-ms", str(slo_ms),
    ]
    if fresh_max_age_s:
        argv += ["--fresh-max-age-s", str(float(fresh_max_age_s))]
    if workdir:
        argv += ["--workdir", os.path.join(workdir, f"replica{index}")]
    return argv


class _Child:
    """Supervisor-side state for one replica slot (mutated only under
    the supervisor lock; the Popen handle itself is poll()ed lock-free
    — poll() is thread-safe and the handle is replaced atomically)."""

    def __init__(self, index: int, port: int):
        self.index = index
        self.port = port
        self.proc: Optional[subprocess.Popen] = None
        self.restarting = False
        self.restarts = 0
        self.backoff_s = 0.0
        self.healthy_since: Optional[float] = None


class ReplicaSupervisor:
    """Spawn + supervise N replica processes (module docstring).

    Either pass `ckpt_dir` (children run `replica_main` with
    `default_replica_argv`) or an `argv_for(index, port) -> argv`
    callable for custom children (tests use a stdlib-only fake).
    `warm_rows_fn() -> (n, d) float32 rows` is the index bootstrap
    replayed into a reborn replica's `/ingest`; None skips the warm
    replay. `extra_env` maps replica index -> env overrides (the chaos
    smoke plants per-replica `MOCO_FAULTS` here).
    """

    def __init__(
        self,
        num_replicas: int,
        ckpt_dir: Optional[str] = None,
        argv_for: Optional[Callable[[int, int], list]] = None,
        workdir: Optional[str] = None,
        host: str = "127.0.0.1",
        buckets=(1, 8, 32),
        slo_ms: float = 1000.0,
        env: Optional[dict] = None,
        extra_env: Optional[dict] = None,
        warm_rows_fn: Optional[Callable[[], np.ndarray]] = None,
        boot_timeout_s: float = 180.0,
        term_timeout_s: float = 30.0,
        monitor_interval_s: float = 0.5,
        restart_backoff_s: float = 0.5,
        restart_backoff_cap_s: float = 10.0,
        auto_restart: bool = True,
        fresh_max_age_s: float = 0.0,
    ):
        if num_replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        # the SWAPPABLE checkpoint dir: the promotion pipeline calls
        # `set_ckpt_dir(candidate)` and then restarts replicas one at a
        # time — each respawn reads the CURRENT value, which is how a
        # staged rollout (and its rollback) changes the served encoder
        # without changing the replica's URL
        self._ckpt_dir = str(ckpt_dir) if ckpt_dir is not None else None
        self._custom_argv = argv_for is not None
        if argv_for is None:
            if ckpt_dir is None:
                raise ValueError("need ckpt_dir or argv_for")
            argv_for = lambda index, port: default_replica_argv(
                self._ckpt_dir, workdir, index, port,
                host=host, buckets=buckets, slo_ms=slo_ms,
                fresh_max_age_s=fresh_max_age_s,
            )
        self._argv_for = argv_for
        self.host = host
        self.workdir = workdir
        self._env = dict(env) if env is not None else dict(os.environ)
        self._extra_env = {int(k): dict(v) for k, v in (extra_env or {}).items()}
        self._warm_rows_fn = warm_rows_fn
        self.boot_timeout_s = float(boot_timeout_s)
        self.term_timeout_s = float(term_timeout_s)
        self.monitor_interval_s = float(monitor_interval_s)
        self.restart_backoff_s = float(restart_backoff_s)
        self.restart_backoff_cap_s = float(restart_backoff_cap_s)
        self.auto_restart = bool(auto_restart)
        self._lock = tsan.make_lock("fleet.supervisor")
        self._children = [
            _Child(i, free_port(host)) for i in range(int(num_replicas))
        ]
        self._events: list = []
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # -- topology ---------------------------------------------------------

    def ckpt_dir(self) -> Optional[str]:
        """The checkpoint dir the NEXT (re)spawn serves from."""
        with self._lock:
            return self._ckpt_dir

    def set_ckpt_dir(self, path: str) -> None:
        """Point future (re)spawns at a different checkpoint dir — the
        promotion swap. Running replicas are untouched; the staged
        rollout restarts them one at a time through the router's drain
        path. Raises with a custom `argv_for` (the supervisor can't know
        how to thread the dir into a caller-built argv)."""
        if self._custom_argv:
            raise RuntimeError(
                "set_ckpt_dir needs the default replica argv (a custom "
                "argv_for owns its own checkpoint wiring)"
            )
        with self._lock:
            self._ckpt_dir = str(path)
        self._record("ckpt_swap", -1, ckpt_dir=str(path))

    def clear_extra_env(self, index: int) -> None:
        """Drop the per-replica env overrides for slot `index` so its
        NEXT respawn comes up clean — the chaos harness healing a
        replica. Persistent fault rules (e.g. a slow@ stage injected via
        MOCO_FAULTS) otherwise re-install on every respawn, and a
        staged rollout soaking on fleet burn gauges would (correctly)
        refuse to promote into a permanently-burning fleet."""
        with self._lock:
            self._extra_env.pop(int(index), None)
        self._record("heal", int(index))

    def url(self, index: int) -> str:
        return f"http://{self.host}:{self._children[index].port}"

    def urls(self) -> list:
        return [self.url(i) for i in range(len(self._children))]

    def events(self) -> list:
        with self._lock:
            return [dict(e) for e in self._events]

    def _record(self, kind: str, index: int, **extra) -> None:
        with self._lock:
            self._events.append(
                {"kind": kind, "replica": index, "t": time.monotonic(), **extra}
            )

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Spawn every replica, wait until ALL report healthy, then
        start the crash monitor. Boot is parallel across children (they
        warm up concurrently); the healthy-wait is sequential — by the
        time the first replica answers, the others are mid-warmup."""
        for child in self._children:
            self._spawn(child.index, scrub_kills=False)
        for child in self._children:
            self._wait_healthy(child.index)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet_supervisor", daemon=True
        )
        self._monitor.start()

    def _child_env(self, index: int, scrub_kills: bool) -> dict:
        env = dict(self._env)
        with self._lock:
            overrides = dict(self._extra_env.get(index, {}))
        env.update(overrides)
        if scrub_kills and env.get("MOCO_FAULTS"):
            # a kill@replica rule already fired for this slot: the
            # reborn process must not inherit its own death warrant
            env["MOCO_FAULTS"] = faults.strip_replica_kills(env["MOCO_FAULTS"])
            if not env["MOCO_FAULTS"]:
                del env["MOCO_FAULTS"]
        return env

    def _spawn(self, index: int, scrub_kills: bool) -> None:
        child = self._children[index]
        argv = self._argv_for(index, child.port)
        proc = subprocess.Popen(argv, env=self._child_env(index, scrub_kills))
        with self._lock:
            child.proc = proc
            child.healthy_since = None
        self._record("spawn", index, pid=proc.pid, port=child.port)

    def _wait_healthy(self, index: int, timeout: Optional[float] = None) -> None:
        """Block until the replica answers /healthz ok (it binds HTTP
        only after AOT warmup, so connection-refused = still booting).
        Raises RuntimeError when the child died or the timeout passed."""
        child = self._children[index]
        deadline = time.monotonic() + (timeout or self.boot_timeout_s)
        url = self.url(index) + "/healthz"
        while time.monotonic() < deadline:
            proc = child.proc
            if proc is not None and proc.poll() is not None:
                raise RuntimeError(
                    f"replica {index} exited rc={proc.returncode} during boot"
                )
            try:
                with urllib.request.urlopen(url, timeout=2.0) as r:
                    if json.loads(r.read()).get("ok"):
                        with self._lock:
                            child.healthy_since = time.monotonic()
                            child.backoff_s = 0.0  # recovery resets the backoff
                        return
            except (OSError, ValueError):
                pass
            time.sleep(0.2)
        raise RuntimeError(f"replica {index} not healthy after {self.boot_timeout_s}s")

    def _warm(self, index: int) -> int:
        """Re-play the index bootstrap into a reborn replica's /ingest
        (retry-wrapped, site fleet.warm_ingest) — the warm-dictionary
        guarantee. Returns rows replayed."""
        if self._warm_rows_fn is None:
            return 0
        rows = np.ascontiguousarray(self._warm_rows_fn(), np.float32)
        if rows.size == 0:
            return 0
        url = self.url(index) + "/ingest"

        def _post(chunk: np.ndarray) -> None:
            req = urllib.request.Request(
                url,
                data=chunk.tobytes(),
                headers={"X-Rows-Shape": f"{chunk.shape[0]},{chunk.shape[1]}"},
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()

        for lo in range(0, rows.shape[0], WARM_INGEST_BLOCK):
            retry.retry_call(
                _post, rows[lo : lo + WARM_INGEST_BLOCK], site="fleet.warm_ingest"
            )
        self._record("warm", index, rows=int(rows.shape[0]))
        return int(rows.shape[0])

    def restart_replica(self, index: int, graceful: bool = True) -> None:
        """The drain worker's restart: SIGTERM (graceful — replica_main
        drains its batcher so accepted requests flush), wait for exit
        (SIGKILL past `term_timeout_s`), respawn with kill@replica
        rules scrubbed, wait healthy, re-warm the index. Blocking."""
        child = self._children[index]
        with self._lock:
            if child.restarting:
                return
            child.restarting = True
        try:
            proc = child.proc
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM if graceful else signal.SIGKILL)
                try:
                    proc.wait(timeout=self.term_timeout_s)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10.0)
            self._record(
                "exit", index,
                rc=proc.returncode if proc is not None else None,
                reason="restart",
            )
            with self._lock:
                child.restarts += 1
            self._spawn(index, scrub_kills=True)
            self._wait_healthy(index)
            self._warm(index)
            self._record("restart", index, graceful=graceful)
        finally:
            with self._lock:
                child.restarting = False

    # -- crash monitor ----------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.monitor_interval_s):
            for child in self._children:
                with self._lock:
                    restarting = child.restarting
                    proc = child.proc
                if restarting or proc is None:
                    continue
                rc = proc.poll()
                if rc is None:
                    continue
                self._record("exit", child.index, rc=rc, reason="crash")
                if not self.auto_restart or self._stop.is_set():
                    continue
                self._respawn_crashed(child, rc)

    def _respawn_crashed(self, child: _Child, rc: int) -> None:
        with self._lock:
            child.restarting = True
            child.restarts += 1
            backoff = child.backoff_s = min(
                self.restart_backoff_cap_s,
                child.backoff_s * 2 if child.backoff_s else self.restart_backoff_s,
            )
        print(
            f"supervisor: replica {child.index} exited rc={rc}; "
            f"respawning in {backoff:.1f}s",
            flush=True,
        )
        try:
            # the backoff sleep polls the stop flag so close() is prompt
            if self._stop.wait(backoff):
                return
            self._spawn(child.index, scrub_kills=True)
            self._wait_healthy(child.index)
            self._warm(child.index)
            self._record("restart", child.index, graceful=False, rc=rc)
        except Exception as e:  # the monitor must survive a failed respawn
            print(
                f"supervisor: respawn of replica {child.index} failed: {e!r}",
                flush=True,
            )
            self._record("respawn_failed", child.index, error=repr(e))
        finally:
            with self._lock:
                child.restarting = False

    def close(self) -> None:
        """Stop the monitor (joined — JX011), SIGTERM every child
        (graceful: their batchers drain), SIGKILL stragglers."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=self.restart_backoff_cap_s + 30.0)
        for child in self._children:
            proc = child.proc
            if proc is None or proc.poll() is not None:
                continue
            proc.terminate()
        for child in self._children:
            proc = child.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=self.term_timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)


__all__ = [
    "ReplicaSupervisor",
    "default_replica_argv",
    "free_port",
]
