"""The embedding service's HTTP front end (stdlib, like the Prometheus
sink it runs alongside).

Endpoints:

- `POST /embed` — body: raw uint8 pixels, `X-Image-Shape: n,h,w,c`
  header (h/w/c must match the engine). Response JSON:
  `{"embedding": [[...f32...]]}` (L2-normalized backbone features).
- `POST /neighbors` — same body; `?k=5` (default 5, capped at the
  prepared k) and `?mode=exact|ivf|exact_i8|ivf_i8` (default: the
  server's `neighbors_mode`). Response adds
  `{"indices": [[...]], "scores": [[...]], "mode": "..."}` — top-k
  cosine rows of the sharded EmbeddingIndex, i.e. the MoCo dictionary
  look-up as a product; `ivf` scans only the `nprobe` nearest cells
  (sub-linear — serve/index.py), the int8 modes score quantized.
- `POST /ingest` — body: raw float32 rows, `X-Rows-Shape: n,d` header
  (plus the propagated `X-Ckpt-Step` header naming the source training
  checkpoint step, so encoder/index provenance is visible).
  FIFO-ingests a block into the live index (the streaming-updates path
  `scripts/serve_ingest.py` drives from a training checkpoint dir);
  IVF cell membership and the int8 mirror follow incrementally, and
  every written row gets a wall-clock ingest stamp (the freshness SLO's
  raw signal). `delay@site=ingest` is the chaos hook that stalls this
  path.
- `GET /admin/model` — the served-model identity: checkpoint step +
  params digest of the encoder answering on this replica, and the last
  ingest's source checkpoint step (encoder/index skew at a glance).
- `GET /stats` — the live `serve/*` gauge snapshot as JSON.
- `GET /healthz` — `{"ok": true, "warm": ..., "draining": false}` once
  the AOT warmup ran; `ok` flips false while draining so a fleet router
  stops dispatching here before the batcher's intake actually shuts.
- `POST /admin/drain` — graceful shutdown of THIS replica: healthz goes
  not-ok, the batcher flushes every accepted request (`drain()`, zero
  failed futures), then intake closes. The fleet router calls this (or
  the SIGTERM path does, via `replica_main`) before a restart.

Recall estimation: with an approximate `neighbors_mode`, every
`recall_sample_every`-th neighbors micro-batch ALSO runs the exact
oracle on the same device features and records the top-k overlap —
`serve/recall_estimate` in the metric flush, the gauge the smoke's
recall floor (and the CONTRIBUTING review gate) reads.

Request rows flow through the ContinuousBatcher (coalescing under the
SLO), so concurrent clients share padded-bucket executions; handler
threads only block on their own future. Metrics flow into the standard
obs sinks: a flusher thread writes `ServeMetrics.payload()` every
`metrics_flush_s` (schema-validated `serve/*` family; with a Prometheus
sink attached each gauge is scraped as `moco_serve_<name>`).

Ports: `resolve_serve_port` (obs/sinks.py) applies the offset rule so
a process running both the server and `--metrics-port` can't collide —
Prometheus owns `metrics_port + process_index`, the server claims
`serve_port + process_index` and shifts by SERVE_PORT_STRIDE when the
two meet.

Request-scoped observability (PR 10, obs/{reqtrace,slo,flight}.py):
with `reqtrace=True` (the default) every request gets a replica-scoped
id and a stage-stamped waterfall (`ingress -> queue_wait ->
batch_assemble -> engine_execute -> index_query -> scatter ->
respond`); completed waterfalls feed a bounded flight-recorder ring,
the `serve/trace_<stage>_ms` window means, the latency histogram's p99
exemplar, and — when a `workdir` is given — Perfetto request spans on
virtual "requests" lanes in `trace_events.s<replica>.jsonl` (the
`heartbeat.s<replica>.json` anchor lets scripts/trace_merge.py align
them with the training timeline). An `SLOBurnTracker` turns the
declared `slo_ms` into multi-window `serve/burn_rate_<w>s` gauges; an
`AlertEngine` over the flush stream (`alert_spec="serve_default"` =
obs/slo.py's threshold rules) dumps the flight recorder to
`flight_<ts>.json` the moment a rule fires, and `GET /debug/flight`
dumps it on demand.

Thread hygiene (JX011): the HTTP server thread and the metrics flusher
are both joined in `close()`, the flusher polls a stop event, and the
batcher's own close fails stragglers loudly.
"""

from __future__ import annotations

import http.server
import json
import os
import socket
import sys
import threading
import time
from collections import deque

import numpy as np

from moco_tpu.obs import ctxprop
from moco_tpu.obs.alerts import AlertEngine, parse_rules
from moco_tpu.obs.flight import FlightRecorder
from moco_tpu.obs.reqtrace import RequestIdAllocator, emit_request_spans
from moco_tpu.obs.sinks import resolve_serve_port  # noqa: F401  (public API)
from moco_tpu.obs.slo import (
    DEFAULT_WINDOWS,
    FreshnessBurnTracker,
    SLOBurnTracker,
    fresh_alert_spec,
    serve_alert_spec,
)
from moco_tpu.obs.trace import Tracer, get_tracer
from moco_tpu.analysis import tsan
from moco_tpu.analysis.contracts import record_route
from moco_tpu.serve.batcher import BatcherClosedError, ContinuousBatcher, ServeMetrics
from moco_tpu.serve.index import QUERY_MODES
from moco_tpu.utils import faults

DEFAULT_NEIGHBORS_K = 5
DEFAULT_RECALL_SAMPLE_EVERY = 8


class _QuietHTTPServer(http.server.ThreadingHTTPServer):
    """ThreadingHTTPServer that stays quiet when a CLIENT abandons the
    connection mid-response — routine under a fleet router (a hedge
    loser's response is discarded, a health probe times out and hangs
    up), not worth a traceback per occurrence."""

    def handle_error(self, request, client_address):
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return
        super().handle_error(request, client_address)


class ServeServer:
    """HTTP front end binding engine + index + batcher (module
    docstring). `port=0` binds ephemeral (tests/smoke); `self.port` is
    the actual one. `index=None` serves `/embed` only (`/neighbors`
    answers 503). `sink=None` keeps metrics in-process (`/stats` only).
    """

    def __init__(
        self,
        engine,
        index=None,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_port: int = 0,
        process_index: int = 0,
        slo_ms: float = 100.0,
        neighbors_k: int = DEFAULT_NEIGHBORS_K,
        neighbors_mode: str = "exact",
        nprobe: int = 0,
        recall_sample_every: int = DEFAULT_RECALL_SAMPLE_EVERY,
        sink=None,
        metrics_flush_s: float = 1.0,
        warmup: bool = True,
        workdir: str = None,
        replica_index: int = 0,
        reqtrace: bool = True,
        slo_objective: float = 0.99,
        burn_windows=DEFAULT_WINDOWS,
        alert_spec: str = "serve_default",
        flight_requests: int = 512,
        model_step: int = None,
        model_digest: str = None,
        fresh_max_age_s: float = None,
        fresh_objective: float = 0.99,
    ):
        if neighbors_mode not in QUERY_MODES:
            raise ValueError(
                f"neighbors_mode must be one of {QUERY_MODES}, got {neighbors_mode!r}"
            )
        self.engine = engine
        self.index = index
        self.neighbors_k = int(neighbors_k)
        self.neighbors_mode = neighbors_mode
        self.nprobe = int(nprobe) or None
        self.recall_sample_every = int(recall_sample_every)
        self.workdir = workdir
        self.replica_index = int(replica_index)
        # served-model identity (obs/quality.py mints the digest): which
        # encoder answers on this replica — /stats and /admin/model
        # expose it so fleet version skew is a gauge, not an incident
        self.model_step = int(model_step) if model_step is not None else None
        self.model_digest = model_digest
        # source checkpoint step of the last /ingest block (X-Ckpt-Step
        # header) — encoder/index provenance skew, replica-side
        self.ingest_ckpt_step = None
        # request-scoped observability: replica-tagged ids + waterfalls,
        # burn-rate accounting over the declared SLO, flight recorder,
        # and the alert engine that trips the flight dump (module
        # docstring). All off the request path except the stamps.
        self._ids = RequestIdAllocator(self.replica_index) if reqtrace else None
        burn = SLOBurnTracker(slo_ms, objective=slo_objective, windows=burn_windows)
        self.metrics = ServeMetrics(slo_ms, burn=burn)
        self.flight = FlightRecorder(
            max_requests=flight_requests, replica=self.replica_index
        )
        # freshness SLO (obs/slo.py): declared max index-row age in wall
        # seconds; each metrics flush records one observation off the
        # index's ingest stamps, so a stalled ingest burns budget
        self.fresh = (
            FreshnessBurnTracker(
                fresh_max_age_s, objective=fresh_objective, windows=burn_windows
            )
            if fresh_max_age_s
            else None
        )
        spec = (
            serve_alert_spec(slo_ms, windows=burn.windows)
            if alert_spec == "serve_default"
            else alert_spec
        )
        if self.fresh is not None and alert_spec == "serve_default":
            # a declared freshness objective arms its burn alerts too
            spec = ",".join(s for s in (spec, fresh_alert_spec(windows=burn.windows)) if s)
        self._alerts = (
            AlertEngine(
                parse_rules(spec),
                workdir=workdir,
                process_index=self.replica_index,
                on_fire=self._on_alert,
            )
            if spec
            else None
        )
        # per-replica Perfetto stream for request spans: reuse the
        # installed process tracer when one exists (co-hosted with a
        # training driver); otherwise open our own replica stream next
        # to the training family, with a serve heartbeat anchor so
        # trace_merge can clock-align it
        self._tracer = get_tracer()
        self._own_tracer = None
        if self._tracer is None and workdir:
            self._own_tracer = self._tracer = Tracer(
                jsonl_path=os.path.join(
                    workdir, f"trace_events.s{self.replica_index}.jsonl"
                ),
                process_index=self.replica_index,
            )
        if workdir and self._tracer is not None:
            self._write_serve_anchor()
        # completed traces awaiting span emission — drained by the
        # metrics flusher thread, bounded so a stalled flusher degrades
        # to dropped spans rather than unbounded memory
        self._span_pending: deque = deque(maxlen=4 * flight_requests)
        self._lane = 0
        self._sink = sink
        self._flush_step = 0
        self._neighbor_flushes = 0
        self.ingested_rows = 0
        # one lock covers every index touch: a donated ingest write must
        # never invalidate a rows buffer a query is reading mid-flight.
        # tsan factory (analysis/tsan.py) so --sanitize-threads smoke
        # runs see its acquisition order; zero-cost otherwise
        self._index_lock = tsan.make_lock("serve.index")
        if warmup:
            engine.warmup()
            if index is not None:
                # the exact tier is always prepared: it is the oracle the
                # recall estimator scores against and the fallback tier
                modes = {"exact", neighbors_mode}
                index.prepare(
                    engine.buckets, self.neighbors_k,
                    nprobe=self.nprobe, modes=sorted(modes),
                )
                index.freeze()
                self._prepared_modes = modes
        if not hasattr(self, "_prepared_modes"):
            # warmup=False: the caller prepared the index; accept any mode
            self._prepared_modes = set(QUERY_MODES)
        self.batcher = ContinuousBatcher(
            self._run_batch,
            max_batch=engine.buckets[-1],
            slo_ms=slo_ms,
            metrics=self.metrics,
        )
        # drain flag (an Event: set from any thread — the /admin/drain
        # handler or the SIGTERM path — read by every healthz handler)
        self._draining = threading.Event()
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                record_route("GET", path)
                if path == "/healthz":
                    draining = server._draining.is_set()
                    self._json(200, {
                        "ok": not draining,
                        "warm": server.engine.recompiles_after_warmup == 0,
                        "draining": draining,
                        "replica": server.replica_index,
                    })
                elif path == "/stats":
                    self._json(200, server.stats())
                elif path == "/admin/model":
                    # served-model identity: the promotion pipeline and
                    # the router's skew gauge read this (and /stats)
                    with server._index_lock:
                        ingest_step = server.ingest_ckpt_step
                    self._json(200, {
                        "model_step": server.model_step,
                        "model_digest": server.model_digest,
                        "ingest_ckpt_step": ingest_step,
                        "replica": server.replica_index,
                    })
                elif path == "/debug/flight":
                    # on-demand flight dump: write the ring to disk when
                    # a workdir exists, and return the snapshot either
                    # way (the live-debugging path)
                    body = server.flight.snapshot()
                    if server.workdir:
                        body["dump_path"] = server.flight.dump(
                            server.workdir, reason="debug_request",
                            extra={"slo_ms": server.metrics.slo_ms},
                        )
                    self._json(200, body)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                t_arrival = time.perf_counter()
                path, _, query = self.path.partition("?")
                record_route("POST", path)
                if path == "/ingest":
                    self._handle_ingest()
                    return
                if path == "/admin/drain":
                    self._handle_drain(query)
                    return
                if path not in ("/embed", "/neighbors"):
                    self.send_error(404)
                    return
                # chaos hook: kill@replica=i[:at=K] dies HERE, with the
                # request (and any coalesced riders) in flight — the
                # router's breaker + retry path must absorb the reset
                faults.maybe_kill_replica(server.replica_index)
                faults.maybe_slow("serve.ingress")
                try:
                    images = self._read_images()
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                want_neighbors = path == "/neighbors"
                if want_neighbors and server.index is None:
                    self._json(503, {"error": "no embedding index attached"})
                    return
                mode = None
                if want_neighbors:
                    mode = _query_param(query, "mode")
                    if mode is not None and (
                        mode not in QUERY_MODES or mode not in server._prepared_modes
                    ):
                        self._json(400, {
                            "error": f"mode {mode!r} not prepared on this replica "
                            f"(serving: {sorted(server._prepared_modes)})"
                        })
                        return
                # adopt the propagated trace context when the fleet
                # front door sent one — this replica's waterfall becomes
                # a child of the router's dispatch-attempt span
                ctx = ctxprop.parse(
                    self.headers.get("X-Trace-Id"),
                    self.headers.get("X-Parent-Span"),
                )
                trace = None
                if server._ids is not None:
                    # backdated to arrival so the ingress stage covers
                    # the body read + parse above
                    trace = server._ids.new_trace(
                        images.shape[0], t0=t_arrival, ctx=ctx
                    )
                    trace.stamp("ingress", t_arrival, time.perf_counter())
                try:
                    fut = server.batcher.submit(
                        images, want_neighbors=want_neighbors, mode=mode, trace=trace
                    )
                    out = fut.result(timeout=30.0)
                except (BatcherClosedError, TimeoutError) as e:
                    self._json(503, {"error": str(e)})
                    return
                faults.maybe_slow("serve.respond")
                t_respond = time.perf_counter()
                body = {"embedding": out["embedding"].tolist()}
                if want_neighbors:
                    k = _query_k(query, server.neighbors_k)
                    eff = mode or server.neighbors_mode
                    body["indices"] = out[f"indices:{eff}"][:, :k].tolist()
                    body["scores"] = out[f"scores:{eff}"][:, :k].tolist()
                    body["mode"] = eff
                if trace is not None:
                    body["request_id"] = trace.req_id
                    if trace.trace_id is not None:
                        # in-band stitching: ship the stage waterfall (as
                        # stamped so far — respond lands in the router's
                        # net_recv slack) back to the router with the
                        # response, so the router can attribute this hop
                        # without waiting for an offline trace merge
                        body["trace"] = trace.waterfall()
                self._json(200, body)
                if trace is not None:
                    trace.stamp("respond", t_respond, time.perf_counter())
                    server._complete(trace)

            def _handle_drain(self, query):
                """Graceful drain of this replica, synchronously: the
                response does not land until every accepted request has
                flushed (or the timeout passed) — the caller can treat a
                200 with drained=true as 'safe to SIGTERM/restart'."""
                try:
                    timeout = float(_query_param(query, "timeout") or 30.0)
                except ValueError:
                    self._json(400, {"error": "bad timeout parameter"})
                    return
                drained = server.drain(timeout=timeout)
                self._json(200, {
                    "draining": True,
                    "drained": drained,
                    "replica": server.replica_index,
                })

            def _handle_ingest(self):
                """FIFO-ingest a raw f32 row block into the live index —
                the wire the streaming updater (scripts/serve_ingest.py)
                pushes fresh training-queue rows over."""
                if server.index is None:
                    self._json(503, {"error": "no embedding index attached"})
                    return
                # chaos hook: delay@site=ingest stalls the freshness
                # pipeline HERE (before the body read, outside the index
                # lock) — row ages keep growing while the block is stuck,
                # which is exactly what the fresh-burn alert must catch
                faults.maybe_delay("ingest")
                try:
                    shape_hdr = self.headers.get("X-Rows-Shape", "")
                    try:
                        n, d = (int(s) for s in shape_hdr.split(","))
                    except ValueError:
                        raise ValueError(f"bad X-Rows-Shape header {shape_hdr!r}")
                    # propagated provenance header: which training
                    # checkpoint step produced these rows
                    ckpt_hdr = self.headers.get("X-Ckpt-Step")
                    ckpt_step = None
                    if ckpt_hdr:
                        try:
                            ckpt_step = int(ckpt_hdr)
                        except ValueError:
                            raise ValueError(
                                f"bad X-Ckpt-Step header {ckpt_hdr!r}"
                            )
                    length = int(self.headers.get("Content-Length", 0))
                    if length != n * d * 4:
                        raise ValueError(
                            f"Content-Length {length} != n*d*4 = {n * d * 4}"
                        )
                    # the socket read stays OUTSIDE the lock (JX013: no
                    # blocking I/O under _index_lock); the dim check and
                    # the response counters move INSIDE it so concurrent
                    # ingests can't interleave a torn snapshot (JX012)
                    rows = np.frombuffer(
                        self.rfile.read(length), np.float32
                    ).reshape(n, d)
                    with server._index_lock:
                        if d != server.index.dim:
                            raise ValueError(
                                f"row dim {d} != index dim {server.index.dim}"
                            )
                        server.index.add(rows)
                        server.ingested_rows += n
                        if ckpt_step is not None:
                            server.ingest_ckpt_step = ckpt_step
                        index_rows = server.index.count
                        total_ingested = server.ingested_rows
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                self._json(200, {
                    "ingested": n,
                    "index_rows": index_rows,
                    "total_ingested": total_ingested,
                })

            def _read_images(self) -> np.ndarray:
                shape_hdr = self.headers.get("X-Image-Shape", "")
                try:
                    shape = tuple(int(s) for s in shape_hdr.split(","))
                except ValueError:
                    raise ValueError(f"bad X-Image-Shape header {shape_hdr!r}")
                if len(shape) != 4:
                    raise ValueError("X-Image-Shape must be 'n,h,w,c'")
                n = int(self.headers.get("Content-Length", 0))
                expected = 1
                for s in shape:
                    expected *= s
                if n != expected:
                    raise ValueError(
                        f"Content-Length {n} != prod(X-Image-Shape) {expected}"
                    )
                return np.frombuffer(self.rfile.read(n), np.uint8).reshape(shape)

            def _json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr lines
                pass

        resolved = resolve_serve_port(port, metrics_port, process_index)
        self._server = _QuietHTTPServer((host, resolved), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serve_http", daemon=True
        )
        self._thread.start()
        self._stop = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, args=(float(metrics_flush_s),),
            name="serve_metrics_flush", daemon=True,
        )
        self._flusher.start()

    # -- request path ----------------------------------------------------

    def _run_batch(self, images, want_neighbors, modes=(), *, stages=None):
        """Batcher thread body: ONE padded engine execution per flush,
        then one index query per requested tier on the same features
        (the scans are small matmuls next to the encoder forward);
        /embed riders just drop the extra keys at scatter. With an
        approximate default tier, every `recall_sample_every`-th
        neighbors flush also runs the exact oracle and records the
        top-k overlap (`serve/recall_estimate`). `stages` (keyword-only,
        the batcher's request-trace contract) splits engine_execute /
        index_query seconds for the waterfall."""
        if want_neighbors and self.index is not None:
            requested = {self.neighbors_mode} | set(modes)
            approx = next(
                (m for m in (self.neighbors_mode, *sorted(requested))
                 if m.startswith("ivf")),
                None,
            )
            sample_recall = False
            if approx is not None and self.recall_sample_every > 0:
                self._neighbor_flushes += 1
                if self._neighbor_flushes % self.recall_sample_every == 0:
                    sample_recall = True
                    requested.add("exact")
            with self._index_lock:
                emb, per_mode, executed = self.engine.embed_and_query_modes(
                    images, self.index, self.neighbors_k,
                    modes=tuple(sorted(requested)), nprobe=self.nprobe,
                    stages=stages,
                )
            if sample_recall:
                _, exact_idx = per_mode["exact"]
                _, approx_idx = per_mode[approx]
                k = exact_idx.shape[1]
                overlap = np.asarray([
                    len(set(exact_idx[i]) & set(approx_idx[i]))
                    for i in range(exact_idx.shape[0])
                ])
                self.metrics.record_recall(float(overlap.mean()) / k)
            results = {"embedding": emb}
            for m, (scores, idx) in per_mode.items():
                results[f"scores:{m}"] = scores
                results[f"indices:{m}"] = idx
            return results, executed
        emb, executed = self.engine.embed(images, stages=stages)
        return {"embedding": emb}, executed

    # -- request-scoped observability ------------------------------------

    def _complete(self, trace) -> None:
        """A request finished responding: file its waterfall in the
        flight ring and queue it for span emission (both O(1); the
        rendering happens on the flusher thread)."""
        self.flight.record_request(trace.waterfall())
        self._span_pending.append(trace)

    def _drain_spans(self) -> None:
        """Flusher-thread side of `_complete`: render queued request
        waterfalls as Perfetto spans on the virtual request lanes."""
        if self._tracer is None:
            self._span_pending.clear()
            return
        while True:
            try:
                trace = self._span_pending.popleft()
            except IndexError:
                break
            emit_request_spans(self._tracer, trace, self._lane)
            self._lane += 1  # mocolint: disable=JX012  (flusher-thread only during the run; close() joins the flusher BEFORE its final _write_metrics call, so the two writers are join-serialized, never concurrent)

    def _on_alert(self, alert: dict) -> None:
        """AlertEngine on_fire hook: an SLO-burn (or any serving) alert
        dumps the flight recorder AT the firing edge and lands an
        in-band alert event line, so scrapers see `moco_alert_<rule>`
        and the postmortem file already exists when a human arrives."""
        if self.workdir:
            try:
                self.flight.dump(
                    self.workdir,
                    reason=f"alert:{alert['rule']}",
                    extra={
                        "alert": alert,
                        "slo_ms": self.metrics.slo_ms,
                        "replica": self.replica_index,
                    },
                )
            except Exception as e:  # the dump must never take serving down
                print(f"WARNING: flight dump failed: {e!r}", flush=True)
        if self._sink is not None:
            self._sink.write(
                self._flush_step,
                {
                    "event": "alert",
                    "alert": alert["rule"],
                    "severity": alert["severity"],
                    f"alert/{alert['rule']}": 1.0,
                },
            )

    def _write_serve_anchor(self) -> None:
        """Atomic `heartbeat.s<replica>.json` with the tracer's wall
        anchor — scripts/trace_merge.py reads it to clock-align this
        replica's request spans with the training timeline."""
        rec = {
            "process": self.replica_index,
            "role": "serve",
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "time": time.time(),
            "trace_wall_t0": self._tracer.wall_t0,
        }
        path = os.path.join(self.workdir, f"heartbeat.s{self.replica_index}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)

    # -- metrics ---------------------------------------------------------

    def stats(self) -> dict:
        # the whole snapshot sits under _index_lock so the gauge line is
        # CONSISTENT: index_rows/ingested_rows/ivf gauges can't interleave
        # with a concurrent /ingest mid-read (JX012). This nests
        # serve.index -> serve.metrics (payload takes the metrics lock
        # inside) — the one sanctioned order; tsan's runtime order graph
        # watches it and the deadlock@site chaos leg inverts it on purpose.
        with self._index_lock:
            return self._stats_locked()

    def _stats_locked(self) -> dict:
        out = self.metrics.payload()
        out["serve/recompiles_after_warmup"] = self.engine.recompiles_after_warmup
        # retrieval-tier gauges: which path answers /neighbors by default
        # (schema: numbers only — nprobe null on exact tiers) and whether
        # scoring runs quantized anywhere (index int8 mirror or engine PTQ)
        out["serve/nprobe"] = (
            (self.nprobe or (self.index._ivf or {}).get("nprobe"))
            if self.index is not None and self.neighbors_mode.startswith("ivf")
            else None
        )
        out["serve/int8"] = int(
            self.neighbors_mode.endswith("_i8") or getattr(self.engine, "int8", False)
        )
        # engine quantization tier as a scraped gauge: 0=off, 1=w8
        # (weight-only PTQ), 2=w8a8 (activation-quantized int8)
        out["serve/quant_tier"] = {"off": 0, "w8": 1, "w8a8": 2}.get(
            getattr(self.engine, "quant", "off"), 0
        )
        # served-model identity + ingest provenance (obs/quality.py):
        # the model plane's version gauges — the router's skew gauge
        # and the promotion pipeline's evidence both read these
        out["serve/model_step"] = self.model_step
        out["serve/model_digest"] = self.model_digest
        out["serve/ingest_ckpt_step"] = self.ingest_ckpt_step
        if self.fresh is not None:
            out.update(self.fresh.payload())
        if self.index is not None:
            ages = self.index.row_age_stats()
            out["serve/row_age_max_s"] = ages["row_age_max_s"]
            out["serve/row_age_mean_s"] = ages["row_age_mean_s"]
            out["serve/index_rows"] = self.index.count
            out["serve/ingested_rows"] = self.ingested_rows
            out["serve/recompiles_after_warmup"] += self.index.recompiles_after_warmup
            # coarse-quantizer health (ROADMAP's future re-fit trigger):
            # rows the IVF could not place (served exactly instead) and
            # mean cell fill — null until train_ivf has run
            ivf_stats = self.index.ivf_stats()
            out["serve/ivf_spill"] = (
                ivf_stats["spilled"] if ivf_stats.get("trained") else None
            )
            out["serve/ivf_occupancy"] = (
                ivf_stats["occupancy"] if ivf_stats.get("trained") else None
            )
        return out

    def _flush_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self._write_metrics()

    def _write_metrics(self) -> None:
        """One off-path observability turn: snapshot the gauges, feed
        the flight ring + alert engine (a fired rule dumps the ring via
        `_on_alert`), render pending request spans, then fan the line
        out to the sink."""
        self._flush_step += 1  # mocolint: disable=JX012  (same join-serialization as _lane: the alert hook fires ON the flusher thread, and close() joins the flusher before the final flush — one writer at a time by construction)
        try:
            if self.fresh is not None:
                # one freshness observation per flush: the index's max
                # row age vs the declared objective (None = empty index,
                # not stale). Sampled under the index lock, recorded
                # outside it (obs.slo after serve.index is NOT a
                # sanctioned nesting — keep them disjoint).
                age = None
                if self.index is not None:
                    with self._index_lock:
                        age = self.index.row_age_stats()["row_age_max_s"]
                self.fresh.record(age)
            payload = self.stats()
            self.flight.record_metrics(self._flush_step, payload)
            if self._alerts is not None:
                self._alerts.observe(self._flush_step, payload)
            self._drain_spans()
            if self._sink is not None:
                self._sink.write(self._flush_step, payload)
        except Exception as e:  # metrics must never take serving down
            print(f"WARNING: serve metrics sink failed: {e!r}", flush=True)

    # -- lifecycle -------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown, phase one: healthz flips not-ok (a fleet
        router stops dispatching here), then the batcher drains — every
        request already accepted is flushed, not failed. The HTTP server
        itself stays up (healthz must answer mid-drain); follow with
        `close()`. Idempotent; True = the flush finished in time. This
        is the server half of the SIGTERM path (`replica_main`) and of
        `POST /admin/drain`."""
        already = self._draining.is_set()
        self._draining.set()
        if already and self.batcher.closed:
            return True  # second drain call: nothing left to flush
        return self.batcher.drain(timeout=timeout)

    def close(self) -> None:
        """Shut down HTTP, batcher, and flusher; join all three threads
        (the obs/sinks.py PrometheusSink close discipline). A final
        metrics flush lands the run's last gauges in the sink."""
        self._stop.set()
        self._flusher.join(timeout=5.0)
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        self.batcher.close()
        self._write_metrics()
        if self._alerts is not None:
            self._alerts.close()
        if self._own_tracer is not None:
            self._own_tracer.close()


def _query_param(query: str, name: str) -> str | None:
    for part in query.split("&"):
        if part.startswith(name + "="):
            return part[len(name) + 1 :] or None
    return None


def _query_k(query: str, default: int) -> int:
    val = _query_param(query, "k")
    if val is not None:
        try:
            return max(1, min(int(val), default))
        except ValueError:
            pass
    return default


__all__ = [
    "DEFAULT_NEIGHBORS_K",
    "DEFAULT_RECALL_SAMPLE_EVERY",
    "ServeServer",
    "resolve_serve_port",
]
