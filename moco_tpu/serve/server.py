"""The embedding service's HTTP front end (stdlib, like the Prometheus
sink it runs alongside).

Endpoints:

- `POST /embed` — body: raw uint8 pixels, `X-Image-Shape: n,h,w,c`
  header (h/w/c must match the engine). Response JSON:
  `{"embedding": [[...f32...]]}` (L2-normalized backbone features).
- `POST /neighbors` — same body; `?k=5` (default 5, capped at the
  prepared k) and `?mode=exact|ivf|exact_i8|ivf_i8` (default: the
  server's `neighbors_mode`). Response adds
  `{"indices": [[...]], "scores": [[...]], "mode": "..."}` — top-k
  cosine rows of the sharded EmbeddingIndex, i.e. the MoCo dictionary
  look-up as a product; `ivf` scans only the `nprobe` nearest cells
  (sub-linear — serve/index.py), the int8 modes score quantized.
- `POST /ingest` — body: raw float32 rows, `X-Rows-Shape: n,d` header.
  FIFO-ingests a block into the live index (the streaming-updates path
  `scripts/serve_ingest.py` drives from a training checkpoint dir);
  IVF cell membership and the int8 mirror follow incrementally.
- `GET /stats` — the live `serve/*` gauge snapshot as JSON.
- `GET /healthz` — `{"ok": true, "warm": ...}` once the AOT warmup ran.

Recall estimation: with an approximate `neighbors_mode`, every
`recall_sample_every`-th neighbors micro-batch ALSO runs the exact
oracle on the same device features and records the top-k overlap —
`serve/recall_estimate` in the metric flush, the gauge the smoke's
recall floor (and the CONTRIBUTING review gate) reads.

Request rows flow through the ContinuousBatcher (coalescing under the
SLO), so concurrent clients share padded-bucket executions; handler
threads only block on their own future. Metrics flow into the standard
obs sinks: a flusher thread writes `ServeMetrics.payload()` every
`metrics_flush_s` (schema-validated `serve/*` family; with a Prometheus
sink attached each gauge is scraped as `moco_serve_<name>`).

Ports: `resolve_serve_port` (obs/sinks.py) applies the offset rule so
a process running both the server and `--metrics-port` can't collide —
Prometheus owns `metrics_port + process_index`, the server claims
`serve_port + process_index` and shifts by SERVE_PORT_STRIDE when the
two meet.

Thread hygiene (JX011): the HTTP server thread and the metrics flusher
are both joined in `close()`, the flusher polls a stop event, and the
batcher's own close fails stragglers loudly.
"""

from __future__ import annotations

import http.server
import json
import threading

import numpy as np

from moco_tpu.obs.sinks import resolve_serve_port  # noqa: F401  (public API)
from moco_tpu.serve.batcher import BatcherClosedError, ContinuousBatcher, ServeMetrics
from moco_tpu.serve.index import QUERY_MODES

DEFAULT_NEIGHBORS_K = 5
DEFAULT_RECALL_SAMPLE_EVERY = 8


class ServeServer:
    """HTTP front end binding engine + index + batcher (module
    docstring). `port=0` binds ephemeral (tests/smoke); `self.port` is
    the actual one. `index=None` serves `/embed` only (`/neighbors`
    answers 503). `sink=None` keeps metrics in-process (`/stats` only).
    """

    def __init__(
        self,
        engine,
        index=None,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_port: int = 0,
        process_index: int = 0,
        slo_ms: float = 100.0,
        neighbors_k: int = DEFAULT_NEIGHBORS_K,
        neighbors_mode: str = "exact",
        nprobe: int = 0,
        recall_sample_every: int = DEFAULT_RECALL_SAMPLE_EVERY,
        sink=None,
        metrics_flush_s: float = 1.0,
        warmup: bool = True,
    ):
        if neighbors_mode not in QUERY_MODES:
            raise ValueError(
                f"neighbors_mode must be one of {QUERY_MODES}, got {neighbors_mode!r}"
            )
        self.engine = engine
        self.index = index
        self.neighbors_k = int(neighbors_k)
        self.neighbors_mode = neighbors_mode
        self.nprobe = int(nprobe) or None
        self.recall_sample_every = int(recall_sample_every)
        self.metrics = ServeMetrics(slo_ms)
        self._sink = sink
        self._flush_step = 0
        self._neighbor_flushes = 0
        self.ingested_rows = 0
        # one lock covers every index touch: a donated ingest write must
        # never invalidate a rows buffer a query is reading mid-flight
        self._index_lock = threading.Lock()
        if warmup:
            engine.warmup()
            if index is not None:
                # the exact tier is always prepared: it is the oracle the
                # recall estimator scores against and the fallback tier
                modes = {"exact", neighbors_mode}
                index.prepare(
                    engine.buckets, self.neighbors_k,
                    nprobe=self.nprobe, modes=sorted(modes),
                )
                index.freeze()
                self._prepared_modes = modes
        if not hasattr(self, "_prepared_modes"):
            # warmup=False: the caller prepared the index; accept any mode
            self._prepared_modes = set(QUERY_MODES)
        self.batcher = ContinuousBatcher(
            self._run_batch,
            max_batch=engine.buckets[-1],
            slo_ms=slo_ms,
            metrics=self.metrics,
        )
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                if path == "/healthz":
                    self._json(200, {"ok": True, "warm": server.engine.recompiles_after_warmup == 0})
                elif path == "/stats":
                    self._json(200, server.stats())
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                path, _, query = self.path.partition("?")
                if path == "/ingest":
                    self._handle_ingest()
                    return
                if path not in ("/embed", "/neighbors"):
                    self.send_error(404)
                    return
                try:
                    images = self._read_images()
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                want_neighbors = path == "/neighbors"
                if want_neighbors and server.index is None:
                    self._json(503, {"error": "no embedding index attached"})
                    return
                mode = None
                if want_neighbors:
                    mode = _query_param(query, "mode")
                    if mode is not None and (
                        mode not in QUERY_MODES or mode not in server._prepared_modes
                    ):
                        self._json(400, {
                            "error": f"mode {mode!r} not prepared on this replica "
                            f"(serving: {sorted(server._prepared_modes)})"
                        })
                        return
                try:
                    fut = server.batcher.submit(
                        images, want_neighbors=want_neighbors, mode=mode
                    )
                    out = fut.result(timeout=30.0)
                except (BatcherClosedError, TimeoutError) as e:
                    self._json(503, {"error": str(e)})
                    return
                body = {"embedding": out["embedding"].tolist()}
                if want_neighbors:
                    k = _query_k(query, server.neighbors_k)
                    eff = mode or server.neighbors_mode
                    body["indices"] = out[f"indices:{eff}"][:, :k].tolist()
                    body["scores"] = out[f"scores:{eff}"][:, :k].tolist()
                    body["mode"] = eff
                self._json(200, body)

            def _handle_ingest(self):
                """FIFO-ingest a raw f32 row block into the live index —
                the wire the streaming updater (scripts/serve_ingest.py)
                pushes fresh training-queue rows over."""
                if server.index is None:
                    self._json(503, {"error": "no embedding index attached"})
                    return
                try:
                    shape_hdr = self.headers.get("X-Rows-Shape", "")
                    try:
                        n, d = (int(s) for s in shape_hdr.split(","))
                    except ValueError:
                        raise ValueError(f"bad X-Rows-Shape header {shape_hdr!r}")
                    if d != server.index.dim:
                        raise ValueError(
                            f"row dim {d} != index dim {server.index.dim}"
                        )
                    length = int(self.headers.get("Content-Length", 0))
                    if length != n * d * 4:
                        raise ValueError(
                            f"Content-Length {length} != n*d*4 = {n * d * 4}"
                        )
                    rows = np.frombuffer(
                        self.rfile.read(length), np.float32
                    ).reshape(n, d)
                    with server._index_lock:
                        server.index.add(rows)
                        server.ingested_rows += n
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                self._json(200, {
                    "ingested": n,
                    "index_rows": server.index.count,
                    "total_ingested": server.ingested_rows,
                })

            def _read_images(self) -> np.ndarray:
                shape_hdr = self.headers.get("X-Image-Shape", "")
                try:
                    shape = tuple(int(s) for s in shape_hdr.split(","))
                except ValueError:
                    raise ValueError(f"bad X-Image-Shape header {shape_hdr!r}")
                if len(shape) != 4:
                    raise ValueError("X-Image-Shape must be 'n,h,w,c'")
                n = int(self.headers.get("Content-Length", 0))
                expected = 1
                for s in shape:
                    expected *= s
                if n != expected:
                    raise ValueError(
                        f"Content-Length {n} != prod(X-Image-Shape) {expected}"
                    )
                return np.frombuffer(self.rfile.read(n), np.uint8).reshape(shape)

            def _json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr lines
                pass

        resolved = resolve_serve_port(port, metrics_port, process_index)
        self._server = http.server.ThreadingHTTPServer((host, resolved), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serve_http", daemon=True
        )
        self._thread.start()
        self._stop = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, args=(float(metrics_flush_s),),
            name="serve_metrics_flush", daemon=True,
        )
        self._flusher.start()

    # -- request path ----------------------------------------------------

    def _run_batch(self, images, want_neighbors, modes=()):
        """Batcher thread body: ONE padded engine execution per flush,
        then one index query per requested tier on the same features
        (the scans are small matmuls next to the encoder forward);
        /embed riders just drop the extra keys at scatter. With an
        approximate default tier, every `recall_sample_every`-th
        neighbors flush also runs the exact oracle and records the
        top-k overlap (`serve/recall_estimate`)."""
        if want_neighbors and self.index is not None:
            requested = {self.neighbors_mode} | set(modes)
            approx = next(
                (m for m in (self.neighbors_mode, *sorted(requested))
                 if m.startswith("ivf")),
                None,
            )
            sample_recall = False
            if approx is not None and self.recall_sample_every > 0:
                self._neighbor_flushes += 1
                if self._neighbor_flushes % self.recall_sample_every == 0:
                    sample_recall = True
                    requested.add("exact")
            with self._index_lock:
                emb, per_mode, executed = self.engine.embed_and_query_modes(
                    images, self.index, self.neighbors_k,
                    modes=tuple(sorted(requested)), nprobe=self.nprobe,
                )
            if sample_recall:
                _, exact_idx = per_mode["exact"]
                _, approx_idx = per_mode[approx]
                k = exact_idx.shape[1]
                overlap = np.asarray([
                    len(set(exact_idx[i]) & set(approx_idx[i]))
                    for i in range(exact_idx.shape[0])
                ])
                self.metrics.record_recall(float(overlap.mean()) / k)
            results = {"embedding": emb}
            for m, (scores, idx) in per_mode.items():
                results[f"scores:{m}"] = scores
                results[f"indices:{m}"] = idx
            return results, executed
        emb, executed = self.engine.embed(images)
        return {"embedding": emb}, executed

    # -- metrics ---------------------------------------------------------

    def stats(self) -> dict:
        out = self.metrics.payload()
        out["serve/recompiles_after_warmup"] = self.engine.recompiles_after_warmup
        # retrieval-tier gauges: which path answers /neighbors by default
        # (schema: numbers only — nprobe null on exact tiers) and whether
        # scoring runs quantized anywhere (index int8 mirror or engine PTQ)
        out["serve/nprobe"] = (
            (self.nprobe or (self.index._ivf or {}).get("nprobe"))
            if self.index is not None and self.neighbors_mode.startswith("ivf")
            else None
        )
        out["serve/int8"] = int(
            self.neighbors_mode.endswith("_i8") or getattr(self.engine, "int8", False)
        )
        if self.index is not None:
            out["serve/index_rows"] = self.index.count
            out["serve/ingested_rows"] = self.ingested_rows
            out["serve/recompiles_after_warmup"] += self.index.recompiles_after_warmup
        return out

    def _flush_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self._write_metrics()

    def _write_metrics(self) -> None:
        if self._sink is None:
            return
        self._flush_step += 1
        try:
            self._sink.write(self._flush_step, self.stats())
        except Exception as e:  # metrics must never take serving down
            print(f"WARNING: serve metrics sink failed: {e!r}", flush=True)

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Shut down HTTP, batcher, and flusher; join all three threads
        (the obs/sinks.py PrometheusSink close discipline). A final
        metrics flush lands the run's last gauges in the sink."""
        self._stop.set()
        self._flusher.join(timeout=5.0)
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        self.batcher.close()
        self._write_metrics()


def _query_param(query: str, name: str) -> str | None:
    for part in query.split("&"):
        if part.startswith(name + "="):
            return part[len(name) + 1 :] or None
    return None


def _query_k(query: str, default: int) -> int:
    val = _query_param(query, "k")
    if val is not None:
        try:
            return max(1, min(int(val), default))
        except ValueError:
            pass
    return default


__all__ = [
    "DEFAULT_NEIGHBORS_K",
    "DEFAULT_RECALL_SAMPLE_EVERY",
    "ServeServer",
    "resolve_serve_port",
]
