"""The embedding service's HTTP front end (stdlib, like the Prometheus
sink it runs alongside).

Endpoints:

- `POST /embed` — body: raw uint8 pixels, `X-Image-Shape: n,h,w,c`
  header (h/w/c must match the engine). Response JSON:
  `{"embedding": [[...f32...]]}` (L2-normalized backbone features).
- `POST /neighbors` — same body; `?k=5` (default 5, capped at the
  prepared k). Response adds `{"indices": [[...]], "scores": [[...]]}`
  — top-k cosine rows of the sharded EmbeddingIndex, i.e. the MoCo
  dictionary look-up as a product.
- `GET /stats` — the live `serve/*` gauge snapshot as JSON.
- `GET /healthz` — `{"ok": true, "warm": ...}` once the AOT warmup ran.

Request rows flow through the ContinuousBatcher (coalescing under the
SLO), so concurrent clients share padded-bucket executions; handler
threads only block on their own future. Metrics flow into the standard
obs sinks: a flusher thread writes `ServeMetrics.payload()` every
`metrics_flush_s` (schema-validated `serve/*` family; with a Prometheus
sink attached each gauge is scraped as `moco_serve_<name>`).

Ports: `resolve_serve_port` (obs/sinks.py) applies the offset rule so
a process running both the server and `--metrics-port` can't collide —
Prometheus owns `metrics_port + process_index`, the server claims
`serve_port + process_index` and shifts by SERVE_PORT_STRIDE when the
two meet.

Thread hygiene (JX011): the HTTP server thread and the metrics flusher
are both joined in `close()`, the flusher polls a stop event, and the
batcher's own close fails stragglers loudly.
"""

from __future__ import annotations

import http.server
import json
import threading

import numpy as np

from moco_tpu.obs.sinks import resolve_serve_port  # noqa: F401  (public API)
from moco_tpu.serve.batcher import BatcherClosedError, ContinuousBatcher, ServeMetrics

DEFAULT_NEIGHBORS_K = 5


class ServeServer:
    """HTTP front end binding engine + index + batcher (module
    docstring). `port=0` binds ephemeral (tests/smoke); `self.port` is
    the actual one. `index=None` serves `/embed` only (`/neighbors`
    answers 503). `sink=None` keeps metrics in-process (`/stats` only).
    """

    def __init__(
        self,
        engine,
        index=None,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_port: int = 0,
        process_index: int = 0,
        slo_ms: float = 100.0,
        neighbors_k: int = DEFAULT_NEIGHBORS_K,
        sink=None,
        metrics_flush_s: float = 1.0,
        warmup: bool = True,
    ):
        self.engine = engine
        self.index = index
        self.neighbors_k = int(neighbors_k)
        self.metrics = ServeMetrics(slo_ms)
        self._sink = sink
        self._flush_step = 0
        if warmup:
            engine.warmup()
            if index is not None:
                index.prepare(engine.buckets, self.neighbors_k)
                index.freeze()
        self.batcher = ContinuousBatcher(
            self._run_batch,
            max_batch=engine.buckets[-1],
            slo_ms=slo_ms,
            metrics=self.metrics,
        )
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                if path == "/healthz":
                    self._json(200, {"ok": True, "warm": server.engine.recompiles_after_warmup == 0})
                elif path == "/stats":
                    self._json(200, server.stats())
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                path, _, query = self.path.partition("?")
                if path not in ("/embed", "/neighbors"):
                    self.send_error(404)
                    return
                try:
                    images = self._read_images()
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                want_neighbors = path == "/neighbors"
                if want_neighbors and server.index is None:
                    self._json(503, {"error": "no embedding index attached"})
                    return
                try:
                    fut = server.batcher.submit(images, want_neighbors=want_neighbors)
                    out = fut.result(timeout=30.0)
                except (BatcherClosedError, TimeoutError) as e:
                    self._json(503, {"error": str(e)})
                    return
                body = {"embedding": out["embedding"].tolist()}
                if want_neighbors:
                    k = _query_k(query, server.neighbors_k)
                    body["indices"] = out["indices"][:, :k].tolist()
                    body["scores"] = out["scores"][:, :k].tolist()
                self._json(200, body)

            def _read_images(self) -> np.ndarray:
                shape_hdr = self.headers.get("X-Image-Shape", "")
                try:
                    shape = tuple(int(s) for s in shape_hdr.split(","))
                except ValueError:
                    raise ValueError(f"bad X-Image-Shape header {shape_hdr!r}")
                if len(shape) != 4:
                    raise ValueError("X-Image-Shape must be 'n,h,w,c'")
                n = int(self.headers.get("Content-Length", 0))
                expected = 1
                for s in shape:
                    expected *= s
                if n != expected:
                    raise ValueError(
                        f"Content-Length {n} != prod(X-Image-Shape) {expected}"
                    )
                return np.frombuffer(self.rfile.read(n), np.uint8).reshape(shape)

            def _json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr lines
                pass

        resolved = resolve_serve_port(port, metrics_port, process_index)
        self._server = http.server.ThreadingHTTPServer((host, resolved), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serve_http", daemon=True
        )
        self._thread.start()
        self._stop = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, args=(float(metrics_flush_s),),
            name="serve_metrics_flush", daemon=True,
        )
        self._flusher.start()

    # -- request path ----------------------------------------------------

    def _run_batch(self, images, want_neighbors):
        """Batcher thread body: one padded engine execution per flush.
        Neighbors are computed for the whole micro-batch when ANY rider
        wants them (the index scan is a small matmul next to the encoder
        forward); /embed riders just drop the extra keys at scatter."""
        if want_neighbors and self.index is not None:
            emb, scores, idx, executed = self.engine.embed_and_query(
                images, self.index, self.neighbors_k
            )
            return {"embedding": emb, "scores": scores, "indices": idx}, executed
        emb, executed = self.engine.embed(images)
        return {"embedding": emb}, executed

    # -- metrics ---------------------------------------------------------

    def stats(self) -> dict:
        out = self.metrics.payload()
        out["serve/recompiles_after_warmup"] = self.engine.recompiles_after_warmup
        if self.index is not None:
            out["serve/index_rows"] = self.index.count
            out["serve/recompiles_after_warmup"] += self.index.recompiles_after_warmup
        return out

    def _flush_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self._write_metrics()

    def _write_metrics(self) -> None:
        if self._sink is None:
            return
        self._flush_step += 1
        try:
            self._sink.write(self._flush_step, self.stats())
        except Exception as e:  # metrics must never take serving down
            print(f"WARNING: serve metrics sink failed: {e!r}", flush=True)

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Shut down HTTP, batcher, and flusher; join all three threads
        (the obs/sinks.py PrometheusSink close discipline). A final
        metrics flush lands the run's last gauges in the sink."""
        self._stop.set()
        self._flusher.join(timeout=5.0)
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        self.batcher.close()
        self._write_metrics()


def _query_k(query: str, default: int) -> int:
    for part in query.split("&"):
        if part.startswith("k="):
            try:
                return max(1, min(int(part[2:]), default))
            except ValueError:
                break
    return default


__all__ = ["DEFAULT_NEIGHBORS_K", "ServeServer", "resolve_serve_port"]
