"""The metrics.jsonl line schema, as code.

README's "metrics.jsonl line format" section is the human contract;
this module is the machine-checkable one — the golden schema test
(tests/test_obs.py), `scripts/obs_smoke.py`, and `scripts/obs_report.py
--strict` all validate against it, so the README can't silently rot.

Line kinds (all carry `step` int + `time` float):

- *training lines*: `loss` present -> require `epoch`/`lr`/`acc1`/
  `acc5`; optionally the step-time breakdown (`t_data`/`t_step`, and
  `t_dispatch`/`t_device` on probe-sampled lines), the input-wire
  gauges (`t_transfer`/`transfer_bytes`/`prefetch_depth_live` when the
  device prefetch ring is on), device-memory gauges
  (`hbm_live_bytes`/`hbm_peak_bytes`, number or null), health gauges
  (`ema_drift*`, `logit_*`, `feature_*`, `queue_age_*`), and the fault
  counters (`nan_steps`/`decode_failures`/`io_retries` when nonzero,
  `compile_cache_misses` under --strict-tracing);
- *event lines*: `event` in EVENT_KINDS instead of the metric fields
  (alert events additionally carry `alert`/`severity` and an
  `alert/<rule>` Prometheus gauge);
- *aux lines*: neither (e.g. the periodic `knn_top1` line).

Fleet-observability fields (obs/fleet.py, obs/comms.py) ride training
lines: `straggler_skew`/`fleet_hosts` plus the
`fleet/<field>_{min,mean,max,argmax}` family on process 0, and the
analytic `comms/<site>` bytes-per-step counters on every process.

Serving lines (serve/server.py's flusher) carry the `serve/*` family,
including the request-scoped surface (PR 10): `serve/trace_<stage>_ms`
stage-waterfall means, `serve/burn_rate_<w>s` SLO burn rates,
`serve/latency_hist` (a structured cumulative-histogram payload), and
the `serve/p99_exemplar` request id — the one STRING inside the
numeric family, which is why explicit field validators take precedence
over the prefix families in `validate_line`.

Numbers are finite or null — NaN/Inf literals are rejected at parse
time (`loads_strict`), matching the writer's scrubbing.

Deliberately stdlib-only so report tooling can import it anywhere.
"""

from __future__ import annotations

import json
from typing import Any, Iterable

EVENT_KINDS = frozenset(
    {"nonfinite_loss", "stall", "recompile_after_warmup", "alert",
     # graceful-preemption exit (SIGTERM -> emergency checkpoint) and
     # the elastic checkpoint-and-rescale (parallel/elastic.py): the
     # rescale line carries the rescale/* family below — old/new mesh
     # shape, old/new global batch, and the re-derived hyperparameters
     "preempt", "rescale",
     # checkpoint-promotion audit lines (serve/promote.py
     # PromotionLedger): verdict + per-gate evidence in the promotion/*
     # family below
     "promotion"}
)

TRAIN_REQUIRED = ("epoch", "lr", "loss", "acc1", "acc5")

# field -> validator; a field listed here, when present, must satisfy it
_NUMBER = (int, float)


def _num(v: Any) -> bool:
    return isinstance(v, _NUMBER) and not isinstance(v, bool)


def _num_or_null(v: Any) -> bool:
    return v is None or _num(v)


def _int_like(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _num_list(v: Any) -> bool:
    return isinstance(v, list) and all(_num_or_null(x) for x in v)


def _counter_map(v: Any) -> bool:
    return isinstance(v, dict) and all(
        isinstance(k, str) and _int_like(n) for k, n in v.items()
    )


def _nonneg_or_null(v: Any) -> bool:
    return v is None or (_num(v) and v >= 0)


def _str_or_null(v: Any) -> bool:
    return v is None or isinstance(v, str)


def _latency_hist(v: Any) -> bool:
    """The cumulative-histogram payload the Prometheus sink renders as
    `<name>_bucket{le=...}`: finite ascending bucket bounds (ms), one
    count per bucket plus the +Inf overflow slot, and the sum/count
    pair. Counts are PER-BUCKET here; the sink cumulates at render."""
    if not isinstance(v, dict):
        return False
    le, counts = v.get("le"), v.get("counts")
    return (
        isinstance(le, list)
        and all(_num(x) for x in le)
        and le == sorted(le)
        and isinstance(counts, list)
        and len(counts) == len(le) + 1
        and all(_int_like(c) and c >= 0 for c in counts)
        and _num(v.get("sum"))
        and _int_like(v.get("count"))
    )


FIELD_VALIDATORS = {
    "step": _int_like,
    "time": _num,
    "epoch": _int_like,
    "lr": _num_or_null,
    "loss": _num_or_null,
    "acc1": _num_or_null,
    "acc5": _num_or_null,
    "knn_top1": _num_or_null,
    # step-time breakdown (obs/stepstats.py)
    "t_data": _num,
    "t_step": _num,
    "t_dispatch": _num_or_null,
    "t_device": _num,
    # input wire (data/device_prefetch.py — present when the device
    # prefetch ring is on): last batch's host→device transfer seconds,
    # its uint8 wire bytes, and how many staged batches were resident
    # when the driver consumed the last one (0 = the wire is the
    # bottleneck, depth = the device is)
    "t_transfer": _num,
    "transfer_bytes": _int_like,
    "prefetch_depth_live": _int_like,
    # device memory gauges (null where the backend lacks memory_stats)
    "hbm_live_bytes": _num_or_null,
    "hbm_peak_bytes": _num_or_null,
    # remaining HBM at the live watermark (bytes_limit - live; null
    # where the backend reports no capacity) — the headroom the ZeRO
    # stages compete on
    "hbm_headroom_bytes": _num_or_null,
    # analytic per-device at-rest bytes of the persistent train state
    # (obs/stepstats.py tree_shard_bytes) — backend-independent, so the
    # ZeRO-1 vs ZeRO-2/3 memory A/B works on CPU meshes too
    "hbm_state_bytes": _int_like,
    # ZeRO-2/3 hoisted-gather overlap efficiency (parallel/zero.py
    # AsyncParamGather): 1 - wait/duration of the gather-side stall the
    # worker absorbed off the critical path (the synthetic
    # delay@site=zero.gather slow collective in the smokes); null when
    # nothing was absorbed — device-side gather/compute overlap is read
    # from the merged trace's zero_gather spans
    "overlap/zero": _num_or_null,
    # layer-granular ZeRO-3 (parallel/zero.py GroupPlan): same gauge
    # under its own key when the per-layer-group gather/free schedule is
    # active, so dashboards can A/B the two stages from the same run set
    "overlap/zero_layer": _num_or_null,
    # analytic per-device PEAK model-param bytes under ZeRO-2/3: shards
    # + the transient gathered full params (whole tree, or the largest
    # adjacent group pair when layer-granular) — the memory-claim gauge
    # that works on CPU meshes where memory_stats is absent
    "hbm_model_peak_bytes": _num_or_null,
    # MoCo health gauges (obs/health.py)
    "ema_drift": _num_or_null,
    "logit_pos_mean": _num_or_null,
    "logit_pos_std": _num_or_null,
    "logit_neg_mean": _num_or_null,
    "logit_neg_std": _num_or_null,
    "feature_std": _num_or_null,
    "feature_dim_active": _num_or_null,
    "queue_age_mean": _num_or_null,
    "queue_age_max": _num_or_null,
    "queue_age_hist": _num_list,
    # fault-tolerance counters (present only when nonzero)
    "nan_steps": _int_like,
    "decode_failures": _int_like,
    "io_retries": _counter_map,
    # mocolint runtime arm (present on every line under --strict-tracing)
    "compile_cache_misses": _int_like,
    # collective-schedule sanitizer (--sanitize-collectives): short hash
    # of this process's traced (site, kind, shape) collective schedule —
    # flat on a healthy run, and every process's must agree
    "collective_schedule_hash": lambda v: isinstance(v, str),
    "watchdog_timeout": _num,
    # serving retrieval tier (serve/server.py): the sampled online
    # recall of the approximate tier vs the exact oracle (a fraction —
    # null until the first sample), the IVF probe width (null when the
    # default tier is exact), whether scoring runs int8 anywhere (0/1),
    # and the streaming-ingest row counter. The generic serve/ prefix
    # family below still applies; these four get the tighter checks.
    "serve/recall_estimate": lambda v: v is None or (_num(v) and 0.0 <= v <= 1.0),
    "serve/nprobe": lambda v: v is None or (_int_like(v) and v >= 1),
    "serve/int8": lambda v: v in (0, 1),
    "serve/ingested_rows": _int_like,
    # raw-speed serving tiers (ISSUE 11): the engine quantization tier
    # (0=off, 1=w8 weight-only, 2=w8a8 activation-quantized int8) and
    # the IVF coarse-quantizer health gauges — rows the inverted file
    # could not place (spill; the exact tier still serves them) and the
    # mean cell fill over cell capacity. Both null until train_ivf runs;
    # ROADMAP names them as the background re-fit trigger.
    "serve/quant_tier": lambda v: v in (0, 1, 2),
    "serve/ivf_spill": lambda v: v is None or (_int_like(v) and v >= 0),
    "serve/ivf_occupancy": lambda v: v is None or (_num(v) and 0.0 <= v <= 1.0),
    # request-scoped serving observability (obs/reqtrace.py, obs/slo.py,
    # obs/flight.py — PR 10): the latency histogram the Prometheus sink
    # exposes with real cumulative buckets, the p99 exemplar linking the
    # latency gauges to the offending request id (a STRING — exempted
    # from the numeric serve/ prefix family below), its latency, the
    # declared SLO objective, and the measured tracing overhead the
    # bench serving leg reports
    "serve/latency_hist": _latency_hist,
    "serve/p99_exemplar": _str_or_null,
    "serve/p99_exemplar_ms": _nonneg_or_null,
    "serve/slo_objective": lambda v: _num(v) and 0.0 < v < 1.0,
    "serve/trace_overhead_pct": _num_or_null,
    # served-model identity (obs/quality.py): the checkpoint step the
    # live encoder came from (null when unknown — e.g. a hand-built
    # engine), its params content digest (a STRING, exempted from the
    # numeric serve/ family), and the checkpoint step of the last
    # /ingest block (X-Ckpt-Step; null until a tailer reports one)
    "serve/model_step": lambda v: v is None or _int_like(v),
    "serve/model_digest": _str_or_null,
    "serve/ingest_ckpt_step": lambda v: v is None or _int_like(v),
    # freshness SLO (obs/slo.py FreshnessBurnTracker + index row
    # stamps): wall-clock age of the oldest/mean stamped index row
    # (null while the index has no stamped rows) and the declared
    # max-age objective (strictly positive — a replica without a
    # freshness objective omits the whole family)
    "serve/row_age_max_s": _nonneg_or_null,
    "serve/row_age_mean_s": _nonneg_or_null,
    "serve/fresh_max_age_s": lambda v: _num(v) and v > 0,
    # embedding-space compatibility gauges (obs/quality.py): mean
    # probe cosine between live and candidate encoders, and top-k
    # neighbor overlap against the live index (null = not measured)
    "serve/compat_cosine": lambda v: v is None or (_num(v) and -1.0 <= v <= 1.0),
    "serve/recall_overlap": lambda v: v is None or (_num(v) and 0.0 <= v <= 1.0),
    # elastic rescale event lines (parallel/elastic.py): the lost host
    # indices (list of ints) ride the otherwise-numeric rescale/ family
    "rescale/dead_hosts": _num_list,
    "rescale/old_num_data": _int_like,
    "rescale/new_num_data": _int_like,
    "rescale/old_global_batch": _int_like,
    "rescale/new_global_batch": _int_like,
    # fleet observability (obs/fleet.py; process-0 lines only)
    "fleet_hosts": _int_like,
    "straggler_skew": _num_or_null,
    # serving-fleet router gauges (serve/router.py FleetRouter.stats):
    # topology counts are ints; the objective mirrors serve/slo_objective
    "fleet_serve/replicas": lambda v: _int_like(v) and v >= 1,
    "fleet_serve/replicas_healthy": lambda v: _int_like(v) and v >= 0,
    "fleet_serve/slo_objective": lambda v: _num(v) and 0.0 < v < 1.0,
    # cumulative cost of cancelled hedge lanes (serve/router.py hedge-
    # loser accounting) — a counter in ms, never negative
    "fleet_serve/hedge_wasted_ms": _nonneg_or_null,
    # fleet version skew (serve/router.py stats): distinct served model
    # digests minus one — 0 homogeneous, >0 mid-rollout; null until any
    # replica reports a digest
    "fleet_serve/model_skew": lambda v: v is None or (_int_like(v) and v >= 0),
    # promotion audit lines (serve/promote.py ledger_record): the
    # verdict enum, the pipeline stage, the candidate's params digest,
    # the first failed gate (null on success), and which replica a
    # rollout event refers to (null for fleet-wide lines). Per-gate
    # evidence rides the numeric promotion/ prefix family below.
    "promotion/verdict": lambda v: v in (
        "accepted", "rejected", "promoted", "rolled_back"
    ),
    "promotion/stage": lambda v: isinstance(v, str),
    "promotion/digest": _str_or_null,
    "promotion/failed_gate": _str_or_null,
    "promotion/replica": lambda v: v is None or _int_like(v),
    "promotion/step": _int_like,
    # scaling-law harness verdict lines (scripts/scaling_smoke.py): the
    # per-leg identity and the battery verdict are strings; every other
    # scaling/ field rides the numeric prefix family below
    "scaling/leg": lambda v: isinstance(v, str),
    "scaling/verdict": lambda v: isinstance(v, str),
    # alert event lines (obs/alerts.py)
    "alert": lambda v: isinstance(v, str),
    "severity": lambda v: v in ("warn", "fatal"),
}

# key-prefix families sharing one validator: per-layer-group EMA drift,
# the fleet min/mean/max/argmax gauges (null where no host reports the
# field), comms bytes counters (analytic, always numeric), the per-rule
# Prometheus alert gauges, and the serving metric family
# (serve/server.py flushes ServeMetrics.payload() through the sinks:
# p50_ms/p99_ms null before the first completed request, occupancy null
# before the first flush, the rest numeric — qps, requests,
# slo_violations, slo_ms, bucket_<b> histogram counts)
PREFIX_VALIDATORS = {
    "ema_drift/": _num_or_null,
    # elastic rescale event fields (kappa, derived lr/momentum, ...);
    # the explicit entries above (dead_hosts list, int mesh shapes) win
    "rescale/": _num_or_null,
    # scaling-law battery numerics (kappa, ema-drift ratios, logit gap,
    # feature_std floor, peak-bytes legs); the explicit string entries
    # above (scaling/leg, scaling/verdict) win
    "scaling/": _num_or_null,
    "fleet/": _num_or_null,
    "comms/": _num,
    "alert/": _num,
    "serve/": _num_or_null,
    # request-trace stage means (ms) and the multi-window SLO burn-rate
    # family — tighter than the generic serve/ family (burn/stage time
    # can be null while a window is empty, never negative). Longest
    # matching prefix wins (see validate_line), so these shadow serve/.
    "serve/trace_": _nonneg_or_null,
    "serve/burn_rate_": _nonneg_or_null,
    # the freshness-SLO burn twin (obs/slo.py FreshnessBurnTracker
    # payload) — same null-while-empty / never-negative contract
    "serve/fresh_burn_rate_": _nonneg_or_null,
    # the fleet-router family (serve/router.py): latency gauges null
    # before the first proxied request, counters numeric; the burn
    # sub-family (router client-observed + per-replica min/mean/max
    # aggregates) is never negative, like its serve/ twin
    "fleet_serve/": _num_or_null,
    # the router renames each replica's serve/burn_rate_* gauges into
    # this family dynamically ("fleet_serve/" + key.split("/", 1)[1]),
    # so no literal emission exists for JX015 to see; the runtime
    # contract-coverage gate proves the family live instead
    "fleet_serve/burn_rate_": _nonneg_or_null,  # mocolint: disable=JX015
    # the freshness burn aggregates ride the same dynamic rename, so
    # the same no-literal-emission exemption applies
    "fleet_serve/fresh_burn_rate_": _nonneg_or_null,  # mocolint: disable=JX015
    # critical-path hop attribution (obs/critpath.py metrics_payload):
    # mean ms on the request critical path per hop — never negative,
    # null while the aggregation window is empty
    "fleet_serve/critpath_": _nonneg_or_null,
    # promotion-ledger per-gate evidence (serve/promote.py):
    # promotion/gate/<name> measured value (null where a gate could not
    # run), promotion/floor/<name> declared threshold,
    # promotion/gate_ok/<name> 0/1 — the explicit entries above
    # (verdict/stage/digest/...) take precedence over this family
    "promotion/": _num_or_null,
}


def _reject_nonfinite(val: str):
    raise ValueError(f"non-finite JSON literal {val!r} (writer must scrub to null)")


def loads_strict(line: str) -> dict:
    """json.loads that rejects NaN/Infinity literals — the writer's
    scrub-to-null contract, enforced at parse time."""
    rec = json.loads(line, parse_constant=_reject_nonfinite)
    if not isinstance(rec, dict):
        raise ValueError("metrics line is not a JSON object")
    return rec


# Runtime contract-coverage arm (analysis/contracts.py): when a
# callback is installed, every validator that actually applies to a
# line — explicit field key or winning prefix family — is reported, so
# a smoke leg can prove its metrics stream still exercises the schema
# entries it claims to. None-checked per use: zero cost when off.
_COVERAGE_CB = None


def set_coverage_callback(cb) -> None:
    """Install/clear the `cb(validator_key)` applied-validator callback."""
    global _COVERAGE_CB
    _COVERAGE_CB = cb


def validate_line(rec: dict) -> list[str]:
    """Schema errors for one parsed line (empty list = valid)."""
    errors = []
    for k in ("step", "time"):
        if k not in rec:
            errors.append(f"missing required key {k!r}")
    if "event" in rec:
        if rec["event"] not in EVENT_KINDS:
            errors.append(f"unknown event kind {rec['event']!r}")
        if "loss" in rec:
            errors.append("event line must not carry metric field 'loss'")
    elif "loss" in rec:
        missing = [k for k in TRAIN_REQUIRED if k not in rec]
        if missing:
            errors.append(f"training line missing {missing}")
    for k, check in FIELD_VALIDATORS.items():
        if k in rec:
            if _COVERAGE_CB is not None:
                _COVERAGE_CB(k)
            if not check(rec[k]):
                errors.append(f"field {k!r} has invalid value {rec[k]!r}")
    # prefix families (ema_drift/<group>, fleet/<field>_<stat>,
    # comms/<site>, alert/<rule>, serve/...) share per-family
    # validators. An explicit FIELD_VALIDATORS entry wins outright
    # (serve/p99_exemplar is a string inside the numeric serve/
    # family); otherwise the LONGEST matching prefix applies, so
    # serve/burn_rate_* gets its non-negative check rather than the
    # looser serve/ one.
    for k, v in rec.items():
        if k in FIELD_VALIDATORS:
            continue
        matches = [p for p in PREFIX_VALIDATORS if k.startswith(p)]
        if matches:
            winner = max(matches, key=len)
            if _COVERAGE_CB is not None:
                _COVERAGE_CB(winner)
            if not PREFIX_VALIDATORS[winner](v):
                errors.append(f"field {k!r} has invalid value {v!r}")
    return errors


def validate_lines(lines: Iterable[str]) -> list[str]:
    """Errors across a whole metrics.jsonl body, tagged with 1-based
    line numbers. Parse failures (including NaN literals) are schema
    errors, not exceptions."""
    errors = []
    for i, line in enumerate(lines, 1):
        if not line.strip():
            continue
        try:
            rec = loads_strict(line)
        except ValueError as e:
            errors.append(f"line {i}: unparseable: {e}")
            continue
        errors.extend(f"line {i}: {e}" for e in validate_line(rec))
    return errors


def validate_file(path: str) -> list[str]:
    with open(path) as f:
        return validate_lines(f)


def read_metrics(path: str, strict: bool = True) -> list[dict]:
    """Parsed records of a metrics.jsonl — the loader obs_report builds
    on. `strict=True` raises on NaN literals / junk lines; with
    `strict=False` bad lines are skipped (the report of a crashed run
    must still render — validate_file reports them separately)."""
    out = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                out.append(loads_strict(line))
            except ValueError:
                if strict:
                    raise
    return out


def required_train_keys(strict_tracing: bool = False) -> tuple:
    """The keys every training line must carry (README contract);
    `strict_tracing` adds the always-present compile counter."""
    base = TRAIN_REQUIRED + ("t_data", "t_step", "hbm_live_bytes")
    return base + ("compile_cache_misses",) if strict_tracing else base
