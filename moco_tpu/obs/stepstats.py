"""Step-time breakdown probe + device-memory gauges.

Where does a step's wall time go? Four places the bare loss line can't
distinguish:

- *host data wait* — the step loop blocked on the prefetch queue
  (input-bound run);
- *wire* — host→device transfer of the batch (reported separately as
  `t_transfer` by the device prefetch ring, data/device_prefetch.py,
  which runs the wire on its own thread so it overlaps both of the
  stages below);
- *dispatch* — host-side time to enqueue the jitted step (tracing,
  argument placement, python overhead);
- *device compute* — the accelerator actually executing.

Because dispatch is async, `t_dispatch` alone says nothing about device
time. The probe separates them by calling `jax.block_until_ready` on
the step's outputs on SAMPLED steps only (`every` steps apart): the
block drains the device queue, so `t_device` ≈ the device-side tail of
this step. Off sampled steps the loop stays sync-free — the probe adds
zero cost to the hot path, same contract as the fault guards.

Device memory comes from `device.memory_stats()` (PjRt): live and peak
bytes in use. Backends without the API (CPU, some tunnels) return None
and the metrics line carries `null` — "unknown", never fake zero.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import numpy as np


class StepTimeProbe:
    """Per-step timing accumulator for the train loop.

    Usage per iteration:
        probe.data_wait(seconds)        # host blocked on input
        probe.dispatched(seconds)       # step_fn call returned (async)
        if probe.should_sample(step):
            t0 = time.perf_counter()
            jax.block_until_ready(outputs)
            probe.device_block(time.perf_counter() - t0)
        probe.step_done(total_seconds)

    `payload()` returns the fields for the metrics line: always
    `t_data`/`t_step`; `t_dispatch`/`t_device` from the most recent
    sampled step (absent until one happened).

    Under the software-pipelined driver loop (ISSUE 5) the log-step
    fetch is deferred one dispatch, so `step_done` receives the
    SMOOTHED per-step wall — (wall since the previous logged flush) /
    (steps since it) — rather than one bursty iteration's host wall;
    per-iteration wall under pipelining is just dispatch time and would
    read ~0 between throttle waits.
    """

    def __init__(self, every: int = 0):
        self.every = int(every)
        self.t_data = 0.0
        self.t_step = 0.0
        self._last_dispatch: Optional[float] = None
        self._t_dispatch: Optional[float] = None
        self._t_device: Optional[float] = None

    def should_sample(self, step: int) -> bool:
        return self.every > 0 and step % self.every == 0

    def data_wait(self, seconds: float) -> None:
        self.t_data = seconds

    def dispatched(self, seconds: float) -> None:
        self._last_dispatch = seconds

    def device_block(self, seconds: float) -> None:
        # a sampled step: the dispatch measured this iteration becomes
        # the published pair (dispatch, device)
        self._t_dispatch = self._last_dispatch
        self._t_device = seconds

    def step_done(self, seconds: float) -> None:
        self.t_step = seconds

    @property
    def last_dispatch(self) -> Optional[float]:
        """Most recent host-side dispatch time (every step, not just
        probe-sampled ones) — the fleet vector's dispatch-lag field."""
        return self._last_dispatch

    def payload(self) -> dict:
        out = {"t_data": self.t_data, "t_step": self.t_step}
        if self._t_device is not None:
            out["t_dispatch"] = self._t_dispatch
            out["t_device"] = self._t_device
        return out


def device_memory_stats(device=None) -> Optional[dict]:
    """{'hbm_live_bytes', 'hbm_peak_bytes'} for `device` (default: first
    local device), or None when the backend doesn't expose memory_stats
    (CPU hosts, some remote tunnels). Key names differ across PjRt
    versions; both spellings are probed."""
    if device is None:
        devices = jax.local_devices()
        if not devices:
            return None
        device = devices[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    live = stats.get("bytes_in_use", stats.get("bytes_in_use_current"))
    peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use_peak"))
    if live is None and peak is None:
        return None
    limit = stats.get("bytes_limit", stats.get("bytes_reservable_limit"))
    return {
        "hbm_live_bytes": int(live) if live is not None else None,
        "hbm_peak_bytes": int(peak) if peak is not None else None,
        # how much HBM is LEFT at the live watermark — the gauge the
        # ZeRO-2/3 work exists to raise (more headroom = bigger per-chip
        # batch); null where the backend reports no capacity
        "hbm_headroom_bytes": int(limit) - int(live)
        if limit is not None and live is not None
        else None,
    }


def memory_payload() -> dict:
    """Metrics-line fields for device memory: concrete gauges when the
    backend reports them, explicit nulls (schema-locked) otherwise."""
    stats = device_memory_stats()
    if stats is None:
        return {
            "hbm_live_bytes": None,
            "hbm_peak_bytes": None,
            "hbm_headroom_bytes": None,
        }
    return stats


def tree_shard_bytes(tree) -> int:
    """Analytic per-device bytes of a pytree's PERSISTENT arrays: each
    leaf contributes its shard size under its actual sharding (a
    replicated leaf costs its full bytes on every device; a
    P(data)-sharded ZeRO leaf 1/n). Backend-independent — this is the
    at-rest state footprint the CPU-mesh smokes compare across ZeRO
    stages, where `memory_stats` is unavailable."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        itemsize = np.dtype(dtype).itemsize
        if sharding is not None:
            try:
                shard_shape = sharding.shard_shape(tuple(shape))
                total += int(np.prod(shard_shape, dtype=np.int64)) * itemsize
                continue
            except Exception:
                pass  # exotic shardings: fall through to full bytes
        total += int(np.prod(shape, dtype=np.int64)) * itemsize
    return total


__all__ = [
    "StepTimeProbe",
    "device_memory_stats",
    "memory_payload",
    "tree_shard_bytes",
]
