"""Trace-context propagation for the serving fleet (router -> replica).

A request that crosses the fleet front door produces spans in TWO
processes: the router's dispatch taxonomy (serve/router.py) and the
replica's stage waterfall (obs/reqtrace.py). Without a shared identity
they are two disconnected timelines. This module is the identity layer:

- a **trace id** — 128 bits, hex, minted once per client request at the
  router's ingress (or adopted verbatim from a client that already
  carries one), identical across every hop of the request;
- a **span id** — 64 bits, hex, minted per span; the router mints one
  per dispatch *attempt* and sends it downstream, so the replica's
  request span can name its exact parent (which attempt of which retry
  round carried it — not just "some router request").

On the wire the pair rides two headers, registered in
`utils/contracts.py` ROUTES (`opt_headers` of /embed and /neighbors —
optional for plain clients, adopted by every handler):

    X-Trace-Id:    32 hex chars (the trace)
    X-Parent-Span: 16 hex chars (the sender's span)

`parse()` is the receiving side (strict: a malformed id is ignored, the
request is served untraced rather than rejected — tracing must never
fail a request). `inject()` is the sending side. Both report to the
contract-coverage recorder when one is installed, so the
`--contract-coverage` smoke arm can prove the headers are actually
exercised end to end.

Stdlib-only, like every obs module (trace_merge and the report tooling
import it on machines without jax).
"""

from __future__ import annotations

import os
from typing import Optional

TRACE_ID_HEADER = "X-Trace-Id"
PARENT_SPAN_HEADER = "X-Parent-Span"
TRACE_HEADERS = (TRACE_ID_HEADER, PARENT_SPAN_HEADER)

TRACE_ID_HEX_LEN = 32  # 128-bit trace id
SPAN_ID_HEX_LEN = 16  # 64-bit span id

_HEX = set("0123456789abcdef")


def new_trace_id() -> str:
    return os.urandom(TRACE_ID_HEX_LEN // 2).hex()


def new_span_id() -> str:
    return os.urandom(SPAN_ID_HEX_LEN // 2).hex()


def _valid_hex(value, length: int) -> bool:
    return (
        isinstance(value, str)
        and len(value) == length
        and set(value) <= _HEX
    )


class TraceContext:
    """One hop's view of the propagated context: the request's trace id
    plus the span id of the SENDER (i.e. the receiver's parent span)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"TraceContext(trace_id={self.trace_id!r}, span_id={self.span_id!r})"


def parse(trace_id, parent_span=None) -> Optional[TraceContext]:
    """Receiving side: header values -> context, or None when the trace
    id is absent/malformed (the request is served untraced — propagation
    must never reject traffic). A malformed parent span degrades to a
    parentless context rather than dropping the trace."""
    if not _valid_hex(trace_id, TRACE_ID_HEX_LEN):
        return None
    span = parent_span if _valid_hex(parent_span, SPAN_ID_HEX_LEN) else None
    _record_header(TRACE_ID_HEADER)
    if span is not None:
        _record_header(PARENT_SPAN_HEADER)
    return TraceContext(trace_id, span)


def extract(headers) -> Optional[TraceContext]:
    """`parse` over any mapping with `.get` (an http.client message, a
    plain dict) — convenience for non-handler callers; HTTP handlers
    read the header literals themselves (the JX016 registry extraction
    trusts literals at the read site)."""
    return parse(headers.get(TRACE_ID_HEADER), headers.get(PARENT_SPAN_HEADER))


def inject(headers: dict, ctx: TraceContext) -> dict:
    """Sending side: stamp the context onto an outbound header dict
    (mutated AND returned). `ctx.span_id` must be the span the receiver
    should parent under — for the router that is the dispatch-attempt
    span, not the request span."""
    headers[TRACE_ID_HEADER] = ctx.trace_id
    _record_header(TRACE_ID_HEADER)
    if ctx.span_id is not None:
        headers[PARENT_SPAN_HEADER] = ctx.span_id
        _record_header(PARENT_SPAN_HEADER)
    return headers


# -- contract-coverage hook (analysis/contracts.py recorder) --------------

_COVERAGE_CB = None


def set_coverage_callback(cb) -> None:
    """Install (or clear, with None) the header-coverage hook; the
    contract-coverage recorder wires `record_header` here."""
    global _COVERAGE_CB
    _COVERAGE_CB = cb


def _record_header(name: str) -> None:
    cb = _COVERAGE_CB
    if cb is not None:
        cb(name)


__all__ = [
    "PARENT_SPAN_HEADER",
    "SPAN_ID_HEX_LEN",
    "TRACE_HEADERS",
    "TRACE_ID_HEADER",
    "TRACE_ID_HEX_LEN",
    "TraceContext",
    "extract",
    "inject",
    "new_span_id",
    "new_trace_id",
    "parse",
    "set_coverage_callback",
]
