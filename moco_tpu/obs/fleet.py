"""Cross-host fleet aggregation + out-of-band host heartbeats.

PR 3's telemetry is strictly per-process: every host writes its own
metrics/trace files and nothing measures inter-host skew — on a pod a
single straggling host stretches every synchronous collective and the
only symptom is global wall clock. This module gives the driver a fleet
view at log-step cadence:

- `FleetAggregator`: each process contributes a small fixed-width
  per-host stats vector (`FLEET_FIELDS`: data wait, step wall, wire
  transfer time, dispatch lag, io retries, decode failures, live HBM);
  a jitted `all_gather` +
  reduction over a one-device-per-host mesh returns per-field
  min/mean/max/argmax plus a `straggler_skew` gauge — `(max(t_step) -
  mean(t_step)) / mean(t_step)`, the fraction of every step the fleet
  spends waiting for its slowest host. Process 0 merges the result into
  its metrics line, so one file answers "which host is slow, and by how
  much".

  Unknown values travel as NaN and aggregate with NaN-aware reductions,
  so a field no host reports (e.g. HBM on CPU) stays null in the line —
  same "unknown, never fake zero" contract as the memory gauges.

- `Heartbeat`: an out-of-band per-process file
  (`heartbeat.p<i>.json`, atomically replaced each beat) carrying the
  process's last step, wall time, and its tracer's wall-clock origin.
  It exists for the failure case the in-band path can't cover: when a
  host dies mid-run its metrics stop, but its heartbeat remains —
  `scripts/obs_report.py` merges heartbeats to name dead hosts, and
  `scripts/trace_merge.py` uses the wall origins for clock-offset
  correction when stitching per-process traces into one Perfetto file.

The aggregation is a real cross-process collective: every process must
call `gather()` at the same (deterministic) log steps — the driver
keys it on the replicated loss's log schedule, which all processes
agree on by construction.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FLEET_FIELDS = (
    "t_data",
    "t_step",
    # per-batch host→device transfer seconds (device prefetch ring,
    # data/device_prefetch.py) — lets straggler skew attribute to the
    # WIRE: a host whose t_step is fat but whose t_transfer is fatter
    # is PCIe/DMA-bound, not compute-bound. NaN on sync-path runs.
    "t_transfer",
    "dispatch_lag",
    "io_retries",
    "decode_failures",
    "hbm_live",
)


def reduce_stats(stats: jax.Array, t_step_index: int) -> dict:
    """Pure per-field reduction over an (n_hosts, n_fields) stats matrix.

    NaN-aware: a host that can't report a field contributes NaN, and a
    field nobody reports reduces to NaN (-> null in the line). Returns
    {'min','mean','max' (F,), 'argmax' (F,) int32, 'straggler_skew' ()}.
    Jit-compatible; shared by the live aggregator and the skew tests.
    """
    s = stats.astype(jnp.float32)
    mins = jnp.nanmin(s, axis=0)
    means = jnp.nanmean(s, axis=0)
    maxs = jnp.nanmax(s, axis=0)
    # argmax over NaN-padded columns: NaN -> -inf so a reporting host
    # always wins; an all-NaN column degrades to host 0 (meaningless
    # alongside a null max, which readers key on).
    argmax = jnp.argmax(jnp.where(jnp.isnan(s), -jnp.inf, s), axis=0).astype(jnp.int32)
    t = s[:, t_step_index]
    t_mean = jnp.nanmean(t)
    skew = (jnp.nanmax(t) - t_mean) / jnp.maximum(t_mean, 1e-12)
    return {
        "min": mins,
        "mean": means,
        "max": maxs,
        "argmax": argmax,
        "straggler_skew": skew,
    }


class FleetAggregator:
    """Jitted cross-host reduction of per-host stats vectors.

    Builds a 1-D `hosts` mesh with ONE representative device per
    process; each process's vector becomes its row of a (n_hosts, F)
    array sharded over that mesh, and the jitted reduce (replicated
    output) is the per-step all_gather. On a single process this
    degenerates to a trivial one-row reduce — the same code path runs
    everywhere, so every CI test exercises it.
    """

    def __init__(self, fields: Sequence[str] = FLEET_FIELDS):
        self.fields = tuple(fields)
        if "t_step" not in self.fields:
            raise ValueError("fleet fields must include 't_step' (skew is defined on it)")
        reps: dict[int, jax.Device] = {}
        for d in jax.devices():
            reps.setdefault(d.process_index, d)
        self.rep_devices = [reps[p] for p in sorted(reps)]
        self.num_hosts = len(self.rep_devices)
        self.process_index = jax.process_index()
        self._t_idx = self.fields.index("t_step")
        mesh = Mesh(np.asarray(self.rep_devices), ("hosts",))
        self._row_sharding = NamedSharding(mesh, P("hosts"))
        self._reduce = jax.jit(
            lambda s: reduce_stats(s, self._t_idx),
            out_shardings=NamedSharding(mesh, P()),
        )

    def host_vector(self, **values) -> np.ndarray:
        """(F,) float32 vector from per-field keyword values; missing or
        None fields become NaN ("unknown")."""
        unknown = set(values) - set(self.fields)
        if unknown:
            raise ValueError(f"unknown fleet fields {sorted(unknown)}; have {self.fields}")
        out = np.full((len(self.fields),), np.nan, np.float32)
        for i, name in enumerate(self.fields):
            v = values.get(name)
            if v is not None:
                out[i] = float(v)
        return out

    def gather(self, host_vector: np.ndarray) -> dict:
        """The per-step collective: contribute this host's vector, get
        the fleet reduction back (host numpy values, replicated — every
        process sees the same result). ALL processes must call this at
        the same step."""
        row = np.asarray(host_vector, np.float32).reshape(1, len(self.fields))
        local = jax.device_put(row, self.rep_devices[self.process_index])
        stats = jax.make_array_from_single_device_arrays(
            (self.num_hosts, len(self.fields)), self._row_sharding, [local]
        )
        return jax.device_get(self._reduce(stats))

    def payload(self, stats: dict) -> dict:
        """Metrics-line fields from a `gather()` result: per-field
        `fleet/<name>_{min,mean,max,argmax}`, `straggler_skew`, and the
        host count. NaNs pass through — the sink scrubs them to null."""
        out = {"fleet_hosts": self.num_hosts}
        for i, name in enumerate(self.fields):
            out[f"fleet/{name}_min"] = float(stats["min"][i])
            out[f"fleet/{name}_mean"] = float(stats["mean"][i])
            out[f"fleet/{name}_max"] = float(stats["max"][i])
            out[f"fleet/{name}_argmax"] = int(stats["argmax"][i])
        out["straggler_skew"] = float(stats["straggler_skew"])
        return out


# -- out-of-band heartbeats ----------------------------------------------


def heartbeat_path(workdir: str, process_index: int) -> str:
    return os.path.join(workdir, f"heartbeat.p{process_index}.json")


class Heartbeat:
    """Atomically-replaced per-process liveness file (see module
    docstring). `beat()` cost is one small JSON write + rename; the
    driver calls it on log steps only."""

    def __init__(self, workdir: str, process_index: int = 0, trace_wall_t0: Optional[float] = None):
        os.makedirs(workdir, exist_ok=True)
        self.process_index = int(process_index)
        self.path = heartbeat_path(workdir, self.process_index)
        self.trace_wall_t0 = trace_wall_t0
        self._host = socket.gethostname()
        self._pid = os.getpid()

    def beat(self, step: int = 0, epoch: int = 0, **extra) -> None:
        rec = {
            "process": self.process_index,
            "host": self._host,
            "pid": self._pid,
            "time": time.time(),
            "step": int(step),
            "epoch": int(epoch),
        }
        if self.trace_wall_t0 is not None:
            rec["trace_wall_t0"] = self.trace_wall_t0
        rec.update(extra)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)  # readers never see a torn write


def read_heartbeats(workdir: str) -> dict[int, dict]:
    """{process_index: last heartbeat record} for every heartbeat file
    under `workdir`. Unparseable files (a crash mid-rename is made
    impossible by the atomic replace, but a foreign file isn't) are
    skipped rather than fatal — the merge path runs on crashed runs."""
    import glob as _glob

    out: dict[int, dict] = {}
    for path in sorted(_glob.glob(os.path.join(workdir, "heartbeat.p*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
            out[int(rec["process"])] = rec
        except (ValueError, KeyError, OSError):
            continue
    return out


__all__ = [
    "FLEET_FIELDS",
    "FleetAggregator",
    "Heartbeat",
    "heartbeat_path",
    "read_heartbeats",
    "reduce_stats",
]
