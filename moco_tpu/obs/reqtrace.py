"""Request-scoped tracing for the serving stack.

The `serve/*` gauges are aggregate-only: they can say p99 rose, not
WHICH request was slow, WHICH stage ate the budget (queue wait vs pad
vs AOT execute vs IVF scan vs scatter), or WHICH replica served it.
This module is the per-request answer: a :class:`RequestTrace` is
created at ingress, rides the request's future through
`server.py -> batcher.py -> engine.py -> index.py`, and collects one
stamped interval per stage of the serving waterfall:

    ingress -> queue_wait -> batch_assemble -> engine_execute
            -> index_query -> scatter -> respond

Cost discipline: a stamp is one `time.perf_counter()` read plus a list
append, collected on the batcher thread (never a client thread); the
expensive parts — JSON encoding, span emission into the Perfetto
stream, flight-recorder bookkeeping — all happen off-path on the
server's metrics-flusher thread. With tracing off no trace object
exists and every hook is a single `is None` check (the bench serving
leg measures the residual as `serve/trace_overhead_pct`).

Request ids carry replica identity (`r<replica>-<seq>`), so a merged
multi-replica Perfetto timeline and the flight-recorder dumps stay
attributable once N processes serve behind a balancer — the
precondition the ROADMAP's multi-replica item names.

A request arriving through the fleet front door additionally carries a
propagated trace context (obs/ctxprop.py): the router's 128-bit trace
id plus the span id of the dispatch attempt that sent it. Adopting that
context (``new_trace(ctx=...)``) makes this replica's stage waterfall a
CHILD of the router's attempt span — the ids ride the waterfall dict
and the emitted `request` span, which is what the offline stitcher
(scripts/trace_merge.py) and the router's in-band stitching join on.

Stage semantics (batcher-granularity stages are shared by every rider
of a micro-batch — the per-request part is queue_wait):

- `ingress`       body read + parse on the handler thread, up to submit
- `queue_wait`    submit -> the flush that carried this request began
- `batch_assemble` concat + pad of the micro-batch
- `engine_execute` AOT encoder forward (device wait included when the
                  engine collects stages; the host sleep of an injected
                  `slow@site=serve.engine_execute` fault lands here)
- `index_query`   top-k scan(s) of the EmbeddingIndex
- `scatter`       per-request row slicing up to THIS request's resolve
- `respond`       JSON encode + socket write on the handler thread

`engine_execute`/`index_query` intervals are synthesized contiguously
from the run start (the real device work interleaves per chunk); their
DURATIONS are exact, which is what the waterfall and the latency
-accounting test consume.

Deliberately stdlib-only, like obs/trace.py.
"""

from __future__ import annotations

import itertools
import time

# Canonical stage order — waterfalls render and validate in this order;
# absent stages (e.g. index_query on an /embed request) simply skip.
STAGES = (
    "ingress",
    "queue_wait",
    "batch_assemble",
    "engine_execute",
    "index_query",
    "scatter",
    "respond",
)

# Virtual-thread lanes the request spans render on in Perfetto: one
# track per lane, requests round-robined so overlapping requests mostly
# land on different lanes and timestamp-containment nesting stays sane.
REQUEST_LANES = 8
REQUEST_LANE_TID_BASE = 1  # tiny ints never collide with real thread idents


class RequestTrace:
    """One request's stage-stamped waterfall (module docstring).

    `stamp()` is the only hot-path call: perf_counter pairs append to a
    plain list. Everything else (waterfall dict, stage sums, span
    records) runs off-path."""

    __slots__ = (
        "req_id", "replica", "rows", "t0", "wall_t0", "stages",
        "trace_id", "parent_span", "span_id",
    )

    def __init__(
        self, req_id: str, rows: int = 1, replica: int = 0, t0: float = None,
        ctx=None,
    ):
        self.req_id = req_id
        self.replica = int(replica)
        self.rows = int(rows)
        # `t0` backdates ingress to when the request actually arrived
        # (the HTTP handler reads the body before it knows the row
        # count, so the trace object is built after arrival)
        now = time.perf_counter()
        self.t0 = now if t0 is None else float(t0)
        self.wall_t0 = time.time() - (now - self.t0)
        self.stages: list[tuple[str, float, float]] = []
        # adopted distributed-trace identity (obs/ctxprop.TraceContext);
        # absent for requests that arrive without the fleet front door
        self.trace_id = ctx.trace_id if ctx is not None else None
        self.parent_span = ctx.span_id if ctx is not None else None
        self.span_id = None
        if ctx is not None:
            from moco_tpu.obs import ctxprop

            self.span_id = ctxprop.new_span_id()

    def stamp(self, stage: str, t0: float, t1: float) -> None:
        """Record one completed stage interval (perf_counter domain)."""
        self.stages.append((stage, t0, t1))

    # -- off-path views --------------------------------------------------

    def stage_ms(self) -> dict[str, float]:
        """{stage: total ms} — repeated stamps of one stage sum."""
        out: dict[str, float] = {}
        for stage, t0, t1 in self.stages:
            out[stage] = out.get(stage, 0.0) + (t1 - t0) * 1e3
        return out

    def total_ms(self) -> float:
        """Ingress-to-last-stamp wall: the request's end-to-end time as
        the trace saw it."""
        if not self.stages:
            return 0.0
        return (max(t1 for _, _, t1 in self.stages) - self.t0) * 1e3

    def waterfall(self) -> dict:
        """JSON-ready waterfall record — the flight recorder's unit of
        storage and the dump/report format. Stage starts are ms relative
        to ingress. Requests carrying an adopted trace context include
        the distributed-trace ids — the join keys for stitching."""
        out = {
            "request_id": self.req_id,
            "replica": self.replica,
            "rows": self.rows,
            "wall_t0": self.wall_t0,
            "total_ms": round(self.total_ms(), 3),
            "stages": [
                {
                    "stage": stage,
                    "start_ms": round((t0 - self.t0) * 1e3, 3),
                    "dur_ms": round((t1 - t0) * 1e3, 3),
                }
                for stage, t0, t1 in self.stages
            ],
        }
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
            out["span_id"] = self.span_id
            if self.parent_span is not None:
                out["parent_span"] = self.parent_span
        return out


class RequestIdAllocator:
    """Monotonic replica-scoped request ids (`r<replica>-<seq>`).
    itertools.count is atomic under the GIL, so handler threads need no
    extra lock."""

    def __init__(self, replica: int = 0):
        self.replica = int(replica)
        self._seq = itertools.count()

    def new_trace(self, rows: int = 1, t0: float = None, ctx=None) -> RequestTrace:
        return RequestTrace(
            f"r{self.replica}-{next(self._seq):06d}",
            rows=rows,
            replica=self.replica,
            t0=t0,
            ctx=ctx,
        )


def emit_request_spans(tracer, trace: RequestTrace, lane: int) -> None:
    """Render one completed request onto the tracer as Perfetto spans:
    an enclosing `request` span plus one child per stage, on a virtual
    "requests" lane track (`REQUEST_LANES` round-robin). Called from the
    server's flusher thread — never the batcher or a handler thread."""
    if tracer is None or not trace.stages:
        return
    lane = lane % REQUEST_LANES
    tid = REQUEST_LANE_TID_BASE + lane
    thread = f"requests-{lane}"
    t_end = max(t1 for _, _, t1 in trace.stages)
    ids = {}
    if trace.trace_id is not None:
        ids["trace_id"] = trace.trace_id
        ids["span_id"] = trace.span_id
        if trace.parent_span is not None:
            ids["parent_span"] = trace.parent_span
    tracer.emit_span(
        "request",
        trace.t0,
        t_end,
        tid=tid,
        thread=thread,
        request_id=trace.req_id,
        rows=trace.rows,
        replica=trace.replica,
        **ids,
    )
    for stage, t0, t1 in trace.stages:
        tracer.emit_span(
            f"req/{stage}", t0, t1, tid=tid, thread=thread, request_id=trace.req_id
        )


__all__ = [
    "REQUEST_LANES",
    "RequestIdAllocator",
    "RequestTrace",
    "STAGES",
    "emit_request_spans",
]
