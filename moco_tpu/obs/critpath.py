"""Critical-path analysis over stitched multi-hop request traces.

The router (serve/router.py) and the offline stitcher
(scripts/trace_merge.py `stitch_traces`) both produce ONE record per
distributed request — the *stitched trace* — and this module reduces it
to the question the fleet's p99 actually hangs on: **which hop ate the
milliseconds?** Router queueing, the network, a specific replica stage,
or a failed attempt the retry layer had to wait out.

Stitched-trace schema (the shared contract between the producers and
this analyzer; all times are ms):

    {
      "trace_id": str, "request_id": str|None, "path": str,
      "status": int, "wall_t0": float,
      "total_ms": float,                  # router ingress -> respond
      "router": {"ingress_ms", "admission_ms", "respond_ms"},
      "attempts": [
        {"span_id", "replica", "retry_index", "lane",   # primary|hedge
         "breaker", "outcome",     # ok|failed|cancelled|pending
         "winner": bool, "start_ms", "dur_ms",
         "net_send_ms", "net_recv_ms",   # clock-aligned network split
         "wasted_ms",                    # cancelled hedge lane's cost
         "error": str|None,
         "remote": {"request_id", "replica",
                    "stages": [{"stage", "start_ms", "dur_ms"}]} | None}
      ],
    }

`attribute()` walks the request's CRITICAL PATH — the chain the client
actually waited on: router ingress/admission, every *failed* attempt's
duration (sequential retries block the response), then the winning
attempt split into network send / replica stages / network receive,
then the router's respond write. Whatever the spans cannot explain
(scheduler gaps, retry backoff sleeps) lands in an explicit
`router_other` hop, so the hop sum equals `total_ms` BY CONSTRUCTION —
the property the fleet smoke gates against the client-measured wall.
A cancelled hedge lane is NOT on the critical path (the client never
waited on it); its cost is accounted separately as `wasted_ms`.

`aggregate()` folds many attributions into per-hop mean/share plus
hedge-win and retry-cost accounting; `metrics_payload()` turns that
into the `fleet_serve/critpath_<hop>_ms` gauge family the router
flushes (schema'd in obs/schema.py).

Stdlib-only: obs_report and the smoke import this on jax-less hosts.
"""

from __future__ import annotations

ROUTER_HOPS = ("router_ingress", "router_admission", "router_respond")


def attribute(stitched: dict) -> dict:
    """One stitched trace -> its critical-path hop attribution (module
    docstring). Hop values are clamped non-negative (clock skew between
    hosts can make a raw network split dip below zero); the residual
    `router_other` absorbs what the spans cannot explain so the hop sum
    is exactly `total_ms`."""
    total = float(stitched.get("total_ms") or 0.0)
    router = stitched.get("router") or {}
    hops: dict[str, float] = {}
    for hop in ROUTER_HOPS:
        ms = router.get(hop[len("router_"):] + "_ms")
        if isinstance(ms, (int, float)):
            hops[hop] = max(0.0, float(ms))
    attempts = stitched.get("attempts") or []
    winner = next((a for a in attempts if a.get("winner")), None)
    hedged = any(a.get("lane") == "hedge" for a in attempts)
    hedge_won = bool(winner and winner.get("lane") == "hedge")
    # Retry cost is accounted per RETRY ROUND (attempts sharing a
    # retry_index ran concurrently — primary + its hedge): a round with
    # a winner puts its losers entirely off-path (waste); a losing
    # round blocked the retry layer for its LONGEST lane (on-path,
    # `retry_failed` hop) while any shorter concurrent lane is waste.
    retry_failed = 0.0
    wasted = 0.0
    rounds: dict[int, list] = {}
    for a in attempts:
        rounds.setdefault(int(a.get("retry_index") or 0), []).append(a)
    for rnd in sorted(rounds):
        group = rounds[rnd]
        has_winner = any(a.get("winner") for a in group)
        blocked = 0.0
        for a in group:
            if a.get("winner"):
                continue
            dur = max(0.0, float(a.get("dur_ms") or 0.0))
            cost = max(0.0, float(a.get("wasted_ms") or 0.0)) or dur
            if a.get("outcome") == "failed" and not has_winner:
                blocked = max(blocked, dur)
                wasted += dur
            else:
                wasted += cost
        if blocked:
            retry_failed += blocked
            wasted -= blocked  # the blocking lane is on-path, not waste
    if retry_failed:
        hops["retry_failed"] = retry_failed
    if winner is not None:
        explained = 0.0
        for key, hop in (("net_send_ms", "net_send"), ("net_recv_ms", "net_recv")):
            ms = winner.get(key)
            if isinstance(ms, (int, float)):
                hops[hop] = hops.get(hop, 0.0) + max(0.0, float(ms))
                explained += max(0.0, float(ms))
        remote = winner.get("remote") or {}
        for s in remote.get("stages") or ():
            ms = max(0.0, float(s.get("dur_ms") or 0.0))
            hop = f"replica_{s.get('stage')}"
            hops[hop] = hops.get(hop, 0.0) + ms
            explained += ms
        # the attempt's own unexplained slack (socket buffering, the
        # replica's respond write — stamped after its response, so it
        # reaches us as slack, never as a remote stage)
        slack = max(0.0, float(winner.get("dur_ms") or 0.0)) - explained
        if slack > 0.0:
            hops["net_recv"] = hops.get("net_recv", 0.0) + slack
    hops["router_other"] = max(0.0, total - sum(hops.values()))
    return {
        "trace_id": stitched.get("trace_id"),
        "total_ms": total,
        "hops": hops,
        "hedged": hedged,
        "hedge_won": hedge_won,
        "retry_failed_ms": retry_failed,
        "wasted_ms": wasted,
        "attempts": len(attempts),
    }


def aggregate(attributions) -> dict:
    """Fold per-trace attributions into run-level accounting: per-hop
    mean ms and share-of-total, hedge win rate, retry cost. Empty input
    -> zeroed aggregate (the router flushes before its first request)."""
    attrs = [a for a in attributions if a]
    n = len(attrs)
    hop_sums: dict[str, float] = {}
    total = 0.0
    hedged = hedge_won = with_retry = 0
    retry_ms = wasted_ms = 0.0
    for a in attrs:
        total += a.get("total_ms", 0.0)
        for hop, ms in (a.get("hops") or {}).items():
            hop_sums[hop] = hop_sums.get(hop, 0.0) + ms
        hedged += 1 if a.get("hedged") else 0
        hedge_won += 1 if a.get("hedge_won") else 0
        if a.get("retry_failed_ms"):
            with_retry += 1
            retry_ms += a["retry_failed_ms"]
        wasted_ms += a.get("wasted_ms", 0.0)
    hops = {
        hop: {
            "mean_ms": s / n,
            "share": (s / total) if total else 0.0,
        }
        for hop, s in hop_sums.items()
    } if n else {}
    return {
        "traces": n,
        "total_mean_ms": (total / n) if n else 0.0,
        "hops": hops,
        "hedge": {
            "hedged": hedged,
            "won": hedge_won,
            "win_rate": (hedge_won / hedged) if hedged else None,
            "wasted_ms": wasted_ms,
        },
        "retry": {
            "traces_with_retry": with_retry,
            "failed_attempt_ms": retry_ms,
            "mean_cost_ms": (retry_ms / with_retry) if with_retry else None,
        },
    }


def metrics_payload(agg: dict) -> dict:
    """Aggregate -> the `fleet_serve/critpath_<hop>_ms` gauge family
    (mean ms per hop over the aggregation window). Hop names are stage
    identifiers ([a-z_]), so the keys stay schema-clean."""
    out: dict = {}
    for hop, rec in sorted((agg.get("hops") or {}).items()):
        out[f"fleet_serve/critpath_{hop}_ms"] = round(rec["mean_ms"], 3)
    return out


def flatten(stitched: dict) -> list[dict]:
    """Stitched trace -> a flat waterfall `stages` list (the flight
    recorder / obs_report display format): router stages, each failed
    attempt, then the winning attempt's network + replica hops, in
    start order where the producers recorded one."""
    out: list[dict] = []
    router = stitched.get("router") or {}

    def add(stage, start, dur):
        if isinstance(dur, (int, float)):
            out.append({
                "stage": stage,
                "start_ms": round(float(start or 0.0), 3),
                "dur_ms": round(max(0.0, float(dur)), 3),
            })

    add("router_ingress", 0.0, router.get("ingress_ms"))
    add("router_admission", router.get("ingress_ms"), router.get("admission_ms"))
    for a in stitched.get("attempts") or ():
        start = float(a.get("start_ms") or 0.0)
        if a.get("outcome") == "failed" and not a.get("winner"):
            add(f"failed_attempt_r{a.get('replica')}", start, a.get("dur_ms"))
            continue
        if a.get("outcome") == "cancelled":
            add(f"cancelled_hedge_r{a.get('replica')}", start, a.get("wasted_ms"))
            continue
        if not a.get("winner"):
            continue
        add("net_send", start, a.get("net_send_ms"))
        cursor = start + float(a.get("net_send_ms") or 0.0)
        for s in (a.get("remote") or {}).get("stages") or ():
            add(
                f"replica_{s.get('stage')}",
                cursor + float(s.get("start_ms") or 0.0),
                s.get("dur_ms"),
            )
        end = start + float(a.get("dur_ms") or 0.0)
        add("net_recv", end - float(a.get("net_recv_ms") or 0.0), a.get("net_recv_ms"))
    total = float(stitched.get("total_ms") or 0.0)
    add("router_respond", total - float(router.get("respond_ms") or 0.0),
        router.get("respond_ms"))
    return out


__all__ = ["ROUTER_HOPS", "aggregate", "attribute", "flatten", "metrics_payload"]
