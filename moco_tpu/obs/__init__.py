"""moco_tpu.obs — the telemetry layer.

One cross-cutting subsystem, five parts (see each module's docstring):

- `trace`     hierarchical span tracer + Chrome-trace (Perfetto) export
- `stepstats` step-time breakdown probe + device-memory gauges
- `health`    jitted MoCo training-health reductions (EMA drift, logit
              stats, collapse detection, queue staleness)
- `sinks`     pluggable metric sinks (JSONL/CSV/TensorBoard/Prometheus
              `/metrics` HTTP endpoint) behind one write() surface
- `schema`    the machine-checkable metrics.jsonl line contract
- `fleet`     cross-host stats aggregation + out-of-band heartbeats
- `comms`     named collective sites + analytic bytes-moved counters
- `alerts`    declarative in-stream alert rules -> alerts.jsonl
- `reqtrace`  request-scoped stage-stamped traces for the serving stack
- `slo`       multi-window SLO burn-rate accounting over `slo_ms`
- `flight`    tail-latency flight recorder (bounded ring + atomic dump)

`span`/`instant` are re-exported eagerly because they are the
high-traffic wiring surface (`from moco_tpu import obs; obs.span(...)`)
and, like `trace` and `schema`, are stdlib-only. Everything touching
jax (`sinks`, `stepstats`, `health`) resolves lazily, so report tooling
can `import moco_tpu.obs.schema` on a machine without a backend."""

from moco_tpu.obs.trace import (  # stdlib-only, eager
    Tracer,
    counter,
    get_tracer,
    instant,
    set_tracer,
    span,
    spans_to_chrome_events,
)

_LAZY = {
    "Sink": "sinks",
    "JsonlSink": "sinks",
    "CsvSink": "sinks",
    "TensorBoardSink": "sinks",
    "PrometheusSink": "sinks",
    "MultiSink": "sinks",
    "build_sinks": "sinks",
    "register_sink": "sinks",
    "gather_payload": "sinks",
    "sanitize": "sinks",
    "StepTimeProbe": "stepstats",
    "device_memory_stats": "stepstats",
    "memory_payload": "stepstats",
    "health_summary": "health",
    # fleet observability (obs/fleet.py — jax) + comms ledger + alerts
    "FleetAggregator": "fleet",
    "Heartbeat": "fleet",
    "read_heartbeats": "fleet",
    "AlertEngine": "alerts",
    "FatalAlertError": "alerts",
    "parse_rules": "alerts",
    # request-scoped serving observability (all stdlib-only, lazy for
    # symmetry with the other non-eager modules)
    "RequestTrace": "reqtrace",
    "RequestIdAllocator": "reqtrace",
    "emit_request_spans": "reqtrace",
    "SLOBurnTracker": "slo",
    "serve_alert_spec": "slo",
    "FlightRecorder": "flight",
    "read_flight_dumps": "flight",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(f"moco_tpu.obs.{_LAZY[name]}"), name)
    raise AttributeError(f"module 'moco_tpu.obs' has no attribute {name!r}")


__all__ = [
    "Tracer",
    "counter",
    "get_tracer",
    "set_tracer",
    "span",
    "instant",
    "spans_to_chrome_events",
    *sorted(_LAZY),
]
