"""Collective-communication instrumentation — named sites + analytic
bytes-moved counters.

MoCo's step time on a pod is gated by its synchronous collectives (the
batch-shuffle all_gather / all_to_all, the queue's key gather, the
gradient psum, ZeRO's reduce-scatter + param all_gather, ring
attention's ppermute rotation), yet none of them were measurable: the
span tracer sees host wall time only, and a jax.profiler capture is a
gigabyte-scale artifact you don't have for every run.

This module makes each collective site *self-describing* at trace time.
A site wraps its collective in `comms.tag(...)`:

    with comms.tag("grad.psum", "psum", grads, n_data):
        grads = lax.pmean(grads, DATA_AXIS)

which does two things, both free at runtime:

- enters a `jax.named_scope` (`comms.<site>`) so the op carries the site
  name into HLO metadata — device profiles and compiled-module dumps
  attribute collective time to the training-level site, not an opaque
  `all-reduce.42`;
- records the site's ANALYTIC per-device wire cost into a process-level
  ledger. Shapes and dtypes are static during tracing, so the cost is
  exact and costs nothing per step — the ledger is written once per
  trace (idempotent on retrace) and read on log steps.

Cost model (per device, per call; n = axis size, b = operand bytes of
this device's shard):

    all_gather     b * (n-1)        receives every other shard
    all_to_all     b * (n-1)/n      keeps 1/n of its own data
    psum           2b * (n-1)/n     ring all-reduce (reduce-scatter +
                                    all-gather halves)
    psum_scatter   b * (n-1)/n      reduce-scatter half only
    ppermute       b                one neighbor hop per call
    broadcast      b
    device_put     b                host→device: the payload crosses
                                    PCIe/DMA once, independent of any
                                    mesh axis (axis_size is ignored) —
                                    the input wire's `input.h2d` site

These are the standard ring-collective volumes ("How to Scale Your
Model" §collectives); they are *analytic* counters, not measurements —
what the ICI must move, independent of link speed.

Surfaced as `comms/<site>` bytes-per-step gauges on every metrics line
(train driver) and as a per-collective table in `scripts/obs_report.py`.

A site whose axis has size 1 records 0 bytes (no wire traffic) but
still registers, so the report can show which sites exist.

NOTE (gather_perm shuffle): the queue enqueue reuses the unshuffle
all_gather (`shuffle.gather_keys`) instead of issuing its own collective
— one of the rebuild's saved collectives — so `queue.enqueue_gather`
appears only for the 'a2a' and 'none' shuffle modes, which gather the
key batch separately.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading

import jax

from moco_tpu.analysis import sanitizer as _schedule

from moco_tpu.analysis import tsan

COLLECTIVES = (
    "all_gather",
    "all_to_all",
    "psum",
    "psum_scatter",
    "ppermute",
    "broadcast",
    "device_put",
)


def tree_bytes(tree) -> int:
    """Total payload bytes of a pytree of arrays (tracers included —
    `.size`/`.dtype` are static during tracing)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is None or dtype is None:
            continue
        total += int(size) * jax.numpy.dtype(dtype).itemsize  # mocolint: disable=JX002  (.size/.dtype are trace-STATIC metadata, exact and free during tracing)
    return total


def _shape_signature(tree) -> str:
    """Stable (shape, dtype) signature of a pytree's leaves, for the
    schedule sanitizer. Like `tree_bytes`, works on tracers."""
    parts = []
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        parts.append(f"{tuple(shape)}:{dtype}")
    return ",".join(parts)


def collective_bytes(collective: str, nbytes: int, axis_size: int) -> int:
    """Per-device wire bytes for ONE call of `collective` on a local
    operand of `nbytes` over an axis of `axis_size` (see the module
    docstring's cost model)."""
    n = int(axis_size)  # mocolint: disable=JX002  (mesh axis size is a static Python int during tracing)
    if collective not in COLLECTIVES:
        raise ValueError(f"unknown collective {collective!r} (known: {COLLECTIVES})")
    if collective == "device_put":
        # host→device transfer, not a ring collective: the bytes cross
        # the wire once whatever the axis size (including 1)
        return nbytes
    if n <= 1:
        return 0
    if collective == "all_gather":
        return nbytes * (n - 1)
    if collective == "all_to_all":
        return (nbytes * (n - 1)) // n
    if collective == "psum":
        return (2 * nbytes * (n - 1)) // n
    if collective == "psum_scatter":
        return (nbytes * (n - 1)) // n
    # ppermute / broadcast: the shard moves once
    return nbytes


@dataclasses.dataclass(frozen=True)
class CommSite:
    """One annotated collective site, as recorded at trace time."""

    site: str
    collective: str
    operand_bytes: int  # this device's shard, one call
    bytes_per_call: int  # analytic wire cost, one call
    calls_per_step: int  # e.g. ring ppermute fires n times per step
    axis_size: int

    @property
    def bytes_per_step(self) -> int:
        return self.bytes_per_call * self.calls_per_step


_LOCK = tsan.make_lock("obs.comms")  # traced under --sanitize-threads
_LEDGER: dict[str, CommSite] = {}


def tag(
    site: str,
    collective: str,
    operand,
    axis_size: int,
    calls_per_step: int = 1,
):
    """Record `site`'s analytic cost and return a context manager naming
    the enclosed ops `comms.<site>` in HLO metadata.

    Call at the collective site, around the collective. Safe inside
    jit/shard_map tracing: the ledger write keys on the site name and is
    idempotent across retraces.
    """
    nbytes = tree_bytes(operand)
    if _schedule.enabled():
        # runtime collective-schedule sanitizer (analysis/sanitizer.py):
        # the site tag doubles as the schedule recorder's event — shapes
        # and dtypes are static during tracing, so this signature is the
        # cross-host agreement contract. Zero-cost when not installed.
        _schedule.on_tag(site, collective, _shape_signature(operand))
    rec = CommSite(
        site=site,
        collective=collective,
        operand_bytes=nbytes,
        bytes_per_call=collective_bytes(collective, nbytes, axis_size),
        calls_per_step=int(calls_per_step),  # mocolint: disable=JX002  (static site metadata, recorded once per trace)
        axis_size=int(axis_size),  # mocolint: disable=JX002  (static site metadata, recorded once per trace)
    )
    with _LOCK:
        _LEDGER[site] = rec
    try:
        return jax.named_scope(f"comms.{site}")
    except Exception:  # exotic backends without named_scope support
        return contextlib.nullcontext()


def snapshot() -> dict[str, CommSite]:
    """Current ledger (site -> CommSite), a copy."""
    with _LOCK:
        return dict(_LEDGER)


def reset() -> None:
    """Clear the ledger (run start / tests)."""
    with _LOCK:
        _LEDGER.clear()


def payload() -> dict:
    """Metrics-line fields: `comms/<site>` per-step wire bytes per
    device, plus `comms/total` — empty dict when nothing is annotated
    (clean lines for runs that never traced a collective)."""
    sites = snapshot()
    if not sites:
        return {}
    out = {f"comms/{name}": rec.bytes_per_step for name, rec in sites.items()}
    out["comms/total"] = sum(rec.bytes_per_step for rec in sites.values())
    return out


__all__ = [
    "COLLECTIVES",
    "CommSite",
    "collective_bytes",
    "payload",
    "reset",
    "snapshot",
    "tag",
    "tree_bytes",
]
