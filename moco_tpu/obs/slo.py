"""SLO burn-rate accounting for the serving stack.

A raw `serve/slo_violations` counter can't drive paging: a single slow
request in a week and a sustained 5% violation rate both increment it.
The SRE-standard signal is the **burn rate** — how fast the service is
spending its error budget:

    burn = (violating fraction over a window) / (1 - objective)

burn == 1 means the budget exactly runs out at the end of the SLO
period; 14.4 means a 30-day budget is gone in 2 days. Multi-window
evaluation (a fast window to catch cliffs, a slow one to catch creep)
is what the default alert rules threshold on.

:class:`SLOBurnTracker` keeps per-second good/bad buckets over the
longest window (bounded memory, O(1) record from the batcher thread)
and reports `serve/burn_rate_<w>s` gauges the obs schema validates,
the Prometheus sink exposes, and the existing `obs/alerts.py`
threshold rules fire on — no new rule kind needed.
:func:`serve_alert_spec` builds the serving default rule set in the
alerts grammar; the server parses it with `alerts.parse_rules` and
dumps the flight recorder when a rule fires.

Stdlib-only, like every obs module the report tooling imports.
"""

from __future__ import annotations

import time
import threading
from collections import deque
from typing import Optional, Sequence

from moco_tpu.analysis import tsan

# (fast, slow) windows, seconds. Burn thresholds below are the classic
# multiwindow pair scaled to these: sustained burn > the threshold on
# the fast window pages quickly; the slow window catches slow leaks.
DEFAULT_WINDOWS = (60, 600)
DEFAULT_FAST_BURN = 14.4
DEFAULT_SLOW_BURN = 6.0


class SLOBurnTracker:
    """Multi-window burn-rate over a declared latency SLO (module
    docstring). `record(ok)` is called once per completed request on
    the batcher thread; `burn_rates()`/`payload()` run on the metrics
    flusher. A deterministic `now` (seconds, monotonic domain) makes
    the math unit-testable."""

    def __init__(
        self,
        slo_ms: float,
        objective: float = 0.99,
        windows: Sequence[int] = DEFAULT_WINDOWS,
    ):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        if not windows or sorted(set(int(w) for w in windows)) != sorted(
            int(w) for w in windows
        ):
            raise ValueError(f"windows must be unique and non-empty, got {windows}")
        self.slo_ms = float(slo_ms)
        self.objective = float(objective)
        self.budget = 1.0 - self.objective
        self.windows = tuple(sorted(int(w) for w in windows))
        self._max_w = self.windows[-1]
        # tsan factory (analysis/tsan.py): traced under --sanitize-threads
        self._lock = tsan.make_lock("obs.slo")
        # per-second [sec, good, bad] buckets, oldest left; pruned on
        # record so memory is bounded by the longest window
        self._buckets: deque = deque()

    def record(self, ok: bool, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        sec = int(now)
        with self._lock:
            if not self._buckets or self._buckets[-1][0] != sec:
                self._buckets.append([sec, 0, 0])
            self._buckets[-1][1 if ok else 2] += 1
            floor = sec - self._max_w
            while self._buckets and self._buckets[0][0] <= floor:
                self._buckets.popleft()

    def burn_rates(self, now: Optional[float] = None) -> dict[int, Optional[float]]:
        """{window_s: burn rate} — None where the window saw no
        requests (a silent service isn't burning budget)."""
        now = time.monotonic() if now is None else now
        sec = int(now)
        out: dict[int, Optional[float]] = {}
        with self._lock:
            buckets = list(self._buckets)
        for w in self.windows:
            floor = sec - w
            good = bad = 0
            for s, g, b in buckets:
                if s > floor:
                    good += g
                    bad += b
            total = good + bad
            out[w] = (bad / total) / self.budget if total else None
        return out

    def payload(self, now: Optional[float] = None) -> dict:
        """The schema'd `serve/burn_rate_<w>s` gauge family plus the
        declared objective — merged into ServeMetrics.payload()."""
        out = {
            f"serve/burn_rate_{w}s": rate
            for w, rate in self.burn_rates(now).items()
        }
        out["serve/slo_objective"] = self.objective
        return out


class FreshnessBurnTracker:
    """Burn-rate accounting for the serving FRESHNESS SLO: the declared
    objective is "at least `objective` of freshness observations see a
    max index-row age <= `max_age_s` wall-seconds". Each metrics flush
    records one observation (the flusher samples
    `EmbeddingIndex.row_age_stats()`), so a stalled ingest pipeline
    burns budget at exactly the flush cadence and the same multi-window
    threshold rules that page on latency burn page on staleness.

    The bucket math is `SLOBurnTracker`'s (composition, not a copy):
    per-second good/bad buckets, bounded memory, deterministic `now`
    for unit tests. The payload family is `serve/fresh_burn_rate_<w>s`
    plus the declared `serve/fresh_max_age_s` objective gauge; the
    router renames per-replica gauges into `fleet_serve/fresh_burn_*`
    aggregates exactly as it does for the latency family."""

    def __init__(
        self,
        max_age_s: float,
        objective: float = 0.99,
        windows: Sequence[int] = DEFAULT_WINDOWS,
    ):
        if not max_age_s > 0:
            raise ValueError(f"max_age_s must be > 0, got {max_age_s}")
        self.max_age_s = float(max_age_s)
        self._burn = SLOBurnTracker(
            slo_ms=self.max_age_s * 1e3, objective=objective, windows=windows
        )
        self.objective = self._burn.objective
        self.windows = self._burn.windows

    def record(self, row_age_s: Optional[float], now: Optional[float] = None) -> None:
        """One freshness observation: the index's current max row age
        (None = no stamped rows yet — an empty index is not stale)."""
        ok = row_age_s is None or float(row_age_s) <= self.max_age_s
        self._burn.record(ok, now=now)

    def burn_rates(self, now: Optional[float] = None) -> dict[int, Optional[float]]:
        return self._burn.burn_rates(now)

    def payload(self, now: Optional[float] = None) -> dict:
        """The schema'd `serve/fresh_burn_rate_<w>s` gauge family plus
        the declared max-age objective — merged into the serve flush."""
        out = {
            f"serve/fresh_burn_rate_{w}s": rate
            for w, rate in self.burn_rates(now).items()
        }
        out["serve/fresh_max_age_s"] = self.max_age_s
        return out


def serve_alert_spec(
    slo_ms: Optional[float] = None,
    windows: Sequence[int] = DEFAULT_WINDOWS,
    fast_burn: float = DEFAULT_FAST_BURN,
    slow_burn: float = DEFAULT_SLOW_BURN,
    prefix: str = "serve",
) -> str:
    """The serving default alert rules, in the obs/alerts.py grammar —
    threshold rules over the burn-rate gauges (fast window at
    `fast_burn`, slow window at `slow_burn`) plus, when `slo_ms` is
    given, a p99-over-SLO warn. `ServeServer(alert_spec="serve_default")`
    expands through this with its own slo/window settings; smokes pass
    tightened values so a short run can fire. The router expands with
    `prefix="fleet_serve"` so its rules watch the client-observed
    fleet gauges rather than any single replica's."""
    windows = tuple(sorted(int(w) for w in windows))
    rules = [
        f"threshold@name=slo_burn_fast:field={prefix}/burn_rate_{windows[0]}s:"
        f"value={fast_burn:g}"
    ]
    if len(windows) > 1:
        rules.append(
            f"threshold@name=slo_burn_slow:field={prefix}/burn_rate_{windows[-1]}s:"
            f"value={slow_burn:g}"
        )
    if slo_ms:
        rules.append(
            f"threshold@name=slo_p99_over:field={prefix}/p99_ms:"
            f"value={float(slo_ms):g}"
        )
    return ",".join(rules)


def fresh_alert_spec(
    windows: Sequence[int] = DEFAULT_WINDOWS,
    fast_burn: float = DEFAULT_FAST_BURN,
    slow_burn: float = DEFAULT_SLOW_BURN,
    prefix: str = "serve",
) -> str:
    """The freshness-SLO default alert rules — the same multiwindow
    threshold pair as `serve_alert_spec`, over the
    `<prefix>/fresh_burn_rate_<w>s` family. A replica with a freshness
    objective appends these to its serving rules; the fleet smoke's
    ingest-stall leg (`delay@site=ingest`) proves they fire."""
    windows = tuple(sorted(int(w) for w in windows))
    rules = [
        f"threshold@name=fresh_burn_fast:field={prefix}/fresh_burn_rate_{windows[0]}s:"
        f"value={fast_burn:g}"
    ]
    if len(windows) > 1:
        rules.append(
            f"threshold@name=fresh_burn_slow:field={prefix}/fresh_burn_rate_{windows[-1]}s:"
            f"value={slow_burn:g}"
        )
    return ",".join(rules)


__all__ = [
    "DEFAULT_FAST_BURN",
    "DEFAULT_SLOW_BURN",
    "DEFAULT_WINDOWS",
    "FreshnessBurnTracker",
    "SLOBurnTracker",
    "fresh_alert_spec",
    "serve_alert_spec",
]
