"""Tail-latency flight recorder for the serving stack.

When a burn-rate alert fires the interesting requests are already
gone: the p99 gauge says the tail rose, but the request that rose it
completed seconds ago. The flight recorder keeps a bounded in-memory
ring of the most recent completed request waterfalls (obs/reqtrace.py
dicts) and the most recent flushed metric lines, and on demand — an
SLO-violation alert, a fatal alert, or a `/debug/flight` request —
dumps the whole ring **atomically** to `flight_<ts>.json` in the
workdir, so the postmortem has the exact stage-stamped history around
the incident instead of an aggregate.

Cost discipline matches reqtrace: `record_request` is one deque append
under a lock (deque maxlen evicts for free); the JSON encoding happens
only at dump time, never on the request path.

The dump carries a `slowest` view (top-N by total_ms) so
`scripts/obs_report.py`'s Serving section and a human tailing the file
see the offenders first; the full ring rides below it.

Stdlib-only.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Optional

from moco_tpu.analysis import tsan

DEFAULT_MAX_REQUESTS = 512
DEFAULT_MAX_METRICS = 120
DEFAULT_TOP_N = 10


class FlightRecorder:
    """Bounded ring of recent request waterfalls + metric lines with an
    atomic JSON dump (module docstring)."""

    def __init__(
        self,
        max_requests: int = DEFAULT_MAX_REQUESTS,
        max_metrics: int = DEFAULT_MAX_METRICS,
        replica: int = 0,
    ):
        self.replica = int(replica)
        # tsan factory (analysis/tsan.py): traced under --sanitize-threads
        self._lock = tsan.make_lock("obs.flight")
        self._requests: deque = deque(maxlen=int(max_requests))
        self._metrics: deque = deque(maxlen=int(max_metrics))
        self._dump_seq = itertools.count()
        self.dumps: list[str] = []  # paths written, oldest first

    # -- recording (hot-adjacent; O(1) appends) --------------------------

    def record_request(self, waterfall: dict) -> None:
        """One completed request's waterfall dict
        (`RequestTrace.waterfall()`)."""
        with self._lock:
            self._requests.append(waterfall)

    def record_metrics(self, step: int, payload: dict) -> None:
        """One flushed metric line (shallow-copied: payloads are
        rebuilt per flush, never mutated after)."""
        with self._lock:
            self._metrics.append({"step": int(step), "time": time.time(), **payload})

    # -- views + dump ----------------------------------------------------

    def snapshot(self, top_n: int = DEFAULT_TOP_N) -> dict:
        """JSON-ready view of the ring: `slowest` (top-N waterfalls by
        total_ms, slowest first), the full `requests` ring, and the
        recent `metrics` lines."""
        with self._lock:
            requests = list(self._requests)
            metrics = list(self._metrics)
        slowest = sorted(
            requests, key=lambda r: r.get("total_ms", 0.0), reverse=True
        )[: max(int(top_n), 0)]
        return {
            "replica": self.replica,
            "requests_recorded": len(requests),
            "slowest": slowest,
            "requests": requests,
            "metrics": metrics,
        }

    def dump(
        self,
        workdir: str,
        reason: str,
        top_n: int = DEFAULT_TOP_N,
        extra: Optional[dict] = None,
    ) -> str:
        """Write the snapshot to `<workdir>/flight_<ts>.json` via the
        atomic tmp+rename discipline (a scraper or the CI artifact
        uploader never sees a torn file); returns the path. The
        monotonic dump sequence keeps two alerts in one second from
        colliding on the timestamped name."""
        rec = {
            "reason": reason,
            "time": time.time(),
            **(extra or {}),
            **self.snapshot(top_n),
        }
        os.makedirs(workdir, exist_ok=True)
        ts = time.strftime("%Y%m%d_%H%M%S", time.localtime(rec["time"]))
        path = os.path.join(
            workdir, f"flight_{ts}_{next(self._dump_seq):03d}.json"
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=2, allow_nan=False)
        os.replace(tmp, path)
        self.dumps.append(path)
        return path


def read_flight_dumps(workdir: str) -> list[tuple[str, dict]]:
    """(path, parsed dump) for every flight_*.json under `workdir`,
    oldest first — the obs_report loader. Unparseable files are skipped
    (reporting on a crashed run is the point)."""
    import glob as _glob

    out = []
    for path in sorted(_glob.glob(os.path.join(workdir, "flight_*.json"))):
        try:
            with open(path) as f:
                out.append((path, json.load(f)))
        except (ValueError, OSError):
            continue
    return out


__all__ = ["FlightRecorder", "read_flight_dumps"]
