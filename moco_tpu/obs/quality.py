"""Model-quality observability: encoder identity + compatibility scoring.

The systems plane (PRs 3/4/10/18) made step time, comms, and request
waterfalls legible; this module gives the *model* plane the same rails.
MoCo's core invariant (He et al., arXiv:1911.05722) is that dictionary
keys stay CONSISTENT with the slowly-evolving key encoder — when the
serving index holds rows embedded by encoder A while queries are
embedded by encoder B, recall degrades silently: no error, no 5xx,
and (before this module) no gauge. The EMA-scaling analysis
(arXiv:2307.13813) says the drift rate is a function of momentum and
schedule, so "the checkpoints are close together" is not a safety
argument — the compatibility of a candidate encoder with the LIVE
index must be measured, per promotion, in embedding space.

Three surfaces:

- **identity** — `params_digest` (content hash of the encoder's
  parameter pytree) + `model_payload` give every replica a stable
  `serve/model_step` / `serve/model_digest` gauge pair, so version
  skew across the fleet is a visible gauge instead of an incident.
- **compatibility** — `score_compat` re-embeds a held-back probe set
  under the candidate AND the live encoder: `compat_cosine` (mean
  probe-wise cosine between the two embeddings — 1.0 means the
  candidate moves nothing, a rotation/collapse drops it) and
  `recall_overlap` (mean top-k id overlap when the same probes query
  the same live index under both encoders — the retrieval-semantics
  check `compat_cosine` alone can miss, reusing the index's existing
  online-recall query machinery). `compat_payload` emits them as the
  schema'd `serve/compat_cosine` / `serve/recall_overlap` gauges the
  promotion ledger and obs_report read.
- **probes** — `synthetic_probes` is the deterministic held-back probe
  set for smokes/CLIs without a real eval split (seeded, so the live
  and candidate sides always embed the SAME inputs).

numpy-only on top of duck-typed engines (anything with
`embed(images) -> (embeddings, executed)`) — unit tests drive it with
fakes, the promotion pipeline with real `InferenceEngine`s.
"""

from __future__ import annotations

import hashlib
from typing import Optional

import numpy as np


def _flat_leaves(tree, prefix=""):
    """Depth-first (path, array) leaves of a nested-dict pytree, paths
    sorted — a stable iteration order so the digest is deterministic
    across processes and save/restore round-trips."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _flat_leaves(tree[k], f"{prefix}/{k}")
    else:
        yield prefix, np.asarray(tree)


def params_digest(params, length: int = 12) -> str:
    """Content hash (hex, `length` chars) of an encoder parameter
    pytree: sha256 over every leaf's path, shape, dtype, and bytes.
    Two replicas serving byte-identical weights agree; any retrain,
    EMA tick, or corruption disagrees — the fleet's version-skew gauge
    keys on this, not on step numbers (which collide across workdirs)."""
    h = hashlib.sha256()
    for path, leaf in _flat_leaves(params):
        h.update(path.encode())
        h.update(str(leaf.shape).encode())
        h.update(str(leaf.dtype).encode())
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()[:length]


def model_payload(step: Optional[int], digest: Optional[str]) -> dict:
    """The served-model identity gauges, schema'd (obs/schema.py):
    `serve/model_step` (checkpoint step the encoder came from, null
    when unknown) and `serve/model_digest` (params content hash)."""
    return {
        "serve/model_step": int(step) if step is not None else None,
        "serve/model_digest": str(digest) if digest is not None else None,
    }


def compat_cosine(live_emb, cand_emb) -> float:
    """Mean probe-wise cosine between the live and candidate encoders'
    embeddings of the SAME probes (both already L2-normalized, (n, d)).
    1.0 = the candidate moves nothing; an orthogonal rotation of the
    head scores ~0 even though every self-similarity looks healthy."""
    a = np.asarray(live_emb, np.float32)
    b = np.asarray(cand_emb, np.float32)
    if a.shape != b.shape or a.ndim != 2:
        raise ValueError(f"embedding shapes differ: {a.shape} vs {b.shape}")
    return float(np.mean(np.sum(a * b, axis=1)))


def recall_overlap(live_emb, cand_emb, index, k: int = 5, mode: str = "exact") -> float:
    """Mean top-k id overlap when the same probes query the SAME live
    index under the live vs candidate encoder — the retrieval-semantics
    compatibility check: the candidate may keep high cosine yet reorder
    the neighborhood structure the index rows were built for."""
    k = int(min(int(k), index.count))
    if k < 1:
        raise ValueError("recall_overlap needs a non-empty index")
    _, live_ids = index.query(np.asarray(live_emb, np.float32), k, mode=mode)
    _, cand_ids = index.query(np.asarray(cand_emb, np.float32), k, mode=mode)
    per_probe = [
        len(set(int(i) for i in l) & set(int(i) for i in c)) / k
        for l, c in zip(live_ids, cand_ids)
    ]
    return float(np.mean(per_probe))


def compat_payload(cosine: Optional[float], overlap: Optional[float]) -> dict:
    """The compatibility drift gauges, schema'd (obs/schema.py):
    `serve/compat_cosine` in [-1, 1], `serve/recall_overlap` in [0, 1]
    (null where the index was empty / the check did not run)."""
    return {
        "serve/compat_cosine": float(cosine) if cosine is not None else None,
        "serve/recall_overlap": float(overlap) if overlap is not None else None,
    }


def score_compat(
    live_engine,
    cand_engine,
    probes,
    index=None,
    k: int = 5,
    mode: str = "exact",
) -> dict:
    """Run the full compatibility scorer: re-embed `probes` under both
    engines, return `{"cosine", "overlap", "n_probes", "k"}` (overlap
    null without a usable index). The promotion gate battery thresholds
    these against its declared floors."""
    probes = np.asarray(probes)
    live_emb, _ = live_engine.embed(probes)
    cand_emb, _ = cand_engine.embed(probes)
    out = {
        "cosine": compat_cosine(live_emb, cand_emb),
        "overlap": None,
        "n_probes": int(probes.shape[0]),
        "k": int(k),
    }
    if index is not None and index.count > 0:
        out["overlap"] = recall_overlap(live_emb, cand_emb, index, k=k, mode=mode)
    return out


def synthetic_probes(n: int = 32, image_size: int = 32, seed: int = 0) -> np.ndarray:
    """Deterministic held-back probe images ((n, s, s, 3) uint8 — the
    engine's wire format) for smokes and CLIs without a real eval
    split — seeded so every gate evaluation embeds the same inputs."""
    rng = np.random.RandomState(seed)
    return rng.randint(
        0, 256, (int(n), int(image_size), int(image_size), 3)
    ).astype(np.uint8)


__all__ = [
    "compat_cosine",
    "compat_payload",
    "model_payload",
    "params_digest",
    "recall_overlap",
    "score_compat",
    "synthetic_probes",
]
