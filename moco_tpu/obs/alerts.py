"""Declarative in-stream alerting over the metrics stream.

The telemetry layer records everything and alerts on nothing: a step
-time regression, a starving input pipeline, a runaway EMA, or a dead
host is only discovered by a human reading a report after the fact.
This engine evaluates declarative rules against every logged payload,
in-stream, on the host — no extra device work — and emits:

- `alerts.jsonl` in the workdir (one JSON object per fired alert);
- an `event: "alert"` metrics line per fire (written by the driver), so
  the Prometheus sink exposes `moco_alert_<rule>` gauges and the event
  counter — scrapers page on them;
- with `--alerts-fatal`, a `FatalAlertError` abort that reuses the
  fault-tolerance layer's emergency-checkpoint path (save first, die
  second).

Rule spec grammar (same shape as the fault-injection spec —
`kind@key=val:key=val`, comma-separated; the literal entry `default`
expands to DEFAULT_SPEC):

    spike@name=N:field=F:factor=X:window=W:warmup=K
        fires when F exceeds X times its rolling median over the last W
        observations (after K observations — compiles are not spikes)
    threshold@name=N:field=F:value=V[:op=gt|lt]
        fires on the rising edge of F crossing V (no re-fire while the
        condition stays true)
    ratio@name=N:num=A:den=B:value=V:consecutive=C
        fires when A/B exceeds V for C consecutive observations
    event@name=N:event=E
        fires on every metrics event line of kind E
    heartbeat@name=N:timeout=T
        process 0 only: fires when another process's heartbeat file is
        older than T seconds (once per host, until it beats again)

Any rule takes `severity=warn|fatal` and `cooldown=K` (min observations
between re-fires; default 10 for spike/ratio/event).

Derived fields: `queue_stale_seconds` = `queue_age_max * t_step` (the
dictionary's oldest key, in wall seconds) is synthesized before rule
evaluation, so staleness rules see wall time rather than steps.

DEFAULT_SPEC covers the failure modes the ISSUE names: step-time spike
vs rolling median, data starvation, straggler skew, EMA-drift runaway,
queue staleness, non-finite loss, a watchdog stall, and heartbeat loss.
"""

from __future__ import annotations

import dataclasses
import json
import os
import statistics
import time
from collections import deque
from typing import Optional

RULE_KINDS = ("spike", "threshold", "ratio", "event", "heartbeat")

_INT_KEYS = ("window", "warmup", "consecutive", "cooldown")
_FLOAT_KEYS = ("value", "factor", "timeout")
_STR_KEYS = ("name", "field", "num", "den", "event", "op", "severity")

DEFAULT_HEARTBEAT_TIMEOUT = 120.0


def default_spec(heartbeat_timeout: float = DEFAULT_HEARTBEAT_TIMEOUT) -> str:
    """The built-in rule set, with the heartbeat-staleness threshold
    parameterized (config.heartbeat_timeout / --heartbeat-timeout): the
    same threshold the elastic rescale trigger uses, so the alert and
    the rescale agree on what "lost" means."""
    return (
        "spike@name=step_time_spike:field=t_step:factor=3:window=32:warmup=8,"
        "ratio@name=data_starvation:num=t_data:den=t_step:value=0.6:consecutive=3,"
        "threshold@name=straggler_skew_high:field=straggler_skew:value=0.5,"
        "threshold@name=ema_drift_runaway:field=ema_drift:value=0.5,"
        "threshold@name=queue_stale:field=queue_stale_seconds:value=600,"
        "event@name=nonfinite_loss:event=nonfinite_loss,"
        "event@name=stall:event=stall,"
        f"heartbeat@name=heartbeat_loss:timeout={heartbeat_timeout:g}:severity=fatal"
    )


DEFAULT_SPEC = default_spec()


class FatalAlertError(RuntimeError):
    """Raised by the driver when a fired alert is fatal under
    --alerts-fatal; the emergency checkpoint is already durable."""


@dataclasses.dataclass(frozen=True)
class AlertRule:
    name: str
    kind: str
    field: str = ""
    op: str = "gt"
    value: float = 0.0
    factor: float = 3.0
    window: int = 32
    warmup: int = 8
    num: str = ""
    den: str = ""
    consecutive: int = 1
    event: str = ""
    timeout: float = 120.0
    cooldown: int = 10
    severity: str = "warn"


def parse_rules(
    spec: Optional[str], heartbeat_timeout: Optional[float] = None
) -> list[AlertRule]:
    """Rules from a spec string; '' / 'none' -> no rules; the entry
    'default' expands in place, so 'default,threshold@name=...' extends
    the built-ins. `heartbeat_timeout` parameterizes the default set's
    heartbeat_loss threshold (explicit heartbeat@ rules keep their own
    timeout=)."""
    if not spec or spec.strip().lower() == "none":
        return []
    rules: list[AlertRule] = []
    seen: set[str] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if part.lower() == "default":
            expanded = default_spec(
                heartbeat_timeout
                if heartbeat_timeout is not None
                else DEFAULT_HEARTBEAT_TIMEOUT
            )
            for r in parse_rules(expanded):
                if r.name not in seen:
                    seen.add(r.name)
                    rules.append(r)
            continue
        kind, _, params = part.partition("@")
        if kind not in RULE_KINDS:
            raise ValueError(f"unknown alert rule kind {kind!r} in {part!r} (known: {RULE_KINDS})")
        kv: dict = {"kind": kind}
        for tok in params.split(":"):
            if not tok:
                continue
            k, _, v = tok.partition("=")
            if k in _INT_KEYS:
                kv[k] = int(v)
            elif k in _FLOAT_KEYS:
                kv[k] = float(v)
            elif k in _STR_KEYS:
                kv[k] = v
            else:
                raise ValueError(f"unknown alert rule param {k!r} in {part!r}")
        if "name" not in kv:
            raise ValueError(f"alert rule {part!r} needs name=")
        rule = AlertRule(**kv)
        _validate_rule(rule, part)
        if rule.name in seen:
            raise ValueError(f"duplicate alert rule name {rule.name!r}")
        seen.add(rule.name)
        rules.append(rule)
    return rules


def _validate_rule(rule: AlertRule, part: str) -> None:
    if rule.kind in ("spike", "threshold") and not rule.field:
        raise ValueError(f"{rule.kind} rule {part!r} needs field=")
    if rule.kind == "ratio" and not (rule.num and rule.den):
        raise ValueError(f"ratio rule {part!r} needs num= and den=")
    if rule.kind == "event" and not rule.event:
        raise ValueError(f"event rule {part!r} needs event=")
    if rule.op not in ("gt", "lt"):
        raise ValueError(f"rule {part!r}: op must be gt or lt")
    if rule.severity not in ("warn", "fatal"):
        raise ValueError(f"rule {part!r}: severity must be warn or fatal")


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


class AlertEngine:
    """Evaluates rules against each logged payload; appends fired alerts
    to `<workdir>/alerts.jsonl` (line-buffered, crash-safe tail) and
    returns them to the caller for in-band event lines / aborts."""

    def __init__(
        self,
        rules: list[AlertRule],
        workdir: Optional[str] = None,
        process_index: int = 0,
        on_fire=None,
    ):
        self.rules = list(rules)
        self.process_index = int(process_index)
        # per-alert callback, invoked (after the jsonl write) with each
        # fired alert dict — the serving stack hooks its flight-recorder
        # dump here so the capture happens AT the firing edge, not a
        # flush later. Exceptions are swallowed: a broken hook must not
        # take alerting (or the run) down.
        self.on_fire = on_fire
        self.workdir = workdir
        self.path = os.path.join(workdir, "alerts.jsonl") if workdir else None
        self._f = None
        self._hist: dict[str, deque] = {
            r.name: deque(maxlen=max(r.window, 1)) for r in self.rules if r.kind == "spike"
        }
        self._consec: dict[str, int] = {}
        self._active: set[str] = set()  # threshold rules currently over the line
        self._last_fired: dict[str, int] = {}  # rule -> observation index
        self._hb_alerted: set[int] = set()  # processes currently declared lost
        self._obs = 0

    # -- evaluation ------------------------------------------------------

    def observe(self, step: int, payload: dict, now: Optional[float] = None) -> list[dict]:
        """Evaluate every rule against one logged payload; returns the
        alerts fired (possibly empty). Cheap: dict lookups + a rolling
        median per spike rule."""
        now = time.time() if now is None else now
        self._obs += 1
        view = dict(payload)
        qmax, tstep = _num(view.get("queue_age_max")), _num(view.get("t_step"))
        if qmax is not None and tstep is not None:
            view["queue_stale_seconds"] = qmax * tstep
        fired: list[dict] = []
        for rule in self.rules:
            alert = self._eval(rule, step, view, now)
            if alert is not None:
                fired.append(alert)
        if fired:
            self._write(fired)
            if self.on_fire is not None:
                for alert in fired:
                    try:
                        self.on_fire(alert)
                    except Exception as e:
                        print(f"WARNING: alert on_fire hook failed: {e!r}", flush=True)
        return fired

    def _cooldown_ok(self, rule: AlertRule) -> bool:
        last = self._last_fired.get(rule.name)
        return last is None or self._obs - last >= max(rule.cooldown, 1)

    def _fire(self, rule: AlertRule, step: int, now: float, value, threshold, message: str) -> dict:
        self._last_fired[rule.name] = self._obs
        return {
            "time": now,
            "step": int(step),
            "rule": rule.name,
            "kind": rule.kind,
            "severity": rule.severity,
            "value": value,
            "threshold": threshold,
            "message": message,
        }

    def _eval(self, rule: AlertRule, step: int, view: dict, now: float) -> Optional[dict]:
        if rule.kind == "spike":
            val = _num(view.get(rule.field))
            if val is None:
                return None
            hist = self._hist[rule.name]
            out = None
            if len(hist) >= max(rule.warmup, 2):
                med = statistics.median(hist)
                if med > 0 and val > rule.factor * med and self._cooldown_ok(rule):
                    out = self._fire(
                        rule, step, now, val, rule.factor * med,
                        f"{rule.field}={val:.4g} > {rule.factor:g}x rolling median {med:.4g}",
                    )
            hist.append(val)
            return out
        if rule.kind == "threshold":
            val = _num(view.get(rule.field))
            if val is None:
                return None
            over = val > rule.value if rule.op == "gt" else val < rule.value
            if not over:
                self._active.discard(rule.name)
                return None
            if rule.name in self._active:  # no re-fire while continuously over
                return None
            self._active.add(rule.name)
            op = ">" if rule.op == "gt" else "<"
            return self._fire(
                rule, step, now, val, rule.value,
                f"{rule.field}={val:.4g} {op} {rule.value:g}",
            )
        if rule.kind == "ratio":
            num, den = _num(view.get(rule.num)), _num(view.get(rule.den))
            if num is None or den is None or den <= 0:
                return None
            ratio = num / den
            if ratio > rule.value:
                self._consec[rule.name] = self._consec.get(rule.name, 0) + 1
            else:
                self._consec[rule.name] = 0
                return None
            if self._consec[rule.name] == rule.consecutive or (
                self._consec[rule.name] > rule.consecutive and self._cooldown_ok(rule)
            ):
                return self._fire(
                    rule, step, now, ratio, rule.value,
                    f"{rule.num}/{rule.den}={ratio:.3f} > {rule.value:g} "
                    f"for {self._consec[rule.name]} consecutive log steps",
                )
            return None
        if rule.kind == "event":
            if view.get("event") != rule.event:
                return None
            return self._fire(
                rule, step, now, 1, None, f"event {rule.event!r} observed"
            )
        if rule.kind == "heartbeat":
            if self.process_index != 0 or not self.workdir:
                return None
            from moco_tpu.obs.fleet import read_heartbeats

            for p, rec in read_heartbeats(self.workdir).items():
                if p == self.process_index:
                    continue
                age = now - float(rec.get("time", 0.0))
                if age <= rule.timeout:
                    self._hb_alerted.discard(p)
                elif p not in self._hb_alerted:
                    self._hb_alerted.add(p)
                    return self._fire(
                        rule, step, now, age, rule.timeout,
                        f"process {p} heartbeat {age:.0f}s old (> {rule.timeout:g}s) "
                        f"— host {rec.get('host', '?')} lost?",
                    )
            return None
        return None

    # -- output ----------------------------------------------------------

    def _write(self, alerts: list[dict]) -> None:
        if self.path is None:
            return
        if self._f is None:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
            self._f = open(self.path, "a", buffering=1)
        for a in alerts:
            self._f.write(json.dumps(a, allow_nan=False) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.flush()
            self._f.close()


def read_alerts(path: str) -> list[dict]:
    """Parsed alerts.jsonl (missing file -> empty list) — the report
    loader."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


__all__ = [
    "DEFAULT_HEARTBEAT_TIMEOUT",
    "DEFAULT_SPEC",
    "default_spec",
    "AlertEngine",
    "AlertRule",
    "FatalAlertError",
    "parse_rules",
    "read_alerts",
]
