"""Pluggable metric sinks + the registry that builds them.

`MetricWriter` (utils/metrics.py) grew into this: the JSONL writer is
now one sink among several behind a single `write(step, payload)`
surface. The driver logs once; the fan-out decides where it lands:

- `JsonlSink` — the canonical append-only `metrics.jsonl` (crash-safe
  per-line flush; the fault counters and chaos harness depend on it, so
  `build_sinks` always includes it);
- `CsvSink` — spreadsheet-friendly wide table (header grows as new
  fields appear; the file is rewritten on header change, cheap at
  logging cadence);
- `TensorBoardSink` — optional, only if a TB writer package is
  importable (the container doesn't bake one in — constructing it
  without one raises a clear error instead of a deep ImportError);
- `PrometheusSink` — in-process HTTP endpoint serving the latest
  gauges in Prometheus text exposition format on `/metrics`, for
  scraping long runs.

Device-transfer discipline: payloads may contain live `jax.Array`
metrics. `gather_payload` fetches ALL of them in ONE `jax.device_get`
call — the old per-field `float(v)` forced one blocking device sync per
field on every log line (satellite fix; regression-tested by counting
transfers in tests/test_obs.py).
"""

from __future__ import annotations

import csv
import http.server
import json
import math
import os
import threading
import time
from typing import Callable, Optional

import jax
import numpy as np

from moco_tpu.analysis import tsan

# Single indirection point for the batched transfer, so tests can count
# calls without monkeypatching jax itself.
_DEVICE_GET = jax.device_get


def gather_payload(payload: dict) -> dict:
    """Fetch every device-array value in ONE transfer; host values pass
    through untouched. Called once per log event, upstream of all sinks."""
    keys = [k for k, v in payload.items() if isinstance(v, jax.Array)]
    if not keys:
        return payload
    fetched = _DEVICE_GET([payload[k] for k in keys])
    out = dict(payload)
    out.update(zip(keys, fetched))
    return out


def _scrub(v):
    """JSON-safe scalar: non-finite floats -> None (NaN/Inf are invalid
    strict JSON; the guard writes its own explicit event for non-finite
    losses), numpy scalars -> python, arrays -> scrubbed lists."""
    if isinstance(v, np.ndarray):
        return _scrub(v.item()) if v.ndim == 0 else [_scrub(x) for x in v.tolist()]
    if isinstance(v, (list, tuple)):
        return [_scrub(x) for x in v]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        v = float(v)
    if isinstance(v, float) and not math.isfinite(v):
        return None
    return v


def sanitize(rec: dict) -> dict:
    return {k: _scrub(v) for k, v in rec.items()}


class Sink:
    """Interface: `write` one log event; `fsync` makes the tail durable
    (preemption/abort paths); `close` is idempotent."""

    def write(self, step: int, payload: dict) -> None:
        raise NotImplementedError

    def fsync(self) -> None:
        pass

    def close(self) -> None:
        pass


class JsonlSink(Sink):
    """Append-only JSONL metrics (one object per log event).

    Crash-safe tail (fault-tolerance layer): every line is flushed to
    the OS as written, so a SIGKILL mid-epoch loses at most the line
    being formatted. `fsync` makes the tail durable across a host crash.
    Line schema: README "metrics.jsonl line format" / obs/schema.py."""

    def __init__(self, workdir: str, filename: str = "metrics.jsonl"):
        os.makedirs(workdir, exist_ok=True)
        self.path = os.path.join(workdir, filename)
        self._f = open(self.path, "a", buffering=1)

    def write(self, step: int, payload: dict) -> None:
        rec = {"step": int(step), "time": time.time()}
        rec.update(sanitize(gather_payload(payload)))
        self._f.write(json.dumps(rec, allow_nan=False) + "\n")
        self._f.flush()

    def fsync(self) -> None:
        if not self._f.closed:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        if not self._f.closed:
            self.fsync()
            self._f.close()


class CsvSink(Sink):
    """Wide-table CSV: one row per log event, columns = union of fields
    seen so far. A payload introducing new fields triggers a one-shot
    rewrite with the grown header (rows are kept in memory; at logging
    cadence — one row per `log_every` steps — this stays tiny). List
    values are JSON-encoded into their cell."""

    def __init__(self, workdir: str, filename: str = "metrics.csv"):
        os.makedirs(workdir, exist_ok=True)
        self.path = os.path.join(workdir, filename)
        self._fields: list[str] = ["step", "time"]
        self._rows: list[dict] = []

    def write(self, step: int, payload: dict) -> None:
        rec = {"step": int(step), "time": time.time()}
        rec.update(sanitize(gather_payload(payload)))
        rec = {
            k: json.dumps(v) if isinstance(v, (list, dict)) else v
            for k, v in rec.items()
        }
        grew = False
        for k in rec:
            if k not in self._fields:
                self._fields.append(k)
                grew = True
        self._rows.append(rec)
        if grew:
            self._rewrite()
        else:
            self._append(rec)

    def _writer(self, f):
        return csv.DictWriter(f, fieldnames=self._fields, restval="")

    def _rewrite(self) -> None:
        with open(self.path, "w", newline="") as f:
            w = self._writer(f)
            w.writeheader()
            w.writerows(self._rows)

    def _append(self, rec: dict) -> None:
        new_file = not os.path.exists(self.path) or os.path.getsize(self.path) == 0
        with open(self.path, "a", newline="") as f:
            w = self._writer(f)
            if new_file:
                w.writeheader()
            w.writerow(rec)

    def close(self) -> None:
        self._rows.clear()


class TensorBoardSink(Sink):
    """Scalar summaries via whichever TB writer is importable
    (`tensorboardX` or `torch.utils.tensorboard`). The training
    container deliberately bakes neither in — constructing this sink
    without one raises a clear RuntimeError naming the fix, instead of
    an ImportError from three layers down."""

    def __init__(self, workdir: str, subdir: str = "tb"):
        writer_cls = None
        try:
            from tensorboardX import SummaryWriter as writer_cls  # noqa: N813
        except ImportError:
            try:
                from torch.utils.tensorboard import SummaryWriter as writer_cls  # noqa: N813
            except ImportError:
                pass
        if writer_cls is None:
            raise RuntimeError(
                "TensorBoardSink needs `tensorboardX` or `torch` installed; "
                "neither is available in this environment. Use sinks="
                "'jsonl,csv' (and scripts/obs_report.py) instead, or install one."
            )
        self._w = writer_cls(os.path.join(workdir, subdir))

    def write(self, step: int, payload: dict) -> None:
        rec = sanitize(gather_payload(payload))
        for k, v in rec.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self._w.add_scalar(k, v, global_step=int(step))

    def fsync(self) -> None:
        self._w.flush()

    def close(self) -> None:
        self._w.close()


# -- Prometheus ----------------------------------------------------------


def prom_name(key: str, prefix: str = "moco") -> str:
    """Metric key -> valid Prometheus metric name ([a-zA-Z_:][a-zA-Z0-9_:]*)."""
    safe = "".join(c if c.isalnum() or c == "_" else "_" for c in key)
    if safe and safe[0].isdigit():
        safe = "_" + safe
    return f"{prefix}_{safe}"


def _is_histogram(v) -> bool:
    """Payload values shaped like obs/schema.py's latency-histogram
    contract render as Prometheus histograms instead of gauges."""
    return (
        isinstance(v, dict)
        and isinstance(v.get("le"), list)
        and isinstance(v.get("counts"), list)
        and len(v["counts"]) == len(v["le"]) + 1
        and "sum" in v
        and "count" in v
    )


def _render_histogram(name: str, hist: dict) -> list[str]:
    """Cumulative `_bucket{le=...}` + `_sum`/`_count` lines for one
    histogram payload. The per-bucket counts cumulate here (Prometheus
    histogram semantics). When the payload carries an exemplar
    ({"request_id", "latency_ms"} — the p99 offender's request id), it
    is attached OpenMetrics-style after the bucket it falls in; text
    -format-0.0.4 scrapers treat the `# {...}` tail as a comment, so
    the line degrades gracefully."""
    lines = [f"# TYPE {name} histogram"]
    exemplar = hist.get("exemplar") or {}
    ex_ms = exemplar.get("latency_ms")
    ex_id = exemplar.get("request_id")
    cum = 0
    for le, count in zip(hist["le"], hist["counts"]):
        cum += count
        line = f'{name}_bucket{{le="{le:g}"}} {cum}'
        if ex_id is not None and ex_ms is not None and ex_ms <= le:
            line += f' # {{request_id="{ex_id}"}} {ex_ms:g}'
            ex_id = ex_ms = None  # exemplar rides exactly one bucket
        lines.append(line)
    cum += hist["counts"][-1]
    line = f'{name}_bucket{{le="+Inf"}} {cum}'
    if ex_id is not None and ex_ms is not None:
        line += f' # {{request_id="{ex_id}"}} {ex_ms:g}'
    lines.append(line)
    lines.append(f"{name}_sum {hist['sum']}")
    lines.append(f"{name}_count {hist['count']}")
    return lines


class PrometheusSink(Sink):
    """Last-value gauges + event counters behind an in-process HTTP
    `/metrics` endpoint (Prometheus text exposition format 0.0.4), for
    scraping long runs. `port=0` binds an ephemeral port (tests);
    `self.port` is the bound one. The server runs on a daemon thread and
    never touches the train loop — `write` only updates a dict under a
    lock."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1", prefix: str = "moco"):
        # tsan factory (analysis/tsan.py): scrape-handler threads and the
        # writer contend here — --sanitize-threads smoke runs trace it
        self._lock = tsan.make_lock("obs.prometheus")
        self._gauges: dict[str, float] = {}
        self._events: dict[str, int] = {}
        # histogram-shaped payload values ({"le", "counts", "sum",
        # "count"[, "exemplar"]} — obs/schema.py `serve/latency_hist`)
        # render as REAL cumulative `_bucket{le=...}` series, so
        # external SLO tooling can compute its own quantiles instead of
        # trusting the precomputed p50/p99 gauges
        self._hists: dict[str, dict] = {}
        self._prefix = prefix
        self.host = host
        sink = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] != "/metrics":
                    self.send_error(404)
                    return
                body = sink.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr lines
                pass

        self._server = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="prometheus-metrics", daemon=True
        )
        self._thread.start()

    def write(self, step: int, payload: dict) -> None:
        rec = sanitize(gather_payload(payload))
        with self._lock:
            self._gauges[prom_name("step", self._prefix)] = int(step)
            if "event" in rec:
                self._events[str(rec["event"])] = self._events.get(str(rec["event"]), 0) + 1
            for k, v in rec.items():
                if _is_histogram(v):
                    # "serve/latency_hist" -> moco_serve_latency_ms (the
                    # bounds are milliseconds; the suffix says so)
                    base = k[: -len("_hist")] if k.endswith("_hist") else k
                    self._hists[prom_name(base + "_ms", self._prefix)] = v
                    continue
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                self._gauges[prom_name(k, self._prefix)] = v

    def render(self) -> str:
        with self._lock:
            lines = []
            for name in sorted(self._gauges):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {self._gauges[name]}")
            for name in sorted(self._hists):
                lines.extend(_render_histogram(name, self._hists[name]))
            total = prom_name("events_total", self._prefix)
            if self._events:
                lines.append(f"# TYPE {total} counter")
                for kind in sorted(self._events):
                    lines.append(f'{total}{{kind="{kind}"}} {self._events[kind]}')
            return "\n".join(lines) + "\n"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # join-on-close (mocolint JX011): shutdown() unblocks
        # serve_forever, but until the thread actually exits it pins the
        # bound port and the handler's references — a restart-in-process
        # (tests, chained bench legs) would hit EADDRINUSE
        self._thread.join(timeout=5.0)


class MultiSink(Sink):
    """Fan one log event out to every registered sink. The device fetch
    happens ONCE here; children receive host values. A failing secondary
    sink is reported but never kills the run (metrics must not take
    training down); the primary JSONL sink's errors propagate."""

    def __init__(self, sinks: list[Sink], primary: Optional[JsonlSink] = None):
        self.sinks = sinks
        self.primary = primary
        # driver-facing conveniences (MetricWriter compat)
        self.path = primary.path if primary is not None else None
        # the Prometheus sink, when present — the driver logs its ACTUAL
        # bound address (the requested port may be 0 = ephemeral, or
        # shifted by the process index)
        self.prometheus: Optional[PrometheusSink] = next(
            (s for s in sinks if isinstance(s, PrometheusSink)), None
        )

    def write(self, step: int, payload: dict) -> None:
        payload = gather_payload(payload)
        for s in self.sinks:
            if s is self.primary:
                s.write(step, payload)
                continue
            try:
                s.write(step, payload)
            except Exception as e:
                print(f"WARNING: metric sink {type(s).__name__} failed: {e!r}", flush=True)

    def fsync(self) -> None:
        for s in self.sinks:
            s.fsync()

    def close(self) -> None:
        for s in self.sinks:
            s.close()


# -- registry ------------------------------------------------------------

SINK_REGISTRY: dict[str, Callable[..., Sink]] = {
    "jsonl": JsonlSink,
    "csv": CsvSink,
    "tensorboard": TensorBoardSink,
}


def register_sink(name: str, factory: Callable[..., Sink]) -> None:
    """Third-party sinks plug in here; `build_sinks` then accepts the
    name in its spec string."""
    SINK_REGISTRY[name] = factory


def per_process_filename(base: str, process_index: int) -> str:
    """`metrics.jsonl` for process 0 (every single-host consumer keeps
    its path); `metrics.p<i>.jsonl` for co-hosted processes sharing a
    workdir, which previously clobbered each other's files.
    `scripts/obs_report.py` globs and merges the family."""
    if process_index <= 0:
        return base
    stem, _, ext = base.rpartition(".")
    return f"{stem}.p{process_index}.{ext}" if stem else f"{base}.p{process_index}"


def derive_metrics_port(base_port: int, process_index: int) -> int:
    """Per-process Prometheus port: `base + process_index`, so N
    processes on one host stop racing for the same bind (satellite fix;
    0 stays 0 = disabled)."""
    return base_port + process_index if base_port else 0


# How far the serve endpoint shifts off a colliding Prometheus port.
# 16 is an upper bound on co-hosted processes per host, so the shifted
# serve family can never land on ANY peer process's metrics port.
# Hosted by utils/contracts.py (single-source port rule, JX018) and
# re-exported here for existing importers; the two functions around
# this constant are the only sanctioned port-offset arithmetic.
from moco_tpu.utils.contracts import SERVE_PORT_STRIDE  # noqa: F401


def resolve_serve_port(serve_port: int, metrics_port: int = 0, process_index: int = 0) -> int:
    """Per-process serving port with the metrics-collision footgun
    removed. The offset rule:

    - Prometheus owns `metrics_port + process_index` (derive_metrics_port);
    - the serve endpoint claims `serve_port + process_index`;
    - if the two families collide (one process running both the server
      and `--metrics-port` — previously an EADDRINUSE at bind time,
      or worse, whichever bound first silently shadowing the other),
      the serve port shifts up by SERVE_PORT_STRIDE.

    Pick bases ≥ SERVE_PORT_STRIDE apart to avoid the shift entirely;
    `serve_port=0` stays 0 (ephemeral bind, tests)."""
    if not serve_port:
        return 0
    resolved = serve_port + process_index
    if metrics_port and resolved == derive_metrics_port(metrics_port, process_index):
        resolved += SERVE_PORT_STRIDE
    return resolved


def build_sinks(
    spec: str,
    workdir: str,
    metrics_port: int = 0,
    metrics_host: str = "127.0.0.1",
    process_index: int = 0,
) -> MultiSink:
    """`spec` is a comma list of registry names ("jsonl,csv"). The JSONL
    sink is always included (the fault-tolerance counters, chaos
    harness, and obs_report all key on metrics.jsonl) and is the
    MultiSink's primary. `metrics_port > 0` additionally serves
    Prometheus text format on `metrics_host:(metrics_port +
    process_index)` — per-process ports so co-hosted processes don't
    collide, and a bindable host for scrapers that aren't on-box.
    Process > 0 file sinks write `*.p<i>.*` names (shared-workdir
    clobber fix)."""
    names = [n.strip() for n in (spec or "").split(",") if n.strip()]
    if "jsonl" not in names:
        names.insert(0, "jsonl")
    unknown = [n for n in names if n not in SINK_REGISTRY]
    if unknown:
        raise ValueError(
            f"unknown metric sink(s) {unknown}; registered: {sorted(SINK_REGISTRY)}"
        )
    default_files = {"jsonl": "metrics.jsonl", "csv": "metrics.csv"}
    primary: Optional[JsonlSink] = None
    sinks: list[Sink] = []
    for n in names:
        if n in default_files:
            s = SINK_REGISTRY[n](
                workdir, filename=per_process_filename(default_files[n], process_index)
            )
        else:
            s = SINK_REGISTRY[n](workdir)
        if n == "jsonl":
            primary = s  # type: ignore[assignment]
        sinks.append(s)
    if metrics_port:
        sinks.append(
            PrometheusSink(
                port=derive_metrics_port(metrics_port, process_index),
                host=metrics_host,
            )
        )
    return MultiSink(sinks, primary=primary)
