"""MoCo training-health reductions — jit-compatible, computed IN the step.

The contrastive-learning literature says the signals that predict a
failed MoCo run are invisible in the loss curve: key-encoder/EMA drift
and momentum scaling ("How to Scale Your EMA", arXiv:2307.13813),
momentum-encoder representation dynamics (arXiv:2208.05744), dictionary
staleness (the MoCo paper's consistency argument), and feature-norm
collapse (all representations converging to one point — InfoNCE can
plateau at a healthy-looking value while features die).

Every function here is a pure jnp reduction over values the train step
already has in registers, returned through the step's metrics dict —
NOT a host-side recomputation. The host only sees the scalars on log
steps, riding the existing metrics fetch (zero extra device syncs).

Conventions: logits are reported in post-temperature units (what the
softmax sees); drift is RELATIVE (`||q - k|| / ||q||`) so it is
comparable across layer groups of different scale; queue ages are in
STEPS (multiply by steps-per-second for wall time).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _tree_sq_norm(tree) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)


def ema_drift(params_q, params_k) -> dict:
    """Relative L2 drift between the query and key (EMA) encoders:
    global plus one gauge per top-level layer group (backbone, head,
    ...). A drift collapsing to 0 means the EMA momentum is too high to
    track learning (or learning stopped); a drift exploding means the
    key encoder no longer provides consistent dictionary keys — the
    failure mode arXiv:2307.13813's momentum-scaling rule prevents."""
    eps = 1e-12
    out = {}
    diff_sq = ref_sq = jnp.zeros((), jnp.float32)
    for group in params_q:
        d = _tree_sq_norm(
            jax.tree.map(lambda q, k: q - k, params_q[group], params_k[group])
        )
        r = _tree_sq_norm(params_q[group])
        out[f"ema_drift/{group}"] = jnp.sqrt(d) / (jnp.sqrt(r) + eps)
        diff_sq = diff_sq + d
        ref_sq = ref_sq + r
    out["ema_drift"] = jnp.sqrt(diff_sq) / (jnp.sqrt(ref_sq) + eps)
    return out


def ema_drift_sharded(params_q, params_k, axis_name: str) -> dict:
    """`ema_drift` over ZeRO-2/3 persistent param SHARDS (each leaf is
    this replica's (m,) flat rows, inside shard_map): local squared
    norms psum over the data axis before the sqrt — the zero-padding
    rows contribute 0, so the gauge equals the replicated one up to
    reduction order."""
    from jax import lax

    eps = 1e-12
    out = {}
    diff_sq = ref_sq = jnp.zeros((), jnp.float32)
    for group in params_q:
        d = lax.psum(
            _tree_sq_norm(
                jax.tree.map(lambda q, k: q - k, params_q[group], params_k[group])
            ),
            axis_name,
        )
        r = lax.psum(_tree_sq_norm(params_q[group]), axis_name)
        out[f"ema_drift/{group}"] = jnp.sqrt(d) / (jnp.sqrt(r) + eps)
        diff_sq = diff_sq + d
        ref_sq = ref_sq + r
    out["ema_drift"] = jnp.sqrt(diff_sq) / (jnp.sqrt(ref_sq) + eps)
    return out


def logit_stats(pos_logits: jax.Array, neg_logits: jax.Array) -> dict:
    """Mean/std of the positive and negative InfoNCE logits (post-
    temperature). The healthy pattern is a widening pos/neg margin;
    pos ≈ neg means the dictionary is not discriminative, and both
    saturating near 1/temperature flags feature collapse (all cosines
    → 1)."""
    pos = pos_logits.astype(jnp.float32)
    neg = neg_logits.astype(jnp.float32)
    return {
        "logit_pos_mean": jnp.mean(pos),
        "logit_pos_std": jnp.std(pos),
        "logit_neg_mean": jnp.mean(neg),
        "logit_neg_std": jnp.std(neg),
    }


def logit_stats_from_dense(logits: jax.Array, labels: jax.Array) -> dict:
    """`logit_stats` from an already-materialized (B, N) logit matrix
    whose positive sits at column `labels[b]` (the v3 symmetric loss and
    the dense v2 path). Negatives are everything else; their mean/std
    come from sum/sum-of-squares with the positives subtracted — no
    (B, N) boolean mask materialization."""
    lg = logits.astype(jnp.float32)
    b, n = lg.shape
    pos = jnp.take_along_axis(lg, labels[:, None].astype(jnp.int32), axis=1)[:, 0]
    n_neg = jnp.asarray(b * (n - 1), jnp.float32)
    neg_mean = (jnp.sum(lg) - jnp.sum(pos)) / n_neg
    neg_sq = (jnp.sum(jnp.square(lg)) - jnp.sum(jnp.square(pos))) / n_neg
    neg_std = jnp.sqrt(jnp.maximum(neg_sq - jnp.square(neg_mean), 0.0))
    return {
        "logit_pos_mean": jnp.mean(pos),
        "logit_pos_std": jnp.std(pos),
        "logit_neg_mean": neg_mean,
        "logit_neg_std": neg_std,
    }


def feature_stats(feats: jax.Array) -> dict:
    """Collapse detector on the step's (L2-normalized) query features.

    `feature_std`: per-dimension std across the batch, averaged over
    dimensions. For d-dim features uniform on the unit sphere this sits
    near 1/sqrt(d); a slide toward 0 means the batch is converging to a
    single direction — dimensional collapse — while the InfoNCE loss
    can still look busy. `feature_dim_active` counts dimensions whose
    std is above 10% of the uniform-sphere value (coarse effective-rank
    gauge)."""
    f = feats.astype(jnp.float32)
    std = jnp.std(f, axis=0)  # (dim,)
    uniform = 1.0 / jnp.sqrt(jnp.asarray(f.shape[-1], jnp.float32))
    return {
        "feature_std": jnp.mean(std),
        "feature_dim_active": jnp.sum(std > 0.1 * uniform).astype(jnp.float32),
    }


def queue_age(
    step: jax.Array, num_negatives: int, global_batch: int, num_buckets: int = 8
) -> dict:
    """Age distribution of the enqueued keys, in steps.

    The FIFO writes `global_batch` keys per step, so the dictionary
    holds the last K/B batches; the batch enqueued j steps ago has age
    j. Early in training (step < K/B) the older slots still hold their
    random init — their age is capped at `step` (they are as stale as
    the run is old). All quantities derive from `step` and the static
    (K, B), so this costs a handful of scalar ops, yet it makes
    dictionary staleness — MoCo's central consistency trade-off — a
    first-class, plottable signal.

    Returns `queue_age_mean`, `queue_age_max` (steps) and
    `queue_age_hist` (fraction of keys per age bucket, oldest last;
    fixed `num_buckets` length so the JSONL schema is stable)."""
    depth = max(num_negatives // max(global_batch, 1), 1)  # batches held
    ages = jnp.minimum(jnp.arange(1, depth + 1, dtype=jnp.float32), step.astype(jnp.float32))
    edges = jnp.linspace(0.0, float(depth), num_buckets + 1)  # mocolint: disable=JX002  (depth is a static Python int from config, not a traced value)
    # bucket membership via searchsorted (jnp.histogram is fine too, but
    # this keeps the bucket count static and the dtype explicit)
    bucket = jnp.clip(jnp.searchsorted(edges, ages, side="right") - 1, 0, num_buckets - 1)
    hist = jnp.zeros((num_buckets,), jnp.float32).at[bucket].add(1.0) / depth
    return {
        "queue_age_mean": jnp.mean(ages),
        "queue_age_max": jnp.max(ages),
        "queue_age_hist": hist,
    }


def health_summary(
    params_q,
    params_k,
    feats_q: jax.Array,
    pos_logits: jax.Array,
    neg_logits: jax.Array,
    step: jax.Array,
    num_negatives: int = 0,
    global_batch: int = 0,
) -> dict:
    """One-call bundle for the train step: EMA drift + logit stats +
    collapse gauges (+ queue staleness when a queue exists). All values
    are jnp scalars/arrays; the caller merges them into the step's
    metrics dict (and pmean's the batch-local ones)."""
    out = {}
    out.update(ema_drift(params_q, params_k))
    out.update(logit_stats(pos_logits, neg_logits))
    out.update(feature_stats(feats_q))
    if num_negatives and global_batch:
        out.update(queue_age(step, num_negatives, global_batch))
    return out


# Keys whose values are batch-local statistics (must be pmean'd over the
# data axis); the rest are functions of replicated state and need no
# reduction. The split lives here so the step function can't drift out
# of sync with the metric definitions.
BATCH_LOCAL_KEYS = (
    "logit_pos_mean",
    "logit_pos_std",
    "logit_neg_mean",
    "logit_neg_std",
    "feature_std",
    "feature_dim_active",
)
