"""Hierarchical span tracer with Chrome-trace (Perfetto) export.

The reference has no timing observability beyond `AverageMeter` console
lines; `jax.profiler` traces exist but capture device ops, not the
host-side structure of a training run (where did the wall time of epoch
7 go — input wait, dispatch, checkpoint write, kNN eval?). This tracer
answers that question with nested spans:

    with obs.span("epoch", epoch=3):
        with obs.span("data_wait"):
            batch = next(it)
        with obs.span("step"):
            state, metrics = step_fn(state, batch, rng)

Spans are recorded per-thread (the prefetch producer's `host_decode`
spans land on their own track) and written in two forms:

- a streaming JSONL file (one object per completed span, flushed as
  written — a SIGKILL loses at most the span being formatted), and
- `export_chrome(path)`: a Chrome trace-event JSON (`ph: "X"` complete
  events, microsecond timestamps) viewable in Perfetto / about:tracing,
  where nesting is rendered from timestamp containment per thread.

Deliberately stdlib-only (no jax import): the tracer must be usable
from any host-side module — data loaders, checkpoint I/O, report
scripts — without dragging a backend in.

Thread safety: completed spans append under a lock; the open-span stack
is thread-local, so concurrent threads can't corrupt each other's
nesting. The in-memory span list is bounded (`max_spans`); the JSONL
stream is not (every span always reaches the file).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from moco_tpu.analysis import tsan


class _NullSpan:
    """Reusable no-op context manager — the zero-cost path when no
    tracer is installed (hot loops call `span()` unconditionally)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanCM:
    """Context manager for one live span: records ts on enter, emits the
    completed event on exit (even when the body raises)."""

    __slots__ = ("tracer", "name", "args", "t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self.tracer._stack().append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        stack = self.tracer._stack()
        stack.pop()
        self.tracer._emit(self.name, self.t0, t1, len(stack), self.args, exc_type)
        return False


class Tracer:
    """Collects hierarchical spans; see the module docstring.

    `jsonl_path`: stream completed spans there as they close (None =
    in-memory only). `max_spans` bounds the in-memory list used by
    `export_chrome` — past it, new spans still stream to JSONL but the
    Chrome export notes the drop count instead of growing unboundedly.
    """

    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        max_spans: int = 200_000,
        process_index: int = 0,
    ):
        # tsan factory (analysis/tsan.py): traced under --sanitize-threads
        self._lock = tsan.make_lock("obs.trace")
        self._local = threading.local()
        self._spans: list[dict] = []
        self._dropped = 0
        self.max_spans = max_spans
        # multi-process runs tag every span with the process index so
        # scripts/trace_merge.py can stitch per-host streams into one
        # Perfetto file with a track per host
        self.process_index = int(process_index)
        # perf_counter origin so ts starts near 0 (Perfetto-friendly);
        # wall-clock anchor recorded for post-hoc correlation with
        # metrics.jsonl `time` fields.
        self._t0 = time.perf_counter()
        self.wall_t0 = time.time()
        self.jsonl_path = jsonl_path
        self._f = None
        if jsonl_path:
            os.makedirs(os.path.dirname(os.path.abspath(jsonl_path)), exist_ok=True)
            self._f = open(jsonl_path, "a", buffering=1)

    # -- recording -------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **args) -> _SpanCM:
        return _SpanCM(self, name, args)

    def _emit(self, name, t0, t1, depth, args, exc_type) -> None:
        rec = {
            "name": name,
            "ts": round((t0 - self._t0) * 1e6, 1),  # µs, trace-relative
            "dur": round((t1 - t0) * 1e6, 1),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            "depth": depth,
            "p": self.process_index,
        }
        if args:
            rec["args"] = args
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(rec)
            else:
                self._dropped += 1
            if self._f is not None and not self._f.closed:
                self._f.write(json.dumps(rec) + "\n")

    def emit_span(
        self,
        name: str,
        t0: float,
        t1: float,
        tid: Optional[int] = None,
        thread: Optional[str] = None,
        **args,
    ) -> None:
        """Record a completed span from EXPLICIT perf_counter stamps,
        optionally onto a virtual track (`tid`/`thread` override). This
        is the off-thread emission path: the serving stack stamps
        request stages on its batcher thread (obs/reqtrace.py) and a
        flusher thread renders them here later — `span()`'s
        enter/exit-on-the-current-thread contract can't express that."""
        rec = {
            "name": name,
            "ts": round((t0 - self._t0) * 1e6, 1),
            "dur": round((t1 - t0) * 1e6, 1),
            "tid": threading.get_ident() if tid is None else int(tid),
            "thread": thread or threading.current_thread().name,
            "depth": 0,
            "p": self.process_index,
        }
        if args:
            rec["args"] = args
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(rec)
            else:
                self._dropped += 1
            if self._f is not None and not self._f.closed:
                self._f.write(json.dumps(rec) + "\n")

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker event (checkpoint committed, fault
        injected, ...) — renders as an arrow in Perfetto."""
        t = time.perf_counter()
        self._emit(name, t, t, len(self._stack()), {**args, "instant": True}, None)

    def counter(self, name: str, **values) -> None:
        """Numeric time series (Chrome `ph:"C"` counter events): the
        device prefetch ring charts its live staged depth this way, so
        Perfetto shows the input pipeline filling/draining against the
        step spans. `values` are the series of one counter track."""
        rec = {
            "name": name,
            "ts": round((time.perf_counter() - self._t0) * 1e6, 1),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            "p": self.process_index,
            "counter": {k: float(v) for k, v in values.items()},
        }
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(rec)
            else:
                self._dropped += 1
            if self._f is not None and not self._f.closed:
                self._f.write(json.dumps(rec) + "\n")

    # -- export ----------------------------------------------------------

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def export_chrome(self, path: str) -> str:
        """Write the Chrome trace-event JSON; returns `path`. pid is the
        PROCESS INDEX (not the OS pid), so merged multi-process traces
        get one track group per host."""
        events = spans_to_chrome_events(self.snapshot(), pid=self.process_index)
        meta = {
            "wall_t0": self.wall_t0,
            "process_index": self.process_index,
            "dropped_spans": self._dropped,
        }
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(
                {"traceEvents": events, "displayTimeUnit": "ms", "otherData": meta},
                f,
            )
        return path

    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.flush()
            self._f.close()


def spans_to_chrome_events(
    spans: list[dict],
    pid: int = 0,
    process_name: Optional[str] = None,
    ts_offset_us: float = 0.0,
) -> list[dict]:
    """Span records -> Chrome trace-event list (`ph:"X"` complete events
    plus thread-name metadata). Shared by the live tracer,
    `scripts/obs_report.py`'s rebuild-from-JSONL path, and
    `scripts/trace_merge.py` (which passes a per-host `ts_offset_us`
    clock correction and a `process_name` track label)."""
    events: list[dict] = []
    if process_name is not None:
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": process_name},
            }
        )
    thread_names: dict[int, str] = {}
    for s in spans:
        tid = s.get("tid", 0)
        thread_names.setdefault(tid, s.get("thread", f"thread-{tid}"))
        if "counter" in s:  # numeric series -> Chrome counter track
            events.append(
                {
                    "name": s["name"],
                    "ph": "C",
                    "ts": s["ts"] + ts_offset_us,
                    "pid": pid,
                    "tid": tid,
                    "args": s["counter"],
                }
            )
            continue
        ev = {
            "name": s["name"],
            "ph": "X",
            "ts": s["ts"] + ts_offset_us,
            "dur": s.get("dur", 0),
            "pid": pid,
            "tid": tid,
        }
        args = dict(s.get("args") or {})
        if "error" in s:
            args["error"] = s["error"]
        if args:
            ev["args"] = args
        events.append(ev)
    for tid, name in thread_names.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": name},
            }
        )
    return events


# -- module-level current tracer (the wiring mechanism) -------------------
#
# Pipelines, checkpointing, and kNN eval call `obs.span(...)` without a
# tracer in hand; the train driver installs one for the run's duration.
# When none is installed the call returns a shared no-op context manager
# (one attribute read + one call — cheap enough for per-batch sites).

_tracer: Optional[Tracer] = None


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install (or clear, with None) the process-wide tracer; returns
    the previous one so callers can restore it."""
    global _tracer
    prev = _tracer
    _tracer = tracer
    return prev


def get_tracer() -> Optional[Tracer]:
    return _tracer


def span(name: str, **args):
    t = _tracer
    return t.span(name, **args) if t is not None else _NULL_SPAN


def instant(name: str, **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, **args)


def counter(name: str, **values) -> None:
    t = _tracer
    if t is not None:
        t.counter(name, **values)
