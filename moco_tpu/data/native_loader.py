"""ctypes bindings for the native (C++) image loader.

`native/loader.cc` replaces the reference's 32 DataLoader worker
*processes* (`main_moco.py:~L255-260`) with an in-process C++ thread
pool: file read → libjpeg/libpng decode → antialiased bilinear
shortest-side resize → center-crop into a caller-owned contiguous uint8
batch, all outside the GIL. `NativeImageFolderDataset` is drop-in
API-compatible with `ImageFolderDataset` (same `load`, plus a batched
`load_batch` fast path the pipeline prefers when present).

Samples the C++ decoders can't handle (webp/bmp/ppm, CMYK JPEGs) are
retried per-slot through the PIL path — same output geometry — so
results are host-independent rather than silently zero-filled.

The library auto-builds via `make` on first use, serialized across
processes with an fcntl lock (multi-host training, pytest-xdist); if the
toolchain or libjpeg is missing the import fails gracefully and callers
fall back to the PIL path (`native_available()` to probe).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from moco_tpu.utils import retry

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libmoco_loader.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
ABI_VERSION = 4


def _build_locked() -> None:
    """Cross-process-safe build: exclusive fcntl lock + re-check, so only
    one process runs make and nobody dlopens a half-written .so."""
    import fcntl

    os.makedirs(_NATIVE_DIR, exist_ok=True)
    lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
    with open(lock_path, "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            if not os.path.exists(_LIB_PATH):
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True,
                    capture_output=True,
                    text=True,
                )
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)


def _declare_bindings(lib: ctypes.CDLL) -> None:
    """Symbol declarations for the CURRENT ABI — only called after the
    version check passes (a stale .so may lack the newer symbols, and a
    failed dlsym here would otherwise mask the rebuild path)."""
    lib.mtl_create.restype = ctypes.c_void_p
    lib.mtl_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.mtl_load_batch.restype = ctypes.c_int
    lib.mtl_load_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.mtl_load_batch_crops.restype = ctypes.c_int
    lib.mtl_load_batch_crops.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.mtl_get_dims.restype = ctypes.c_int
    lib.mtl_get_dims.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.mtl_create_raw.restype = ctypes.c_void_p
    lib.mtl_create_raw.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.mtl_destroy.argtypes = [ctypes.c_void_p]


def _load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            _build_locked()
        lib = ctypes.CDLL(_LIB_PATH)
        # version check BEFORE declaring ABI-current symbols: a stale .so
        # lacks them and the dlsym failure would shadow this rebuild path
        lib.mtl_version.restype = ctypes.c_int
        if lib.mtl_version() != ABI_VERSION:
            # stale .so from an older checkout: rebuild once
            os.remove(_LIB_PATH)
            _build_locked()
            lib = ctypes.CDLL(_LIB_PATH)
            lib.mtl_version.restype = ctypes.c_int
            if lib.mtl_version() != ABI_VERSION:
                raise RuntimeError("native loader ABI mismatch after rebuild")
        _declare_bindings(lib)
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        _load_lib()
        return True
    except Exception:
        return False


class NativeBatchLoader:
    """Thin handle over the C++ loader for a fixed list of image paths."""

    def __init__(self, paths: list[str], canvas: int, threads: int = 8):
        self._lib = _load_lib()
        arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
        self._handle = self._lib.mtl_create(arr, len(paths), canvas, threads)
        if not self._handle:
            raise RuntimeError("mtl_create failed")
        self.paths = paths
        self.canvas = canvas
        self.num_paths = len(paths)
        # Hard (native + PIL both failed) decode failures, cumulative.
        # Zero-filled slots are silent black images to the trainer —
        # this counter is how the pipeline makes them visible
        # (`decode_failures` in metrics.jsonl).
        self.decode_failures = 0

    def _pil_fallback(self, path: str) -> Optional[np.ndarray]:
        """Decode one image through PIL with the same geometry (the
        ImageFolderDataset.load recipe) for formats the C++ side lacks.
        The file read retries (transient NFS/GCS errors must not count
        as a decode failure); a genuinely undecodable image returns
        None."""
        try:
            from PIL import Image

            size = self.canvas

            def _decode():
                with Image.open(path) as im:
                    im = im.convert("RGB")
                    w, h = im.size
                    s = size / min(w, h)
                    im = im.resize(
                        (max(size, round(w * s)), max(size, round(h * s))),
                        resample=Image.BILINEAR,
                    )
                    return np.asarray(im, np.uint8)

            arr = retry.retry_call(_decode, site="data.native_pil")
            h, w, _ = arr.shape
            y0, x0 = (h - size) // 2, (w - size) // 2
            return arr[y0 : y0 + size, x0 : x0 + size]
        except Exception:
            return None

    def load_batch(self, indices: np.ndarray) -> np.ndarray:
        """(bs, canvas, canvas, 3) uint8. Slots the native decoders fail on
        are retried via PIL; only doubly-failed slots stay zero."""
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        out = np.empty((len(idx), self.canvas, self.canvas, 3), np.uint8)
        status = np.empty(len(idx), np.uint8)
        errors = self._lib.mtl_load_batch(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if errors:
            hard_failures = 0
            for slot in np.nonzero(status == 0)[0]:
                i = int(idx[slot])
                img = self._pil_fallback(self.paths[i]) if 0 <= i < self.num_paths else None
                if img is not None:
                    out[slot] = img
                else:
                    hard_failures += 1
            if hard_failures:
                import warnings

                self.decode_failures += hard_failures
                warnings.warn(
                    f"native loader: {hard_failures}/{len(idx)} images failed to decode"
                )
        return out

    def get_dims(self, indices: np.ndarray) -> np.ndarray:
        """(bs, 2) original (h, w) per sample — header parse only, cached
        in C++. Slots that fail get (0, 0); callers treat those as
        undecodable (their crops degrade to the PIL fallback)."""
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        dims = np.empty((len(idx), 2), np.int32)
        status = np.empty(len(idx), np.uint8)
        self._lib.mtl_get_dims(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx),
            dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return dims

    def _pil_fallback_crops(
        self, path: str, boxes: np.ndarray, out_size: int
    ) -> Optional[np.ndarray]:
        """(n_crops, out, out, 3) via PIL resized-crop — same geometry."""
        try:
            from PIL import Image

            with Image.open(path) as im:
                im = im.convert("RGB")
                w, h = im.size
                outs = []
                for y0, x0, ch, cw in np.asarray(boxes, np.int64):
                    y0 = int(np.clip(y0, 0, h - 1))
                    x0 = int(np.clip(x0, 0, w - 1))
                    ch = int(np.clip(ch, 1, h - y0))
                    cw = int(np.clip(cw, 1, w - x0))
                    crop = im.crop((x0, y0, x0 + cw, y0 + ch)).resize(
                        (out_size, out_size), resample=Image.BILINEAR
                    )
                    outs.append(np.asarray(crop, np.uint8))
                return np.stack(outs)
        except Exception:
            return None

    def load_crops(
        self, indices: np.ndarray, boxes: np.ndarray, out_size: int
    ) -> np.ndarray:
        """(bs, n_crops, out, out, 3) uint8: decode each sample ONCE, then
        antialias-resize each of its boxes (y0, x0, ch, cw in original
        coords). Failed slots retry through PIL; doubly-failed stay zero."""
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        boxes = np.ascontiguousarray(boxes, dtype=np.int32)
        bs, n_crops = boxes.shape[0], boxes.shape[1]
        assert bs == len(idx) and boxes.shape[2] == 4
        out = np.empty((bs, n_crops, out_size, out_size, 3), np.uint8)
        status = np.empty(bs, np.uint8)
        errors = self._lib.mtl_load_batch_crops(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            bs,
            boxes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_crops,
            out_size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if errors:
            hard_failures = 0
            for slot in np.nonzero(status == 0)[0]:
                i = int(idx[slot])
                img = (
                    self._pil_fallback_crops(self.paths[i], boxes[slot], out_size)
                    if 0 <= i < self.num_paths
                    else None
                )
                if img is not None:
                    out[slot] = img
                else:
                    hard_failures += 1
            if hard_failures:
                import warnings

                self.decode_failures += hard_failures
                warnings.warn(
                    f"native loader: {hard_failures}/{bs} images failed to decode"
                )
        return out

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.mtl_destroy(handle)
            self._handle = None


class NativeRawBatchLoader:
    """C++ loader over a packed-RGB cache file (moco_tpu/data/cache.py):
    the codec stage disappears (samples are raw blobs mmap'd in C++) and
    the antialiased crop+resize runs in the C++ worker pool instead of
    PIL — no GIL, no per-image Python. Same load_crops/load_batch/
    get_dims surface as NativeBatchLoader; raw reads cannot soft-fail,
    so there is no PIL fallback (dead build slots stay zero, like the
    path backend's doubly-failed slots)."""

    def __init__(
        self,
        data_path: str,
        offsets: np.ndarray,
        dims: np.ndarray,
        canvas: int,
        threads: int = 8,
    ):
        self._lib = _load_lib()
        offsets = np.ascontiguousarray(offsets, np.int64)
        dims = np.ascontiguousarray(dims, np.int32)
        n = len(dims)
        assert len(offsets) == n + 1
        # mtl_create_raw copies both arrays into C++ vectors at create
        self._handle = self._lib.mtl_create_raw(
            data_path.encode(),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n,
            canvas,
            threads,
        )
        if not self._handle:
            raise RuntimeError(f"mtl_create_raw failed for {data_path}")
        self.canvas = canvas
        self._dims = dims  # (n, 2) int32, answers get_dims without C++

    def get_dims(self, indices: np.ndarray) -> np.ndarray:
        return self._dims[np.asarray(indices, np.int64)]

    def load_batch(self, indices: np.ndarray) -> np.ndarray:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        out = np.empty((len(idx), self.canvas, self.canvas, 3), np.uint8)
        status = np.empty(len(idx), np.uint8)
        errors = self._lib.mtl_load_batch(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        self._check(errors, status, idx)
        return out

    def load_crops(
        self, indices: np.ndarray, boxes: np.ndarray, out_size: int
    ) -> np.ndarray:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        boxes = np.ascontiguousarray(boxes, dtype=np.int32)
        bs, n_crops = boxes.shape[0], boxes.shape[1]
        assert bs == len(idx) and boxes.shape[2] == 4
        out = np.empty((bs, n_crops, out_size, out_size, 3), np.uint8)
        status = np.empty(bs, np.uint8)
        errors = self._lib.mtl_load_batch_crops(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            bs,
            boxes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_crops,
            out_size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        self._check(errors, status, idx)
        return out

    def _check(self, errors: int, status: np.ndarray, idx: np.ndarray) -> None:
        """Raw blob reads cannot soft-fail like codec decodes can — a
        failed slot means the cache index is inconsistent with data.bin.
        Training on silently zero-filled slots would be much worse than
        stopping, so raise."""
        if errors:
            bad = idx[np.nonzero(status == 0)[0]].tolist()
            raise RuntimeError(
                f"raw cache read failed for indices {bad[:8]}{'...' if len(bad) > 8 else ''} "
                "— the packed cache is corrupt or its index mismatches data.bin; "
                "delete the cache dir to rebuild"
            )

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.mtl_destroy(handle)
            self._handle = None


class NativeImageFolderDataset:
    """`root/class_x/img.jpg` layout (torchvision ImageFolder semantics,
    like `ImageFolderDataset`) backed by the C++ decode pool."""

    def __init__(self, root: str, decode_size: int = 256, threads: int = 8):
        from moco_tpu.data.datasets import ImageFolderDataset

        # reuse the Python class for directory walking / label assignment
        py = ImageFolderDataset(root, decode_size=decode_size)
        self.samples = py.samples
        self.class_to_idx = py.class_to_idx
        self.num_classes = py.num_classes
        self.decode_size = decode_size
        self._labels = np.asarray([l for _, l in py.samples], np.int32)
        self._loader = NativeBatchLoader(
            [p for p, _ in py.samples], canvas=decode_size, threads=threads
        )

    def __len__(self) -> int:
        return len(self.samples)

    @property
    def decode_failures(self) -> int:
        """Cumulative hard decode failures (native + PIL both failed);
        surfaced by the pipeline as a `decode_failures` metric."""
        return self._loader.decode_failures

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        if decode_size is not None and decode_size != self.decode_size:
            raise ValueError(
                f"native loader decodes at the fixed canvas {self.decode_size}; "
                f"got decode_size={decode_size} (use ImageFolderDataset for variable sizes)"
            )
        img = self._loader.load_batch(np.asarray([index]))[0]
        return img, int(self._labels[index])

    def load_batch(self, indices: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        return self._loader.load_batch(indices), self._labels[np.asarray(indices)]

    # -- host-crop protocol (pipeline samples torchvision-exact RRC boxes
    # against original geometry; decode once, crop N times) --------------
    def dims(self, indices: np.ndarray) -> np.ndarray:
        return self._loader.get_dims(indices)

    def load_crop_batch(
        self, indices: np.ndarray, boxes: np.ndarray, out_size: int, pool=None
    ) -> tuple[np.ndarray, np.ndarray]:
        # `pool` accepted for PIL-path signature compatibility; the C++
        # loader owns its own thread pool.
        crops = self._loader.load_crops(indices, boxes, out_size)
        return crops, self._labels[np.asarray(indices)]
