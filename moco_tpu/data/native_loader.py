"""ctypes bindings for the native (C++) image loader.

`native/loader.cc` replaces the reference's 32 DataLoader worker
*processes* (`main_moco.py:~L255-260`) with an in-process C++ thread
pool: file read → libjpeg/libpng decode → bilinear shortest-side resize
→ center-crop into a caller-owned contiguous uint8 batch, all outside
the GIL. `NativeImageFolderDataset` is drop-in API-compatible with
`ImageFolderDataset` (same `load`, plus a batched `load_batch` fast path
the pipeline prefers when present).

The library auto-builds via `make` on first use; if the toolchain or
libjpeg is missing the import fails gracefully and callers fall back to
the PIL path (`native_available()` to probe).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libmoco_loader.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                text=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.mtl_create.restype = ctypes.c_void_p
        lib.mtl_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.mtl_load_batch.restype = ctypes.c_int
        lib.mtl_load_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.mtl_destroy.argtypes = [ctypes.c_void_p]
        lib.mtl_version.restype = ctypes.c_int
        assert lib.mtl_version() == 1
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        _load_lib()
        return True
    except Exception:
        return False


class NativeBatchLoader:
    """Thin handle over the C++ loader for a fixed list of image paths."""

    def __init__(self, paths: list[str], canvas: int, threads: int = 8):
        self._lib = _load_lib()
        arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
        self._handle = self._lib.mtl_create(arr, len(paths), canvas, threads)
        if not self._handle:
            raise RuntimeError("mtl_create failed")
        self.canvas = canvas
        self.num_paths = len(paths)

    def load_batch(self, indices: np.ndarray) -> np.ndarray:
        """(bs, canvas, canvas, 3) uint8; failed decodes are zero frames."""
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        out = np.empty((len(idx), self.canvas, self.canvas, 3), np.uint8)
        errors = self._lib.mtl_load_batch(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if errors:
            import warnings

            warnings.warn(f"native loader: {errors}/{len(idx)} images failed to decode")
        return out

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.mtl_destroy(handle)
            self._handle = None


class NativeImageFolderDataset:
    """`root/class_x/img.jpg` layout (torchvision ImageFolder semantics,
    like `ImageFolderDataset`) backed by the C++ decode pool."""

    def __init__(self, root: str, decode_size: int = 256, threads: int = 8):
        from moco_tpu.data.datasets import ImageFolderDataset

        # reuse the Python class for directory walking / label assignment
        py = ImageFolderDataset(root, decode_size=decode_size)
        self.samples = py.samples
        self.class_to_idx = py.class_to_idx
        self.decode_size = decode_size
        self._labels = np.asarray([l for _, l in py.samples], np.int32)
        self._loader = NativeBatchLoader(
            [p for p, _ in py.samples], canvas=decode_size, threads=threads
        )

    def __len__(self) -> int:
        return len(self.samples)

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        img = self._loader.load_batch(np.asarray([index]))[0]
        return img, int(self._labels[index])

    def load_batch(self, indices: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        return self._loader.load_batch(indices), self._labels[np.asarray(indices)]
