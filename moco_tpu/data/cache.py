"""Decode-once packed RGB cache for path-based datasets.

The reference hides JPEG-decode cost behind 32 DataLoader worker
processes per GPU (`main_moco.py:~L256` num_workers); on TPU hosts with
few cores the decode is the input-pipeline bound (see PROFILE.md /
bench.py's with-data rate). This cache removes the per-epoch decode
entirely: every image is decoded ONCE at full original geometry and its
raw RGB pixels appended to one packed file; epochs then read crops
straight out of an `np.memmap` — no codec work, no per-image files, and
the host-crop RandomResizedCrop protocol keeps sampling boxes against
the ORIGINAL image dims, so the crop distribution stays
torchvision-exact (the same guarantee the direct JPEG path gives).

Layout under `cache_dir`:
    data.bin        — concatenated H*W*3 uint8 blobs (original geometry)
    canvas_{S}.bin  — (N, S, S, 3) uint8 fixed-stride canvases
                      (shortest-side resize + center crop at S), so the
                      canvas/on-device-crop input mode (`host_rrc=False`)
                      is a pure mmap row read — zero host codec AND
                      resize work per epoch
    index.npz       — offsets (N+1,) int64, dims (N,2) int32 [h,w],
                      labels (N,) int32, num_classes
    .complete       — stamp JSON {n, canvas_sizes, root, fingerprint}

Safety properties:
- builds take an exclusive fcntl lock (same pattern as the native
  loader's cross-process build lock) and write per-pid temp names, so
  concurrent processes sharing a cache_dir cannot interleave writes;
- the stamp records the SOURCE identity (root path + a fingerprint of
  the (path, label, file-size) listing); reuse verifies both, so a cache
  from a different source, one whose source gained/lost images or
  classes, or files re-encoded in place under identical names (size
  drift) raises instead of silently serving the wrong pixels. (If the
  source directory is gone the self-contained cache is trusted as-is.)
  A same-size in-place pixel edit is the one drift this cannot see —
  delete the cache_dir to force a rebuild;
- a cache built at one canvas size grows canvases for new sizes on
  demand from data.bin (no re-decode), so changing image_size never
  silently drops the mmap fast path.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Callable, Optional, Union

import numpy as np

from moco_tpu.utils import retry

__all__ = ["PackedRGBCacheDataset", "build_rgb_cache"]


def _fingerprint(samples, legacy: bool = False) -> str:
    """Identity of the source listing. v2 folds each file's SIZE into the
    per-sample hash so files re-encoded in place under identical names
    (e.g. a synthetic folder regenerated with new constants) are caught
    as drift, not served stale. `legacy=True` reproduces the pre-size
    format so caches stamped before v2 still verify instead of being
    invalidated wholesale."""
    h = hashlib.sha256()
    for path, label in samples:
        if legacy:
            h.update(f"{os.path.basename(path)}\0{label}\n".encode())
        else:
            try:
                size = os.path.getsize(path)
            except OSError:
                size = -1
            h.update(f"{os.path.basename(path)}\0{label}\0{size}\n".encode())
    prefix = "" if legacy else "v2:"
    return f"{prefix}{len(samples)}:{h.hexdigest()[:16]}"


def _read_stamp(cache_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(cache_dir, ".complete")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _canvas(arr: np.ndarray, size: int) -> np.ndarray:
    """Shortest-side BILINEAR resize + square center crop — the same
    canvas ImageFolderDataset.load produces from the decoded image."""
    from PIL import Image

    h, w, _ = arr.shape
    s = size / min(w, h)
    im = Image.fromarray(np.ascontiguousarray(arr)).resize(
        (max(size, round(w * s)), max(size, round(h * s))),
        resample=Image.BILINEAR,
    )
    out = np.asarray(im, np.uint8)
    h, w, _ = out.shape
    y0, x0 = (h - size) // 2, (w - size) // 2
    return out[y0 : y0 + size, x0 : x0 + size]


def build_rgb_cache(
    source_or_factory: Union[object, Callable[[], object]],
    cache_dir: str,
    num_workers: int = 8,
    canvas_size: int = 256,
    root: Optional[str] = None,
) -> str:
    """Decode every image of a source dataset (anything with `.samples`
    [(path, label), ...]) at ORIGINAL size into the packed-file layout,
    plus a fixed-stride canvas file at `canvas_size`.

    `source_or_factory` may be a zero-arg callable; on reuse it is still
    invoked (a directory listing) to verify the stamp's fingerprint, but
    no pixels are re-decoded — and if construction fails (source
    directory since removed) the self-contained cache is trusted as-is.
    `root` is the source's directory, recorded in the stamp on build and
    checked on reuse. A stale cache — different root, or a listing whose
    fingerprint drifted (images/classes added or removed) — raises
    instead of silently serving wrong pixels. A complete cache missing
    `canvas_{canvas_size}.bin` grows it from data.bin without
    re-decoding. Returns `cache_dir`."""
    stamp = _read_stamp(cache_dir)
    root_real = os.path.realpath(root) if root else None
    if stamp is not None:
        # mismatch only matters when the REQUESTED root actually exists:
        # with the source gone, split detection upstream degrades to a
        # different root string, and the self-contained cache must still
        # be usable
        if (
            root_real
            and stamp.get("root")
            and stamp["root"] != root_real
            and os.path.isdir(root_real)
        ):
            raise ValueError(
                f"RGB cache at {cache_dir} was built from {stamp['root']!r}, "
                f"not {root_real!r} — point --cache-dir elsewhere or delete it"
            )
        if stamp.get("fingerprint"):
            try:
                source = (
                    source_or_factory() if callable(source_or_factory) else source_or_factory
                )
            except OSError:
                # source DIRECTORY gone: the cache is self-contained.
                # Anything else (e.g. "no images under root" — a directory
                # that exists but lost its images) must propagate: that IS
                # the drift the fingerprint check exists to catch.
                source = None
            legacy = not stamp["fingerprint"].startswith("v2:")
            if source is not None and _fingerprint(source.samples, legacy=legacy) != stamp["fingerprint"]:
                raise ValueError(
                    f"RGB cache at {cache_dir} is stale: the source listing under "
                    f"{stamp.get('root') or root_real!r} changed since the build "
                    "(images or classes added/removed) — delete the cache dir to rebuild"
                )
        if canvas_size in stamp.get("canvas_sizes", []):
            return cache_dir
        _with_build_lock(cache_dir, lambda: _grow_canvas(cache_dir, canvas_size))
        return cache_dir
    source = source_or_factory() if callable(source_or_factory) else source_or_factory
    _with_build_lock(
        cache_dir,
        lambda: _build(source, cache_dir, num_workers, canvas_size, root_real),
    )
    return cache_dir


def _with_build_lock(cache_dir: str, fn) -> None:
    """Exclusive fcntl lock + post-acquire re-check wrapper (the native
    loader's build-lock pattern): only one process builds; the rest wait
    and find the finished artifacts."""
    import fcntl

    os.makedirs(cache_dir, exist_ok=True)
    with open(os.path.join(cache_dir, ".build.lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            fn()
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)


def _build(source, cache_dir, num_workers, canvas_size, root_real) -> None:
    from concurrent.futures import ThreadPoolExecutor

    from PIL import Image

    if _read_stamp(cache_dir) is not None:  # another process built it
        _grow_canvas(cache_dir, canvas_size)
        return
    samples = source.samples
    n = len(samples)

    dead_slots = [0]  # undecodable sources, recorded in the stamp

    def decode(i):
        """Decode + canvas-resize in the worker (the consumer thread only
        writes), returning ready-to-write bytes. File reads retry;
        genuinely undecodable sources become counted dead slots."""
        path, label = samples[i]
        try:
            def _read():
                with Image.open(path) as im:
                    return np.asarray(im.convert("RGB"), np.uint8)

            arr = retry.retry_call(_read, site="data.cache_build")
        except Exception:
            dead_slots[0] += 1  # dead slot, mirrors loaders — but COUNTED
            arr = np.zeros((1, 1, 3), np.uint8)
        return arr.tobytes(), arr.shape[:2], _canvas(arr, canvas_size).tobytes(), int(label)

    offsets = np.zeros(n + 1, np.int64)
    dims = np.zeros((n, 2), np.int32)
    labels = np.zeros(n, np.int32)
    pid = os.getpid()  # per-pid temps: no interleaved writes even unlocked
    data_tmp = os.path.join(cache_dir, f"data.bin.tmp.{pid}")
    canvas_tmp = os.path.join(cache_dir, f"canvas_{canvas_size}.bin.tmp.{pid}")
    workers = max(num_workers, 1)
    with open(data_tmp, "wb") as f, open(canvas_tmp, "wb") as cf, ThreadPoolExecutor(
        max_workers=workers
    ) as pool:
        # bounded submission window (2x workers): plain pool.map would
        # enqueue all n decodes up front and the finished full-geometry
        # arrays would accumulate far ahead of the serial writer —
        # unbounded memory on an ImageNet-scale build
        from collections import deque

        window: deque = deque()
        i = 0
        for j in range(min(2 * workers, n)):
            window.append(pool.submit(decode, j))
        next_submit = len(window)
        while window:
            raw, hw, canvas_bytes, label = window.popleft().result()
            if next_submit < n:
                window.append(pool.submit(decode, next_submit))
                next_submit += 1
            f.write(raw)
            cf.write(canvas_bytes)
            offsets[i + 1] = offsets[i] + len(raw)
            dims[i] = hw
            labels[i] = label
            i += 1
    np.savez(
        os.path.join(cache_dir, "index.npz"),
        offsets=offsets,
        dims=dims,
        labels=labels,
        num_classes=np.int32(getattr(source, "num_classes", int(labels.max()) + 1)),
    )
    os.replace(data_tmp, os.path.join(cache_dir, "data.bin"))
    os.replace(canvas_tmp, os.path.join(cache_dir, f"canvas_{canvas_size}.bin"))
    if dead_slots[0]:
        import warnings

        warnings.warn(
            f"RGB cache build: {dead_slots[0]}/{n} images failed to decode "
            "(zero-filled dead slots, recorded in the stamp)"
        )
    with open(os.path.join(cache_dir, ".complete"), "w") as f:
        json.dump(
            {
                "n": n,
                "canvas_sizes": [canvas_size],
                "root": root_real,
                "fingerprint": _fingerprint(samples),
                "dead_slots": dead_slots[0],
            },
            f,
        )


def _grow_canvas(cache_dir: str, canvas_size: int) -> None:
    """Add canvas_{S}.bin for a new size to a complete cache, resizing
    from the stored full-geometry pixels (no re-decode)."""
    stamp = _read_stamp(cache_dir)
    if stamp is None or canvas_size in stamp.get("canvas_sizes", []):
        return
    ds = PackedRGBCacheDataset(cache_dir, decode_size=canvas_size, use_native=False)
    pid = os.getpid()
    canvas_tmp = os.path.join(cache_dir, f"canvas_{canvas_size}.bin.tmp.{pid}")
    with open(canvas_tmp, "wb") as cf:
        for i in range(len(ds)):
            cf.write(_canvas(ds._image(i), canvas_size).tobytes())
    os.replace(canvas_tmp, os.path.join(cache_dir, f"canvas_{canvas_size}.bin"))
    stamp["canvas_sizes"] = sorted(stamp.get("canvas_sizes", []) + [canvas_size])
    with open(os.path.join(cache_dir, ".complete"), "w") as f:
        json.dump(stamp, f)


class PackedRGBCacheDataset:
    """Same duck-typed surface as ImageFolderDataset (load / dims /
    load_crop_batch / num_classes), reading from the packed cache.

    `use_native=None` (auto) routes the host-crop protocol through the
    C++ raw loader when the native library is available — the crop+
    resize then runs in the C++ worker pool with no codec, GIL, or
    per-image Python cost. `use_native=False` keeps the PIL resampler
    (bit-exact with the direct JPEG path; the native resampler agrees
    only to the documented mean-abs-diff tolerance)."""

    def __init__(
        self,
        cache_dir: str,
        decode_size: int = 256,
        use_native: Optional[bool] = None,
        num_workers: int = 8,
    ):
        if not os.path.exists(os.path.join(cache_dir, ".complete")):
            raise FileNotFoundError(f"no complete RGB cache under {cache_dir}")
        # transient-store retries on the open path; once the memmap is
        # established, page reads are the kernel's problem
        idx = retry.retry_call(
            np.load, os.path.join(cache_dir, "index.npz"), site="data.cache_open"
        )
        self.offsets = idx["offsets"]
        self._dims = idx["dims"]
        self.labels = idx["labels"]
        self.num_classes = int(idx["num_classes"])
        self.decode_size = decode_size
        self._num_workers = max(num_workers, 1)
        # dead slots stamped at build time: a constant decode_failures
        # count the pipeline surfaces like the live loaders' counters
        stamp = _read_stamp(cache_dir) or {}
        self.decode_failures = int(stamp.get("dead_slots", 0))
        self._data = retry.retry_call(
            np.memmap,
            os.path.join(cache_dir, "data.bin"),
            dtype=np.uint8,
            mode="r",
            site="data.cache_open",
        )
        self._native = None
        if use_native is not False:
            try:
                from moco_tpu.data.native_loader import NativeRawBatchLoader

                self._native = NativeRawBatchLoader(
                    os.path.join(cache_dir, "data.bin"),
                    self.offsets,
                    self._dims,
                    canvas=decode_size,
                    threads=max(num_workers, 1),
                )
            except Exception:
                if use_native:  # explicit request must not degrade silently
                    raise
                self._native = None
        n = len(self.labels)
        canvas_path = os.path.join(cache_dir, f"canvas_{decode_size}.bin")
        self._canvases = (
            np.memmap(canvas_path, dtype=np.uint8, mode="r").reshape(
                n, decode_size, decode_size, 3
            )
            if os.path.exists(canvas_path)
            else None
        )

    def __len__(self) -> int:
        return len(self.labels)

    def _image(self, index: int) -> np.ndarray:
        h, w = self._dims[index]
        start = self.offsets[index]
        return self._data[start : start + h * w * 3].reshape(h, w, 3)

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        """Shortest-side resize + square center-crop canvas, matching
        ImageFolderDataset.load (same BILINEAR semantics) minus the
        decode. At the cache's own canvas size this is a pure mmap row
        read — no resize either."""
        size = decode_size or self.decode_size
        if self._canvases is not None and size == self._canvases.shape[1]:
            return np.asarray(self._canvases[index]), int(self.labels[index])
        return _canvas(self._image(index), size), int(self.labels[index])

    def dims(self, indices) -> np.ndarray:
        return self._dims[np.asarray(indices, np.int64)]

    def load_crop_batch(
        self, indices, boxes: np.ndarray, out_size: int, pool=None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Host-crop protocol against the cached full-geometry pixels:
        same pixels as the JPEG path's decode+crop, at memmap-read cost.
        Routed through the C++ raw loader when available (thread-pool
        crop+resize, no GIL); PIL otherwise."""
        from PIL import Image

        if self._native is not None:
            out = self._native.load_crops(indices, boxes, out_size)
            return out, np.asarray(self.labels[np.asarray(indices, np.int64)], np.int32)

        idx = np.asarray(indices, np.int64)
        boxes = np.asarray(boxes, np.int64)
        bs, n_crops = boxes.shape[0], boxes.shape[1]
        out = np.zeros((bs, n_crops, out_size, out_size, 3), np.uint8)
        labels = np.empty(bs, np.int32)

        def one(row):
            i = int(idx[row])
            labels[row] = self.labels[i]
            arr = self._image(i)
            h, w, _ = arr.shape
            for c in range(n_crops):
                y0, x0, ch, cw = boxes[row, c]
                y0 = int(np.clip(y0, 0, h - 1))
                x0 = int(np.clip(x0, 0, w - 1))
                ch = int(np.clip(ch, 1, h - y0))
                cw = int(np.clip(cw, 1, w - x0))
                crop = Image.fromarray(
                    np.ascontiguousarray(arr[y0 : y0 + ch, x0 : x0 + cw])
                ).resize((out_size, out_size), resample=Image.BILINEAR)
                out[row, c] = np.asarray(crop, np.uint8)

        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            if not hasattr(self, "_crop_pool"):
                self._crop_pool = ThreadPoolExecutor(max_workers=self._num_workers)
            pool = self._crop_pool
        list(pool.map(one, range(bs)))
        return out, labels
