"""On-device, batched, jittable augmentations — the TPU-native redesign of
the reference's PIL/torchvision pipeline (`moco/loader.py` +
`main_moco.py:~L225-255`).

The reference decodes and augments per-image in 32 DataLoader worker
processes (PIL C code). On TPU the elementwise augmentation work
(jitter/grayscale/blur/flip/normalize) fuses into one XLA program and runs
on-device on the whole batch, leaving the host only JPEG decode + crop.
Every op takes images in [0, 1] float, NHWC, and a per-call PRNG key; all
randomness is per-example (`jax.vmap` over split keys) except where noted.

Recipe parity (SURVEY.md §2.2 row 9):
- v2 / `--aug-plus`: RandomResizedCrop(224, scale=(0.2,1)),
  RandomApply(ColorJitter(0.4,0.4,0.4,0.1), p=0.8), RandomGrayscale(0.2),
  RandomApply(GaussianBlur(sigma∈[0.1,2]), p=0.5), HorizontalFlip(0.5),
  Normalize(ImageNet mean/std).
- v1: RandomResizedCrop, RandomGrayscale(0.2), ColorJitter(0.4,0.4,0.4,0.4)
  always applied, HorizontalFlip(0.5), Normalize.

Parity with PIL/torchvision (quantified in tests/test_aug_parity.py):
- RandomResizedCrop reproduces torchvision's 10-attempt rejection sampler
  exactly (integer-rounded crop boxes, randint top-left, center-crop
  fallback with ratio clamping) — vectorized over a fixed attempt axis
  with first-valid selection instead of a Python loop.
- ColorJitter draws the sub-op order per *image* (argsort-of-uniforms
  permutation), matching torchvision's per-call randperm(4).
- GaussianBlur uses a truncated separable Gaussian (fixed 23-tap window,
  the SimCLR convention of ~10% of image size) instead of PIL's
  sequential-box-blur approximation; measured deviation is bounded in the
  parity tests.
- Hue jitter is a float HSV round-trip (torchvision's tensor-backend
  model); it matches PIL's uint8 HSV shift to within quantization
  (~0.003 mean abs at ±0.1, bounded in the parity tests).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)

# ---------------------------------------------------------------- crops


def random_resized_crop_params(
    rng: jax.Array,
    batch: int,
    h: int,
    w: int,
    scale: tuple[float, float] = (0.2, 1.0),
    ratio: tuple[float, float] = (3.0 / 4.0, 4.0 / 3.0),
    attempts: int = 10,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-image crop boxes (y0, x0, ch, cw), each (batch,) float32 holding
    integer values — torchvision RandomResizedCrop.get_params semantics.

    torchvision loops up to 10 attempts: draw area∈scale·A and log-uniform
    aspect∈ratio, round to integer (cw, ch), accept iff the box fits, then
    draw an integer top-left uniformly; after 10 rejections it falls back
    to a ratio-clamped center crop. Vectorized here: all `attempts` draws
    happen up front along a second axis and the first valid one is
    selected per image (independent draws, so picking the first valid
    column is distributionally identical to the sequential loop).
    """
    area = float(h * w)
    k_area, k_ratio, k_y, k_x = jax.random.split(rng, 4)
    shape = (batch, attempts)
    target_area = jax.random.uniform(k_area, shape, minval=scale[0], maxval=scale[1]) * area
    aspect = jnp.exp(
        jax.random.uniform(k_ratio, shape, minval=jnp.log(ratio[0]), maxval=jnp.log(ratio[1]))
    )
    cw_all = jnp.round(jnp.sqrt(target_area * aspect))
    ch_all = jnp.round(jnp.sqrt(target_area / aspect))
    valid = (cw_all > 0) & (cw_all <= w) & (ch_all > 0) & (ch_all <= h)
    first = jnp.argmax(valid, axis=1)  # index of first valid attempt (0 if none)
    any_valid = jnp.any(valid, axis=1)

    def pick(arr):
        return jnp.take_along_axis(arr, first[:, None], axis=1)[:, 0]

    cw, ch = pick(cw_all), pick(ch_all)
    # randint(0, H-h+1) as floor(u * n) with u ∈ [0,1); drawn per attempt so
    # the accepted attempt's top-left is independent of the rejections.
    y0 = jnp.floor(pick(jax.random.uniform(k_y, shape)) * (h - ch + 1.0))
    x0 = jnp.floor(pick(jax.random.uniform(k_x, shape)) * (w - cw + 1.0))

    # Fallback: center crop clamped to the ratio range (static geometry).
    in_ratio = w / h
    if in_ratio < ratio[0]:
        fw, fh = w, round(w / ratio[0])
    elif in_ratio > ratio[1]:
        fh, fw = h, round(h * ratio[1])
    else:
        fw, fh = w, h
    fy, fx = (h - fh) // 2, (w - fw) // 2
    ch = jnp.where(any_valid, ch, float(fh))
    cw = jnp.where(any_valid, cw, float(fw))
    y0 = jnp.where(any_valid, y0, float(fy))
    x0 = jnp.where(any_valid, x0, float(fx))
    return y0, x0, ch, cw


def random_resized_crop(
    rng: jax.Array,
    images: jax.Array,  # (B, H, W, C) float in [0,1]
    out_size: int,
    scale: tuple[float, float] = (0.2, 1.0),
    ratio: tuple[float, float] = (3.0 / 4.0, 4.0 / 3.0),
) -> jax.Array:
    """torchvision RandomResizedCrop: 10-attempt rejection-sampled box
    (`random_resized_crop_params`), crop, bilinear-resize to
    (out_size, out_size)."""
    b, h, w, _ = images.shape
    y0, x0, ch, cw = random_resized_crop_params(rng, b, h, w, scale, ratio)

    def crop_one(img, y0_, x0_, ch_, cw_):
        # scale_and_translate maps output pixel p to input p/scale - translate/scale;
        # we want out [0, out_size) to cover input [x0, x0+cw).
        sy = out_size / ch_
        sx = out_size / cw_
        return jax.image.scale_and_translate(
            img,
            (out_size, out_size, img.shape[-1]),
            (0, 1),
            jnp.array([sy, sx]),
            jnp.array([-y0_ * sy, -x0_ * sx]),
            method="linear",
        )

    return jax.vmap(crop_one)(images, y0, x0, ch, cw)


def center_crop(images: jax.Array, out_size: int, resize_to: int = 256) -> jax.Array:
    """Eval transform: Resize(resize_to) + CenterCrop(out_size)
    (`main_lincls.py` val pipeline)."""
    b, h, w, c = images.shape
    short = min(h, w)
    nh, nw = int(round(h * resize_to / short)), int(round(w * resize_to / short))
    images = jax.image.resize(images, (b, nh, nw, c), method="linear")
    y0, x0 = (nh - out_size) // 2, (nw - out_size) // 2
    return images[:, y0 : y0 + out_size, x0 : x0 + out_size, :]


# ------------------------------------------------------------ color ops


def _blend(a: jax.Array, b: jax.Array, factor: jax.Array) -> jax.Array:
    """torchvision _blend: factor*a + (1-factor)*b, clipped to [0,1]."""
    return jnp.clip(factor * a + (1.0 - factor) * b, 0.0, 1.0)


def _rgb_to_gray(img: jax.Array) -> jax.Array:
    """ITU-R 601 luma, as PIL convert('L') uses."""
    r, g, b = img[..., 0], img[..., 1], img[..., 2]
    return (0.299 * r + 0.587 * g + 0.114 * b)[..., None]


def adjust_brightness(img, factor):
    return _blend(img, jnp.zeros_like(img), factor)


def adjust_contrast(img, factor):
    mean = jnp.mean(_rgb_to_gray(img), axis=(-3, -2, -1), keepdims=True)
    return _blend(img, mean, factor)


def adjust_saturation(img, factor):
    return _blend(img, _rgb_to_gray(img), factor)


def adjust_hue(img, delta):
    """Hue shift by delta (fraction of the color wheel, torch range
    [-0.5, 0.5]) via a float HSV round-trip — the same model torchvision
    uses, preserving S and V exactly. (A YIQ chroma rotation was tried
    first: it preserves luma instead, and the PIL parity test measured
    ~0.17 mean abs deviation on saturated colors — HSV is the parity
    answer.) Branch-free piecewise conversion, vectorized over the batch.
    """
    r, g, b = img[..., 0], img[..., 1], img[..., 2]
    maxc = jnp.maximum(jnp.maximum(r, g), b)
    minc = jnp.minimum(jnp.minimum(r, g), b)
    v = maxc
    c = maxc - minc
    s = jnp.where(maxc > 0, c / jnp.where(maxc > 0, maxc, 1.0), 0.0)
    safe_c = jnp.where(c > 0, c, 1.0)
    rc = (maxc - r) / safe_c
    gc = (maxc - g) / safe_c
    bc = (maxc - b) / safe_c
    h = jnp.where(
        r == maxc, bc - gc, jnp.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc)
    )
    h = jnp.where(c > 0, (h / 6.0) % 1.0, 0.0)

    # delta arrives (B,1,1,1); drop the channel dim so it broadcasts
    # against the (B,H,W) hue plane.
    d = jnp.reshape(delta, delta.shape[:-1]) if delta.ndim == img.ndim else delta
    h = (h + d) % 1.0

    # HSV -> RGB (colorsys sextant form)
    h6 = h * 6.0
    i = jnp.floor(h6)
    f = h6 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(jnp.int32) % 6
    r_out = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4], [v, q, p, p, t], v)
    g_out = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4], [t, v, v, q, p], p)
    b_out = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4], [p, p, t, v, v], q)
    return jnp.clip(jnp.stack([r_out, g_out, b_out], axis=-1), 0.0, 1.0)


def color_jitter(
    rng: jax.Array,
    images: jax.Array,
    brightness: float = 0.4,
    contrast: float = 0.4,
    saturation: float = 0.4,
    hue: float = 0.0,
    apply_prob: float = 1.0,
) -> jax.Array:
    """torchvision ColorJitter(b, c, s, h) wrapped in RandomApply(p).

    Factors ~ U[max(0,1-x), 1+x] per image; hue ~ U[-h, h]. Sub-op order
    is a fresh randperm(4) per *image* (torchvision draws per call, i.e.
    per image), realized as argsort of per-image uniforms. Each of the 4
    slots evaluates all 4 candidate ops on the whole batch and selects
    per image — 16 fused elementwise passes, negligible next to the
    encoder FLOPs, and fully batched (no vmap-of-switch serialization).
    """
    b = images.shape[0]
    k_order, k_apply, kb, kc, ks, kh = jax.random.split(rng, 6)
    fb = jax.random.uniform(kb, (b, 1, 1, 1), minval=max(0.0, 1 - brightness), maxval=1 + brightness)
    fc = jax.random.uniform(kc, (b, 1, 1, 1), minval=max(0.0, 1 - contrast), maxval=1 + contrast)
    fs = jax.random.uniform(ks, (b, 1, 1, 1), minval=max(0.0, 1 - saturation), maxval=1 + saturation)
    fh = jax.random.uniform(kh, (b, 1, 1, 1), minval=-hue, maxval=hue)

    # (B, 4) independent per-image permutations of the op indices.
    order = jnp.argsort(jax.random.uniform(k_order, (b, 4)), axis=1)
    out = images
    for slot in range(4):
        idx = order[:, slot][:, None, None, None]
        xb = adjust_brightness(out, fb)
        xc = adjust_contrast(out, fc)
        xs = adjust_saturation(out, fs)
        xh = adjust_hue(out, fh) if hue > 0 else out
        out = jnp.where(idx == 0, xb, jnp.where(idx == 1, xc, jnp.where(idx == 2, xs, xh)))
    if apply_prob < 1.0:
        keep = jax.random.bernoulli(k_apply, apply_prob, (b, 1, 1, 1))
        out = jnp.where(keep, out, images)
    return out


def random_grayscale(rng: jax.Array, images: jax.Array, prob: float = 0.2) -> jax.Array:
    b = images.shape[0]
    gray = jnp.broadcast_to(_rgb_to_gray(images), images.shape)
    take = jax.random.bernoulli(rng, prob, (b, 1, 1, 1))
    return jnp.where(take, gray, images)


# ---------------------------------------------------------------- blur


def _gaussian_kernels(sigma: jax.Array, taps: int) -> jax.Array:
    """(B, taps) normalized 1-D Gaussian kernels for per-example sigma."""
    x = jnp.arange(taps, dtype=jnp.float32) - (taps - 1) / 2.0
    k = jnp.exp(-0.5 * (x[None, :] / sigma[:, None]) ** 2)
    return k / jnp.sum(k, axis=1, keepdims=True)


def gaussian_blur(
    rng: jax.Array,
    images: jax.Array,
    sigma_range: tuple[float, float] = (0.1, 2.0),
    apply_prob: float = 0.5,
    taps: int = 23,
) -> jax.Array:
    """RandomApply(GaussianBlur(sigma∈U[range]), p) — SimCLR/MoCo-v2 blur
    (`moco/loader.py:~L23-35`), as a separable depthwise conv."""
    b, h, w, c = images.shape
    k_sigma, k_apply = jax.random.split(rng)
    sigma = jax.random.uniform(k_sigma, (b,), minval=sigma_range[0], maxval=sigma_range[1])
    kernels = _gaussian_kernels(sigma, taps)  # (B, taps)

    def blur_one(img, k1d):  # img (H, W, C)
        pad = taps // 2
        # Edge-replicate padding, as PIL's blur extends border pixels
        # (zero-padding would darken edges).
        x = jnp.pad(img, ((pad, pad), (pad, pad), (0, 0)), mode="edge")
        x = x.transpose(2, 0, 1)[:, None]  # (C, 1, H+2p, W+2p)
        kv = k1d.reshape(1, 1, taps, 1)
        kh = k1d.reshape(1, 1, 1, taps)
        x = lax.conv_general_dilated(x, kv, (1, 1), [(0, 0), (0, 0)])
        x = lax.conv_general_dilated(x, kh, (1, 1), [(0, 0), (0, 0)])
        return x[:, 0].transpose(1, 2, 0)

    blurred = jax.vmap(blur_one)(images, kernels)
    keep = jax.random.bernoulli(k_apply, apply_prob, (b, 1, 1, 1))
    return jnp.where(keep, blurred, images)


# ------------------------------------------------------------- flip/norm


def random_horizontal_flip(rng: jax.Array, images: jax.Array, prob: float = 0.5) -> jax.Array:
    b = images.shape[0]
    flip = jax.random.bernoulli(rng, prob, (b, 1, 1, 1))
    return jnp.where(flip, images[:, :, ::-1, :], images)


def normalize(images: jax.Array, mean=IMAGENET_MEAN, std=IMAGENET_STD) -> jax.Array:
    mean = jnp.asarray(mean, images.dtype)
    std = jnp.asarray(std, images.dtype)
    return (images - mean) / std


# -------------------------------------------------------------- recipes


class AugRecipe(NamedTuple):
    """A composed augmentation: fn(rng, images_in_01) -> normalized views."""

    name: str
    crop: bool  # random-resized-crop from the (larger) input
    jitter: tuple[float, float, float, float]
    jitter_prob: float
    grayscale_prob: float
    blur_prob: float
    crop_scale: tuple[float, float] = (0.2, 1.0)
    mean: tuple = IMAGENET_MEAN
    std: tuple = IMAGENET_STD


V1_RECIPE = AugRecipe("v1", True, (0.4, 0.4, 0.4, 0.4), 1.0, 0.2, 0.0)
V2_RECIPE = AugRecipe("v2", True, (0.4, 0.4, 0.4, 0.1), 0.8, 0.2, 0.5)
# Linear-probe training transform (`main_lincls.py` train pipeline):
# RandomResizedCrop (default scale 0.08-1.0) + flip + normalize only.
PROBE_RECIPE = AugRecipe("probe", True, (0.0, 0.0, 0.0, 0.0), 0.0, 0.0, 0.0, (0.08, 1.0))
# Geometric-only two-crop recipe (RRC + flip + normalize, pretrain crop
# scale): the BN-leak positive control's setting, where photometric
# jitter would swamp the weak global tint that carries BOTH the honest
# and the cheat channel (LeakControlSyntheticDataset).
CROPS_ONLY_RECIPE = AugRecipe("probe", True, (0.0, 0.0, 0.0, 0.0), 0.0, 0.0, 0.0, (0.2, 1.0))


def apply_recipe(
    recipe: AugRecipe, rng: jax.Array, images: jax.Array, out_size: int
) -> jax.Array:
    """One view. `images` float [0,1] NHWC, any (H, W) ≥ out_size."""
    k_crop, k_jit, k_gray, k_blur, k_flip = jax.random.split(rng, 5)
    x = images
    if recipe.crop:
        x = random_resized_crop(k_crop, x, out_size, scale=recipe.crop_scale)
    if recipe.name == "v1":
        # v1 order: crop, grayscale, jitter, flip (main_moco.py:~L245-255)
        x = random_grayscale(k_gray, x, recipe.grayscale_prob)
        x = color_jitter(k_jit, x, *recipe.jitter, apply_prob=recipe.jitter_prob)
    elif recipe.name == "probe":
        pass  # crop + flip + normalize only
    else:
        # v2 order: crop, jitter(p=0.8), grayscale, blur, flip (~L228-240)
        x = color_jitter(k_jit, x, *recipe.jitter, apply_prob=recipe.jitter_prob)
        x = random_grayscale(k_gray, x, recipe.grayscale_prob)
        if recipe.blur_prob > 0:
            x = gaussian_blur(k_blur, x, apply_prob=recipe.blur_prob)
    x = random_horizontal_flip(k_flip, x)
    return normalize(x, recipe.mean, recipe.std)


def two_crop_augment(
    recipe: AugRecipe, rng: jax.Array, images: jax.Array, out_size: int
) -> dict[str, jax.Array]:
    """TwoCropsTransform (`moco/loader.py:~L10-20`): the same recipe applied
    twice with independent randomness → query and key views."""
    k_q, k_k = jax.random.split(rng)
    return {
        "im_q": apply_recipe(recipe, k_q, images, out_size),
        "im_k": apply_recipe(recipe, k_k, images, out_size),
    }


def get_recipe(aug_plus: bool, image_size: int, crops_only: bool = False) -> AugRecipe:
    """Recipe lookup; CIFAR-sized inputs skip blur (23-tap blur on 32px is
    degenerate) and use CIFAR normalization stats."""
    base = CROPS_ONLY_RECIPE if crops_only else (V2_RECIPE if aug_plus else V1_RECIPE)
    if image_size <= 64:
        return base._replace(
            blur_prob=0.0,
            mean=(0.4914, 0.4822, 0.4465),
            std=(0.2470, 0.2435, 0.2616),
        )
    return base
