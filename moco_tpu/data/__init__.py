from moco_tpu.data.augment import (
    AugRecipe,
    V1_RECIPE,
    V2_RECIPE,
    apply_recipe,
    center_crop,
    color_jitter,
    gaussian_blur,
    get_recipe,
    normalize,
    random_grayscale,
    random_horizontal_flip,
    random_resized_crop,
    two_crop_augment,
)
from moco_tpu.data.datasets import (
    Cifar10Dataset,
    ImageFolderDataset,
    SyntheticDataset,
    build_dataset,
)
from moco_tpu.data.device_prefetch import DevicePrefetchRing
from moco_tpu.data.pipeline import EvalPipeline, TwoCropPipeline

__all__ = [
    "AugRecipe",
    "V1_RECIPE",
    "V2_RECIPE",
    "apply_recipe",
    "center_crop",
    "color_jitter",
    "gaussian_blur",
    "get_recipe",
    "normalize",
    "random_grayscale",
    "random_horizontal_flip",
    "random_resized_crop",
    "two_crop_augment",
    "Cifar10Dataset",
    "ImageFolderDataset",
    "SyntheticDataset",
    "build_dataset",
    "DevicePrefetchRing",
    "EvalPipeline",
    "TwoCropPipeline",
]
