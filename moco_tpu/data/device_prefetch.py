"""Device prefetch ring — overlap the host→device wire with compute.

PROFILE.md's round-5 ledger: the device sustains ~1940 imgs/s/chip and
the host pipeline alone feeds 3474–6816 imgs/s in canvas mode, yet the
with-data rate was 288 imgs/s — because decode, transfer, and compute
ran *serially* on one producer thread. The reference MoCo recipe hides
the wire behind 32 DataLoader workers + pinned-memory async H2D per GPU
(`main_moco.py` DataLoader(pin_memory=True)); this module is the JAX
rebuild of that overlap:

- the host pipeline's `_prefetch` thread decodes batch *k+2*;
- this ring's dedicated transfer thread issues the sharded
  `jax.device_put` (uint8 on the wire — 4x fewer bytes than fp32;
  normalize/cast happen on device inside the jitted augment) for batch
  *k+1* into the next staging slot;
- the train loop dispatches step *k* against an already device-resident
  batch.

The "ring" is the bounded output queue: at most `depth` transferred
batches are alive at once, so the staging slots rotate — a new transfer
only starts once the consumer has taken a slot, and (optionally) the
consumed slot's uint8 buffer is *donated* to the augment step so XLA
reuses its memory for the normalized output instead of allocating a
fresh batch-sized buffer.

Observability contract (wired end-to-end, see ISSUE 5): every transfer
runs under a `transfer` span on the ring thread's trace track, the ring
keeps per-batch `t_transfer`/`transfer_bytes` plus a live-depth gauge
(`stats_payload()` feeds the driver's metrics lines and the fleet
straggler vector), and the wire registers an `input.h2d` entry in the
comms ledger so obs_report's byte table shows H2D next to the ICI
collectives.

Shutdown: `close()` is safe from the consumer side at any point —
mid-epoch abandonment (preemption, a step-loop exception) must not leak
the transfer thread or the upstream producer (see `_prefetch`'s
poison-pill close, which this propagates to).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional

from moco_tpu.obs.trace import counter as obs_counter, span as obs_span
from moco_tpu.utils import faults

from moco_tpu.analysis import tsan

# fault-injection site for the wire (`delay@site=input.h2d:seconds=S`):
# the overlap tests and `scripts/overlap_smoke.py` slow the transfer
# stage deterministically through this hook
H2D_SITE = "input.h2d"

_END = object()
_CLOSED = object()


def _responsive_put(q: queue.Queue, stop: threading.Event, item) -> bool:
    """Bounded put that stays responsive to a stop flag; False = stopped."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _ring_loop(
    host_iter: Iterator,
    transfer: Callable,
    q: queue.Queue,
    stop: threading.Event,
) -> None:
    """Transfer-thread body. MODULE-LEVEL on purpose: the thread must
    not reference the ring OBJECT, so an abandoned ring can be GC'd
    (`__del__` flips the stop flag) instead of living forever."""
    seq = 0
    try:
        for item in host_iter:
            if stop.is_set():
                return
            t0 = time.perf_counter()
            with obs_span("transfer", seq=seq):
                faults.maybe_delay(H2D_SITE)
                batch, nbytes = transfer(item)
            seconds = time.perf_counter() - t0
            if not _responsive_put(q, stop, (batch, seconds, nbytes)):
                return
            seq += 1
        _responsive_put(q, stop, _END)
    except BaseException as e:  # surface transfer errors to the consumer
        _responsive_put(q, stop, e)


class TransferStats:
    """Thread-safe per-batch + cumulative transfer accounting."""

    def __init__(self):
        # tsan factory (analysis/tsan.py): traced under --sanitize-threads
        self._lock = tsan.make_lock("data.transfer_stats")
        self.t_transfer: Optional[float] = None  # seconds, last batch
        self.transfer_bytes: Optional[int] = None  # wire bytes, last batch
        self.depth_live: int = 0  # staged batches ready right now
        self.batches: int = 0
        self.total_seconds: float = 0.0
        self.total_bytes: int = 0

    def record(self, seconds: float, nbytes: int, depth_live: int) -> None:
        with self._lock:
            self.t_transfer = seconds
            self.transfer_bytes = int(nbytes)
            self.depth_live = int(depth_live)
            self.batches += 1
            self.total_seconds += seconds
            self.total_bytes += int(nbytes)

    def set_depth(self, depth_live: int) -> None:
        with self._lock:
            self.depth_live = int(depth_live)

    def payload(self) -> dict:
        """Metrics-line fields (schema: t_transfer/transfer_bytes/
        prefetch_depth_live) — empty before the first transfer so sync
        runs keep clean lines."""
        with self._lock:
            if self.batches == 0:
                return {}
            return {
                "t_transfer": self.t_transfer,
                "transfer_bytes": self.transfer_bytes,
                "prefetch_depth_live": self.depth_live,
            }

    def wire_rate_bytes_per_sec(self) -> Optional[float]:
        """Cumulative wire bandwidth (the `wire-rate` leg of bench.py's
        overlap_efficiency denominator)."""
        with self._lock:
            if self.total_seconds <= 0:
                return None
            return self.total_bytes / self.total_seconds


class DevicePrefetchRing:
    """Depth-N transfer ring between a host-batch iterator and the step
    loop (module docstring). Iterate it like the sync pipeline iterator;
    `stats_payload()` exposes the per-line wire metrics; `close()` shuts
    the transfer thread and the upstream producer down without leaks.

    `transfer(host_item) -> (device_batch, wire_bytes)` runs on the ring
    thread — it owns the sharded `device_put` + the jitted augment
    dispatch, so the main thread never touches the wire.
    """

    def __init__(
        self,
        host_iter: Iterator,
        transfer: Callable,
        depth: int = 2,
        name: str = "device_prefetch",
    ):
        if depth < 1:
            raise ValueError(f"ring depth must be >= 1, got {depth}")
        self.depth = int(depth)
        self.stats = TransferStats()
        self._host_iter = host_iter
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=_ring_loop, args=(host_iter, transfer, self._q, self._stop),
            daemon=True, name=name,
        )
        self._thread.start()

    # -- consumer side ---------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        if item is _END or item is _CLOSED:
            # re-arm the sentinel: a second next() after exhaustion must
            # also stop, not block on an empty queue
            self._stop.set()
            raise StopIteration
        if isinstance(item, BaseException):
            self._stop.set()
            raise item
        batch, seconds, nbytes = item
        depth_live = self._q.qsize()
        self.stats.record(seconds, nbytes, depth_live=depth_live)
        obs_counter("prefetch_depth_live", depth=depth_live)
        return batch

    def stats_payload(self) -> dict:
        return self.stats.payload()

    def close(self, timeout: float = 5.0) -> None:
        """Consumer-side shutdown: unblock and join the transfer thread,
        then close the upstream host iterator (poison-pill through the
        decode producer). Idempotent; safe mid-epoch."""
        self._stop.set()
        # drain so a put()-blocked transfer thread unblocks immediately
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        upstream_close = getattr(self._host_iter, "close", None)
        if upstream_close is not None:
            upstream_close()
        self._thread.join(timeout=timeout)

    @property
    def closed(self) -> bool:
        return self._stop.is_set()

    def __del__(self):
        # abandoned-ring safety net (no close() call): the transfer
        # thread holds no reference to this object, so GC reaches here —
        # flip the flags and let both threads exit on their next poll
        self._stop.set()
        upstream_close = getattr(self._host_iter, "close", None)
        if upstream_close is not None:
            try:
                # timeout=0: never block inside GC — the pill is posted
                # and the threads unwind on their own
                upstream_close(timeout=0)
            except Exception:
                pass


__all__ = ["DevicePrefetchRing", "TransferStats", "H2D_SITE"]
