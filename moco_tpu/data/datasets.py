"""Host-side dataset sources.

The reference reads `torchvision.datasets.ImageFolder` through a
32-worker `DataLoader` (`main_moco.py:~L255-260`, SURVEY.md §3.4). Here a
dataset is just an indexable source of raw images (uint8 HWC) + labels;
decode/resize runs in a thread pool (PIL releases the GIL for JPEG
decode), and all stochastic augmentation happens on-device
(`moco_tpu.data.augment`).

Sources:
- `SyntheticDataset` — deterministic random images; CI / bench / smoke.
- `Cifar10Dataset` — the standard python-pickle batches from a local
  directory (no network in this environment; torchvision's downloader is
  deliberately not reproduced).
- `ImageFolderDataset` — class-per-subdirectory layout, identical
  semantics to torchvision ImageFolder (sorted class names → indices).
"""

from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import numpy as np

from moco_tpu.utils import retry

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".webp")


def draw_rrc_uniforms(
    rng: np.random.Generator, n: int, attempts: int = 10
) -> dict[str, np.ndarray]:
    """The four uniform tables one RandomResizedCrop sample consumes
    (scale, log-ratio, y, x — each (n, attempts)), drawn VECTORIZED from
    a single generator. The pipeline draws one table for the whole
    global batch × crops instead of constructing a fresh seeded
    Generator per (row, crop) — measured at ~0.24 ms per (row, crop) of
    pure seeding/slicing overhead (scripts/profile_input.py), i.e.
    ~120 ms of serial host time per 256-image two-crop batch."""
    return {
        "scale": rng.uniform(size=(n, attempts)),
        "log_ratio": rng.uniform(size=(n, attempts)),
        "y": rng.uniform(size=(n, attempts)),
        "x": rng.uniform(size=(n, attempts)),
    }


def rrc_boxes_from_uniforms(
    u: dict[str, np.ndarray],
    dims: np.ndarray,  # (bs, 2) original (h, w) per image
    scale: tuple[float, float] = (0.2, 1.0),
    ratio: tuple[float, float] = (3.0 / 4.0, 4.0 / 3.0),
) -> np.ndarray:
    """(bs, 4) int32 RandomResizedCrop boxes (y0, x0, ch, cw) in ORIGINAL
    image coordinates from pre-drawn uniforms — torchvision get_params
    semantics (10-attempt rejection + ratio-clamped center-crop
    fallback), vectorized in numpy for the host-crop pipeline
    (`random_resized_crop_params` is the jax twin for the on-device
    path; the parity test covers both)."""
    b = dims.shape[0]
    attempts = u["scale"].shape[1]
    h = np.maximum(dims[:, 0].astype(np.float64), 1.0)
    w = np.maximum(dims[:, 1].astype(np.float64), 1.0)
    area = h * w
    ta = (scale[0] + (scale[1] - scale[0]) * u["scale"]) * area[:, None]
    log_r0, log_r1 = np.log(ratio[0]), np.log(ratio[1])
    ar = np.exp(log_r0 + (log_r1 - log_r0) * u["log_ratio"])
    cw = np.round(np.sqrt(ta * ar))
    ch = np.round(np.sqrt(ta / ar))
    valid = (cw > 0) & (cw <= w[:, None]) & (ch > 0) & (ch <= h[:, None])
    first = np.argmax(valid, axis=1)
    any_valid = valid.any(axis=1)
    rows = np.arange(b)
    cw_s, ch_s = cw[rows, first], ch[rows, first]
    y0 = np.floor(u["y"][rows, first] * (h - ch_s + 1.0))
    x0 = np.floor(u["x"][rows, first] * (w - cw_s + 1.0))

    in_ratio = w / h
    fw = np.where(in_ratio < ratio[0], w, np.where(in_ratio > ratio[1], np.round(h * ratio[1]), w))
    fh = np.where(in_ratio < ratio[0], np.round(w / ratio[0]), h)
    fy = np.floor((h - fh) / 2)
    fx = np.floor((w - fw) / 2)
    ch_s = np.where(any_valid, ch_s, fh)
    cw_s = np.where(any_valid, cw_s, fw)
    y0 = np.where(any_valid, y0, fy)
    x0 = np.where(any_valid, x0, fx)
    return np.stack([y0, x0, ch_s, cw_s], axis=1).astype(np.int32)


def sample_rrc_boxes(
    rng: np.random.Generator,
    dims: np.ndarray,
    scale: tuple[float, float] = (0.2, 1.0),
    ratio: tuple[float, float] = (3.0 / 4.0, 4.0 / 3.0),
    attempts: int = 10,
) -> np.ndarray:
    """Draw + transform in one call (tests and single-shot callers);
    the pipeline uses the split form to amortize the draw over the
    whole batch."""
    return rrc_boxes_from_uniforms(
        draw_rrc_uniforms(rng, dims.shape[0], attempts), dims, scale, ratio
    )


class SyntheticDataset:
    """Fixed-seed random uint8 images; index-deterministic so tests can
    rely on reproducibility without holding the whole set in memory."""

    def __init__(self, num_examples: int = 1024, image_size: int = 224, num_classes: int = 10):
        self.num_examples = num_examples
        self.image_size = image_size
        self.num_classes = num_classes

    def __len__(self) -> int:
        return self.num_examples

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        size = decode_size or self.image_size
        rng = np.random.default_rng(index)
        img = rng.integers(0, 256, (size, size, 3), dtype=np.uint8)
        return img, int(index % self.num_classes)


class LearnableSyntheticDataset:
    """Deterministic synthetic dataset with real class structure — the
    learning-signal stand-in for ImageNet in this no-dataset environment
    (the reference's de-facto test is metric reproduction on ImageNet,
    SURVEY.md §4; this gives the same end-to-end signal at CI scale).

    Each class c is a fixed low-frequency color field (seeded by c);
    an instance adds a seeded affine warp of the template (shift +
    scale), its own high-frequency texture, and pixel noise. Same-class
    images are therefore similar but not identical, and two random crops
    of one image share instance + class structure — exactly the setting
    in which contrastive pretraining produces kNN/probe accuracy far
    above chance while raw-pixel kNN stays weak.
    """

    def __init__(
        self,
        num_examples: int = 2048,
        image_size: int = 32,
        num_classes: int = 8,
        train: bool = True,
        noise: float = 0.15,
    ):
        self.num_examples = num_examples
        self.image_size = image_size
        self.num_classes = num_classes
        self.noise = noise
        # train/test draw disjoint instance seeds from the same classes
        self._seed_base = 0 if train else 1_000_003
        # class templates: smooth random RGB fields, upsampled 4x4 -> full
        self._templates = []
        for c in range(num_classes):
            rng = np.random.default_rng(77_000 + c)
            coarse = rng.uniform(0.15, 0.85, (4, 4, 3))
            self._templates.append(_bilinear_upsample(coarse, image_size))

    def __len__(self) -> int:
        return self.num_examples

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        size = decode_size or self.image_size
        label = int(index % self.num_classes)
        rng = np.random.default_rng(self._seed_base + index)
        t = self._templates[label]
        # instance-specific roll (toroidal shift) + brightness/contrast
        dy, dx = rng.integers(0, self.image_size, 2)
        img = np.roll(np.roll(t, dy, axis=0), dx, axis=1)
        img = img * rng.uniform(0.8, 1.2) + rng.uniform(-0.1, 0.1)
        # instance texture: a smooth field unique to this example
        coarse = rng.uniform(-1.0, 1.0, (8, 8, 3))
        img = img + 0.25 * _bilinear_upsample(coarse, self.image_size)
        img = img + rng.normal(0.0, self.noise, img.shape)
        img = np.clip(img, 0.0, 1.0)
        if size != self.image_size:
            img = _bilinear_upsample(img, size)
        return (img * 255).astype(np.uint8), label


class HardSyntheticDataset:
    """Harder learning-signal task (VERDICT r2 next-round #7): ≥32
    classes, raw-pixel kNN at chance, large pretrain headroom.

    Class identity is a *power spectrum*: each class c owns a smooth
    spectral mask (a few Gaussian lobes in log-frequency × orientation
    space, seeded by c), and an instance is white noise filtered by
    that mask — a Gaussian random field with class-specific texture
    statistics. Every frequency bin carries an independent random
    phase, so two same-class instances are pixel-decorrelated in
    hundreds of independent dimensions (no phase-matched twin exists
    in any reasonably-sized bank) and raw-pixel kNN sits at chance.
    The class signature survives exactly the transforms two-crop
    training is invariant to — cropping, rescaling, color jitter all
    preserve the orientation/band structure of the texture — so the
    crop-invariant content IS the label (the reference's QA is metric
    reproduction on ImageNet, SURVEY.md §4; this gives the same
    end-to-end evidence with an honest margin over the pixel
    baseline, unlike the 8-class `LearnableSyntheticDataset` where
    pixel kNN reaches ~73%).

    `tests/test_data.py` validates both halves: pixel-kNN ≈ chance
    and an FFT-magnitude oracle (phase-invariant spectral features)
    far above chance, i.e. the task is unsolvable from pixels but
    solvable from exactly the invariances two-crop training rewards.
    """

    def __init__(
        self,
        num_examples: int = 16384,
        image_size: int = 32,
        num_classes: int = 32,
        train: bool = True,
        n_lobes: int = 4,
        signal: float = 0.28,
        nuisance: float = 0.40,
        noise: float = 0.04,
    ):
        self.num_examples = num_examples
        self.image_size = image_size
        self.num_classes = num_classes
        self.signal = signal
        self.nuisance = nuisance
        self.noise = noise
        self._seed_base = 0 if train else 9_000_017
        # class spectral masks over the full fft grid (image_size²),
        # built from n_lobes Gaussian bumps in (log radius, orientation);
        # band 2-10 cycles/image: low enough to survive the v2 recipe's
        # blur and the RRC rescale (which shifts apparent frequency by
        # the crop scale, up to ~2.2x), high enough to be texture rather
        # than color. Lobe widths (0.5 in log-radius, 0.8 in angle) are
        # tuned so the mask spans enough independent frequency bins that
        # best-of-bank phase matching fails: measured pixel-kNN 5.5% vs
        # 3.1% chance with narrow lobes leaking 40%+ (the FFT oracle
        # stays at 95%).
        s = image_size
        fy = np.fft.fftfreq(s)[:, None] * s  # cycles/image
        fx = np.fft.fftfreq(s)[None, :] * s
        r = np.hypot(fy, fx)
        logr = np.log(np.maximum(r, 1e-6))
        ang = np.arctan2(fy, fx) % np.pi  # spectrum symmetry: angle mod pi
        self._masks = np.empty((num_classes, s, s))
        for c in range(num_classes):
            rng = np.random.default_rng(55_000 + c)
            mask = np.zeros((s, s))
            for _ in range(n_lobes):
                lr0 = rng.uniform(np.log(2.0), np.log(10.0))
                a0 = rng.uniform(0.0, np.pi)
                d_ang = np.minimum(np.abs(ang - a0), np.pi - np.abs(ang - a0))
                mask += np.exp(
                    -((logr - lr0) ** 2) / (2 * 0.5**2) - d_ang**2 / (2 * 0.8**2)
                )
            mask[r < 1.5] = 0.0  # no DC/near-DC: keep signal out of mean color
            self._masks[c] = mask / np.sqrt((mask**2).mean() + 1e-12)

    def __len__(self) -> int:
        return self.num_examples

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        size = decode_size or self.image_size
        label = int(index % self.num_classes)
        rng = np.random.default_rng(self._seed_base + index)
        s = self.image_size
        mask = self._masks[label]
        # per-channel GRF: filter white noise through the class mask
        white = rng.normal(size=(3, s, s))
        tex = np.fft.ifft2(np.fft.fft2(white, axes=(1, 2)) * mask, axes=(1, 2)).real
        tex = tex / (tex.std(axis=(1, 2), keepdims=True) + 1e-8)
        img = 0.5 + self.signal * tex.transpose(1, 2, 0)
        # instance nuisance: smooth color field dominating pixel distance
        coarse = rng.uniform(-1.0, 1.0, (4, 4, 3))
        img = img + self.nuisance * _bilinear_upsample(coarse, s)
        img = img + rng.normal(0.0, self.noise, img.shape)
        img = np.clip(img, 0.0, 1.0)
        if size != self.image_size:
            img = _bilinear_upsample(img, size)
        return (img * 255).astype(np.uint8), label


class HardTemplateDataset:
    """Second-generation hard learning-signal task (the redesign brief in
    REPORT.md's hard-signal section): class identity is a FIXED texture
    realization, instances are geometric transforms of it.

    `HardSyntheticDataset` (class = power spectrum, instance = fresh
    phases) measured unlearnable at CI budget: per-instance phases are
    themselves a perfect crop-invariant instance signature, so instance
    discrimination never needs class structure. Here the design inverts:
    every instance of class c carries the SAME band-limited texture
    realization T_c, seen under a random rotation + scale + toroidal
    shift. Shared class structure (the template) is now the cheapest
    crop-invariant signal — the regime where instance discrimination
    provably transfers (the 8-class template task) — while pixel kNN
    dies geometrically: the (rotation × scale × shift) transform space
    is far too large for any bank to contain a near-aligned same-class
    neighbor (`tests/test_data.py` pins pixel-kNN near chance).

    STATUS (measured, REPORT.md hard-signal section): pixel-kNN at
    chance as designed, but the 12-epoch CI-budget training gate FAILED
    (kNN flat ~4%): a CNN solves instance discrimination with
    rotation-SPECIFIC template features that do not cluster across a
    class's rotations. Kept as the documented experiment; not
    registered as a supported dataset. The lesson feeds the next
    design: the class-shared signal must be invariant under transforms
    conv features natively tolerate (translation/scale/appearance
    noise), not rotation.
    """

    def __init__(
        self,
        num_examples: int = 16384,
        image_size: int = 32,
        num_classes: int = 32,
        train: bool = True,
        signal: float = 0.30,
        nuisance: float = 0.25,
        noise: float = 0.04,
        scale_range: tuple[float, float] = (0.75, 1.35),
    ):
        self.num_examples = num_examples
        self.image_size = image_size
        self.num_classes = num_classes
        self.signal = signal
        self.nuisance = nuisance
        self.noise = noise
        self.scale_range = scale_range
        self._seed_base = 0 if train else 9_000_017
        # class templates: band-limited GRF realizations on a 2x-size
        # torus (band chosen so a 1x window sees ~2-8 cycles; the torus
        # wraps, so any rotated/scaled window samples valid texture)
        t = 2 * image_size
        fy = np.fft.fftfreq(t)[:, None] * t
        fx = np.fft.fftfreq(t)[None, :] * t
        r = np.hypot(fy, fx)
        # 4-16 cycles per 2x torus = 2-8 per 1x window
        band = ((r >= 4.0) & (r <= 16.0)).astype(np.float64)
        self._templates = np.empty((num_classes, t, t, 3))
        for c in range(num_classes):
            rng = np.random.default_rng(77_700 + c)
            white = rng.normal(size=(3, t, t))
            tex = np.fft.ifft2(np.fft.fft2(white, axes=(1, 2)) * band, axes=(1, 2)).real
            tex /= tex.std(axis=(1, 2), keepdims=True) + 1e-8
            self._templates[c] = tex.transpose(1, 2, 0)

    def __len__(self) -> int:
        return self.num_examples

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        size = decode_size or self.image_size
        label = int(index % self.num_classes)
        rng = np.random.default_rng(self._seed_base + index)
        t = self._templates[label]
        ts = t.shape[0]
        s = self.image_size
        theta = rng.uniform(0.0, 2 * np.pi)
        zoom = rng.uniform(*self.scale_range)
        dy, dx = rng.uniform(0.0, ts, 2)
        # inverse-map the s x s window through rotate/scale/shift on the torus
        yy, xx = np.mgrid[0:s, 0:s].astype(np.float64)
        ct, st = np.cos(theta), np.sin(theta)
        sy = (ct * yy - st * xx) / zoom + dy
        sx = (st * yy + ct * xx) / zoom + dx
        y0 = np.floor(sy).astype(int)
        x0 = np.floor(sx).astype(int)
        wy = (sy - y0)[..., None]
        wx = (sx - x0)[..., None]
        y0 %= ts; x0 %= ts
        y1 = (y0 + 1) % ts
        x1 = (x0 + 1) % ts
        tex = (
            t[y0, x0] * (1 - wy) * (1 - wx)
            + t[y0, x1] * (1 - wy) * wx
            + t[y1, x0] * wy * (1 - wx)
            + t[y1, x1] * wy * wx
        )
        img = 0.5 + self.signal * tex
        coarse = rng.uniform(-1.0, 1.0, (4, 4, 3))
        img = img + self.nuisance * _bilinear_upsample(coarse, s)
        img = img + rng.normal(0.0, self.noise, img.shape)
        img = np.clip(img, 0.0, 1.0)
        if size != s:
            img = _bilinear_upsample(img, size)
        return (img * 255).astype(np.uint8), label


def _bilinear_upsample(field: np.ndarray, size: int) -> np.ndarray:
    """(h, w, c) float -> (size, size, c) bilinear (numpy, no deps)."""
    h, w, _ = field.shape
    ys = np.linspace(0, h - 1, size)
    xs = np.linspace(0, w - 1, size)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    a = field[y0][:, x0] * (1 - wy) * (1 - wx)
    b = field[y0][:, x1] * (1 - wy) * wx
    c = field[y1][:, x0] * wy * (1 - wx)
    d = field[y1][:, x1] * wy * wx
    return a + b + c + d


class LeakControlSyntheticDataset:
    """BN-cheat POSITIVE CONTROL (VERDICT r3 missing #3): a task built so
    the batch-statistics shortcut Shuffle-BN prevents
    (`moco/builder.py:~L79-126`) is the DOMINANT gradient.

    Why the leak never developed on the other synthetic tasks: their
    two crops share strong pixel content, so the honest channel is far
    cheaper than reading co-batch statistics. This dataset inverts the
    balance. Every image is iid uniform noise (two non-identical crops
    of noise are content-decorrelated — resampling destroys pixel
    alignment) carrying only a weak GLOBAL color tint:

        img = noise + class_tint[label] + instance_tint[index]

    The tint is the only crop-invariant signal. Per crop it is weak
    (amplitude ~ the crop's noise-mean fluctuation), so the honest path
    — estimate the tint from one crop, match it across views — is slow.
    But BatchNorm *injects* each BN group's mean into every activation
    it normalizes: with tiny groups (2 rows/device), the injected
    co-batch fingerprint (tint_a + tint_b)/2 has several times the
    per-crop SNR and is shared between the query group and the aligned
    key group by construction. Training with shuffle='none' therefore
    has a high-SNR shortcut that solves the (K+1)-way task without
    learning content; gather_perm/a2a decorrelate the key groups and
    leave only the honest channel. Run with crops-only augmentation —
    photometric jitter (±0.4 brightness) would swamp a 0.03-0.05 tint
    through BOTH channels and mask the phenomenon.

    The class component of the tint survives to held-out instances, so
    class-kNN measures honest learning; the instance component makes
    group fingerprints near-unique (queue keys from other compositions
    rarely collide, keeping the cheat's ceiling high).
    """

    def __init__(
        self,
        num_examples: int = 512,
        image_size: int = 32,
        num_classes: int = 8,
        train: bool = True,
        class_tint: float = 0.03,
        instance_tint: float = 0.05,
    ):
        self.num_examples = num_examples
        self.image_size = image_size
        self.num_classes = num_classes
        self.class_tint = class_tint
        self.instance_tint = instance_tint
        self._seed_base = 0 if train else 9_000_017
        tints = []
        for c in range(num_classes):
            v = np.random.default_rng(551_000 + c).normal(size=3)
            tints.append(v / np.linalg.norm(v) * class_tint)
        self._class_tints = np.asarray(tints)

    def __len__(self) -> int:
        return self.num_examples

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        size = decode_size or self.image_size
        label = int(index % self.num_classes)
        rng = np.random.default_rng(self._seed_base + index)
        inst = rng.normal(size=3)
        inst = inst / np.linalg.norm(inst) * self.instance_tint
        img = rng.uniform(0.0, 1.0, (size, size, 3))
        img = img + self._class_tints[label] + inst
        img = np.clip(img, 0.0, 1.0)
        return (img * 255).astype(np.uint8), label


class Cifar10Dataset:
    """CIFAR-10 from the standard `cifar-10-batches-py` pickle files."""

    def __init__(self, data_dir: str, train: bool = True):
        batch_dir = data_dir
        if os.path.isdir(os.path.join(data_dir, "cifar-10-batches-py")):
            batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
        names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
        images, labels = [], []
        for name in names:
            path = os.path.join(batch_dir, name)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"{path} not found — provide the standard cifar-10-batches-py "
                    "directory (no network access to download it)"
                )

            def _read(p=path):
                with open(p, "rb") as f:
                    return pickle.load(f, encoding="bytes")

            d = retry.retry_call(_read, site="data.cifar10")
            images.append(d[b"data"])
            labels.extend(d[b"labels"])
        data = np.concatenate(images).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        self.images = np.ascontiguousarray(data)  # uint8 NHWC
        self.labels = np.asarray(labels, np.int32)
        self.num_classes = 10

    def __len__(self) -> int:
        return len(self.images)

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        return self.images[index], int(self.labels[index])


class ImageFolderDataset:
    """`root/class_x/img.jpg` layout; classes sorted alphabetically, as
    torchvision ImageFolder assigns indices."""

    def __init__(self, root: str, decode_size: int = 256):
        self.root = root
        self.decode_size = decode_size
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise ValueError(f"no class subdirectories under {root}")
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.num_classes = len(classes)
        # Cumulative zero-filled crop slots (undecodable images) —
        # surfaced by the pipeline as the `decode_failures` metric so
        # corrupt data is visible instead of silently training on black.
        self.decode_failures = 0
        self.samples: list[tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(IMG_EXTENSIONS):
                    self.samples.append((os.path.join(cdir, fname), self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no images under {root}")

    def __len__(self) -> int:
        return len(self.samples)

    def load(self, index: int, decode_size: Optional[int] = None) -> tuple[np.ndarray, int]:
        from PIL import Image

        path, label = self.samples[index]
        size = decode_size or self.decode_size

        def _decode():
            with Image.open(path) as im:
                im = im.convert("RGB")
                # Shortest-side resize to `size` on the host; used by the
                # eval center-crop path and as the canvas for on-device RRC
                # when host_rrc is off. (Training normally uses the
                # host-crop protocol below, which samples crops against the
                # ORIGINAL geometry — no canvas clipping.)
                w, h = im.size
                s = size / min(w, h)
                # explicit BILINEAR: the reference's torchvision transforms
                # default, and what native/loader.cc reproduces (antialiased)
                im = im.resize(
                    (max(size, round(w * s)), max(size, round(h * s))),
                    resample=Image.BILINEAR,
                )
                return np.asarray(im, np.uint8)

        # transient filesystem errors retry; a truly bad file raises
        arr = retry.retry_call(_decode, site="data.imagefolder")
        # Center-crop the long side to a square canvas of fixed shape so
        # batches stack.
        h, w, _ = arr.shape
        y0, x0 = (h - size) // 2, (w - size) // 2
        return arr[y0 : y0 + size, x0 : x0 + size], label

    # -- host-crop protocol (same surface as NativeImageFolderDataset):
    # the pipeline samples RandomResizedCrop boxes against the ORIGINAL
    # image geometry and the dataset decodes once + crops N times, so the
    # crop distribution matches torchvision exactly (no fixed-canvas
    # clipping — VERDICT r1 weak-item 6). ------------------------------
    def dims(self, indices) -> np.ndarray:
        from PIL import Image

        if not hasattr(self, "_dims_cache"):
            self._dims_cache: dict[int, tuple[int, int]] = {}
        out = np.zeros((len(indices), 2), np.int32)
        for row, i in enumerate(np.asarray(indices, np.int64)):
            i = int(i)
            hw = self._dims_cache.get(i)
            if hw is None:
                try:
                    with Image.open(self.samples[i][0]) as im:  # header-only
                        w, h = im.size
                    hw = (h, w)
                except Exception:
                    hw = (0, 0)
                self._dims_cache[i] = hw
            out[row] = hw
        return out

    def load_crop_batch(
        self, indices, boxes: np.ndarray, out_size: int, pool=None
    ) -> tuple[np.ndarray, np.ndarray]:
        """(bs, n_crops, out, out, 3) uint8 + labels; PIL resized-crop.
        `pool` is the caller's ThreadPoolExecutor (the pipeline passes its
        config.num_workers-sized pool); a small default is created only
        for direct/test use."""
        from PIL import Image

        idx = np.asarray(indices, np.int64)
        boxes = np.asarray(boxes, np.int64)
        bs, n_crops = boxes.shape[0], boxes.shape[1]
        out = np.zeros((bs, n_crops, out_size, out_size, 3), np.uint8)
        labels = np.empty(bs, np.int32)

        def one(row):
            # returns the failure count for this row instead of bumping
            # self.decode_failures from 8 pool threads at once — `+=` is
            # a read-modify-write, and concurrent workers lose updates
            # (JX012); the caller aggregates single-threaded below
            i = int(idx[row])
            path, label = self.samples[i]
            labels[row] = label
            try:
                with Image.open(path) as im:
                    im = im.convert("RGB")
                    w, h = im.size
                    for c in range(n_crops):
                        y0, x0, ch, cw = boxes[row, c]
                        y0 = int(np.clip(y0, 0, h - 1))
                        x0 = int(np.clip(x0, 0, w - 1))
                        ch = int(np.clip(ch, 1, h - y0))
                        cw = int(np.clip(cw, 1, w - x0))
                        crop = im.crop((x0, y0, x0 + cw, y0 + ch)).resize(
                            (out_size, out_size), resample=Image.BILINEAR
                        )
                        out[row, c] = np.asarray(crop, np.uint8)
            except Exception:
                return 1  # slot stays zero, but COUNTED (by the caller)
            return 0

        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            if not hasattr(self, "_crop_pool"):
                self._crop_pool = ThreadPoolExecutor(max_workers=8)
            pool = self._crop_pool
        self.decode_failures += sum(pool.map(one, range(bs)))
        return out, labels


def build_dataset(
    name: str,
    data_dir: Optional[str],
    image_size: int,
    train: bool = True,
    num_workers: int = 8,
    cache_dir: Optional[str] = None,
):
    if name == "synthetic":
        return SyntheticDataset(image_size=max(image_size, 32))
    if name == "synthetic_learnable":
        return LearnableSyntheticDataset(image_size=max(image_size, 32), train=train)
    if name == "synthetic_hard":
        return HardSyntheticDataset(
            num_examples=16384 if train else 2048,
            image_size=max(image_size, 32),
            train=train,
        )
    if name == "synthetic_learnable32":
        # the round-3 hard-task redesign's surviving candidate (REPORT.md
        # hard-signal lesson v2): the PROVEN template design — class
        # structure as the cheapest crop-invariant signal, inside the
        # transform group conv features tolerate — at 32 classes with
        # heavy per-instance noise (pixel-kNN ~7% vs 3.1% chance). The
        # budget-binding claim is tested by running THIS task at the
        # headline chain's budget.
        return LearnableSyntheticDataset(
            image_size=max(image_size, 32), train=train,
            num_classes=32, noise=0.5,
        )
    if name == "synthetic_leak_control":
        return LeakControlSyntheticDataset(image_size=max(image_size, 32), train=train)
    if name == "cifar10":
        if data_dir is None:
            raise ValueError("cifar10 needs data_dir")
        return Cifar10Dataset(data_dir, train=train)
    if name == "imagefolder":
        if data_dir is None:
            raise ValueError("imagefolder needs data_dir")
        split = "train" if train else "val"
        root = data_dir
        if os.path.isdir(os.path.join(data_dir, split)):
            root = os.path.join(data_dir, split)
        # decode canvas ~1.146x the crop (256 for 224-crops, the standard ratio)
        decode_size = round(image_size * 256 / 224)
        if cache_dir:
            # decode-once packed RGB cache: built from the plain folder
            # listing, then all epoch reads come from the mmap. Reuse
            # re-lists the source to verify the stamped fingerprint (a
            # drifted listing raises; a since-REMOVED data_dir is
            # tolerated — the cache is self-contained).
            from moco_tpu.data.cache import PackedRGBCacheDataset, build_rgb_cache

            # key the cache subdir by the RESOLVED root: a flat data_dir
            # (no train/ val/ subdirs) serves both splits from one cache
            # ("all") instead of building two identical copies. Existing
            # caches win over the naming rule: a legacy flat-layout cache
            # under train/ (or val/) is reused rather than re-decoded, and
            # when the source directory is GONE the split detection above
            # degrades (isdir false -> root==data_dir) — the surviving
            # stamped cache from the original layout is still found.
            from moco_tpu.data.cache import _read_stamp

            flat = root == data_dir
            req = "train" if train else "val"
            primary = "all" if flat else req
            # Pass 1 — exact stamp-root match. Flat layout: both splits
            # are the same data, so ANY matching stamped subdir serves
            # (legacy caches included). Split layout: only this split's
            # subdir or "all" may serve — the other split is different
            # data (the root check enforces that).
            candidates = ["all", "train", "val"] if flat else [primary, "all"]
            split = None
            for cand in dict.fromkeys(candidates):
                stamp = _read_stamp(os.path.join(cache_dir, cand))
                if stamp and stamp.get("root") in (None, os.path.realpath(root)):
                    split = cand
                    break
            if split is None and not os.path.isdir(root):
                # Pass 2 — the source is gone, so no stamp can match and
                # the layout is undetectable. Prefer the REQUESTED
                # split's cache (a gone split-layout val request must not
                # silently get the train cache), then "all", then the
                # other split as a last resort. Loud either way: this is
                # indistinguishable from a typo'd --data-dir.
                other = "val" if req == "train" else "train"
                for cand in dict.fromkeys([req, "all", other]):
                    stamp = _read_stamp(os.path.join(cache_dir, cand))
                    if stamp:
                        import warnings

                        warnings.warn(
                            f"data_dir {root!r} does not exist; serving RGB cache "
                            f"{cand!r} built from {stamp.get('root')!r} — if this "
                            "is a mistyped --data-dir, fix it"
                        )
                        split = cand
                        break
            split_cache = os.path.join(cache_dir, split or primary)
            build_rgb_cache(
                lambda: ImageFolderDataset(root, decode_size=decode_size),
                split_cache,
                num_workers=num_workers,
                canvas_size=decode_size,
                root=root,
            )
            return PackedRGBCacheDataset(
                split_cache, decode_size=decode_size, num_workers=num_workers
            )
        from moco_tpu.data.native_loader import native_available

        if native_available():  # C++ decode pool (native/loader.cc)
            from moco_tpu.data.native_loader import NativeImageFolderDataset

            return NativeImageFolderDataset(
                root, decode_size=decode_size, threads=max(num_workers, 1)
            )
        return ImageFolderDataset(root, decode_size=decode_size)
    raise ValueError(f"unknown dataset {name!r}")
