"""Input pipeline: host decode → device transfer ring → device augment.

Replaces the reference's `DataLoader(workers=32)` + `TwoCropTransform`
(`main_moco.py:~L255-260`, `moco/loader.py`). Split of labor:

- host: index shuffling (per-epoch, seeded — the
  `DistributedSampler.set_epoch` equivalent); for datasets exposing the
  host-crop protocol (ImageFolder), torchvision-exact RandomResizedCrop
  boxes sampled against each image's ORIGINAL geometry and executed in
  the loader (decode once, crop/resize N times — native C++ pool when
  built, else PIL threads); otherwise decode to a fixed uint8 canvas;
- wire: uint8 crosses the host→device boundary (4x fewer bytes than
  fp32), sharded over the mesh's data axis;
- device: /255 + the remaining stochastic augmentation (jitter/gray/
  blur/flip/normalize — plus the crop itself on the canvas path),
  batched and jitted (`moco_tpu.data.augment`).

Two epoch modes, bit-identical in output (same seeded order, same step
rngs, same jitted augment):

- `epoch(e)` — the synchronous path: one producer thread runs decode →
  transfer → augment dispatch serially, a depth-2 prefetch queue
  overlaps that whole chain with the train step;
- `epoch(e, device=True)` — the overlapped path
  (`data/device_prefetch.py`): the producer thread decodes batch k+2
  while a dedicated transfer thread stages batch k+1 on device and the
  driver dispatches step k. Decode, wire, and compute pipeline instead
  of taking turns — the round-5 with-data ceiling lever (PROFILE.md).

Training pipelines use drop_last=True semantics (reference DataLoader) —
the queue's `K % global_batch == 0` invariant requires full batches. The
eval pipeline instead pads the tail batch and carries a validity mask so
the whole val split is scored (the reference evaluates the full split
too).

Every epoch iterator exposes `close()`: a consumer that abandons it
mid-epoch (preemption, a step-loop exception) MUST call it — before the
poison-pill close existed, the daemon producer stayed blocked on
`q.put` forever, holding the decode pool (the PR-5 leak fix; the train
driver closes on every epoch exit path).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from moco_tpu.data.augment import (
    AugRecipe,
    PROBE_RECIPE,
    apply_recipe,
    get_recipe,
    two_crop_augment,
)
from moco_tpu.data.datasets import build_dataset
from moco_tpu.data.device_prefetch import DevicePrefetchRing
from moco_tpu.obs import comms
from moco_tpu.obs.trace import span as obs_span
from moco_tpu.parallel.dist import ProcessDataPartition
from moco_tpu.parallel.mesh import batch_sharding
from moco_tpu.utils import faults, retry
from moco_tpu.utils.config import DataConfig

_END = object()
_CLOSED = object()


def _responsive_put(q: queue.Queue, stop: threading.Event, item) -> bool:
    """Bounded put that stays responsive to a stop flag; False = stopped."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _producer_loop(src: Iterator, q: queue.Queue, stop: threading.Event) -> None:
    """Prefetch producer body. A MODULE-LEVEL function on purpose: the
    thread must not hold a reference to the iterator OBJECT, or the
    abandoned-iterator safety net (`__del__` flips the stop flag) could
    never fire — the thread would keep its owner alive forever."""
    try:
        for item in src:
            if not _responsive_put(q, stop, item):
                return
        _responsive_put(q, stop, _END)
    except BaseException as e:  # surface producer errors to the consumer
        _responsive_put(q, stop, e)
    finally:
        close = getattr(src, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass


class _PrefetchIterator:
    """Producer thread + bounded queue, with a poison-pill `close()`.

    The producer keeps `depth` items in flight; errors it raises are
    re-raised at the consumer's `next()`. `close()` is the leak fix: it
    flips the stop flag, drains the queue (so a `put`-blocked producer
    unblocks within one poll interval), enqueues a CLOSED pill (so a
    `get`-blocked consumer on another thread unblocks too), closes the
    source iterator (releasing the decode pool a suspended generator
    would pin), and joins the thread. Idempotent, safe mid-epoch.

    An iterator abandoned WITHOUT close() (a consumer that just drops
    it) still self-cleans: the producer thread does not reference this
    object, so GC runs `__del__`, which flips the stop flag and lets
    the thread exit on its next put poll.
    """

    def __init__(self, it: Iterator, depth: int = 2, name: str = "prefetch"):
        self._src = it
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=_producer_loop, args=(it, self._q, self._stop),
            daemon=True, name=name,
        )
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        if item is _END or item is _CLOSED:
            self._stop.set()  # later next() calls must not block
            raise StopIteration
        if isinstance(item, BaseException):
            self._stop.set()
            raise item
        return item

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        try:
            self._q.put_nowait(_CLOSED)  # unblock a get()-blocked consumer
        except queue.Full:
            pass
        self._thread.join(timeout=timeout)

    def __del__(self):
        self._stop.set()


def _prefetch(it: Iterator, depth: int = 2) -> _PrefetchIterator:
    """Run the producer in a thread, keeping `depth` batches in flight."""
    return _PrefetchIterator(it, depth=depth)


class HostBatch(NamedTuple):
    """One step's host-side product: local uint8 rows, not yet on
    device. `views` is (B_local, n_views, S, S, 3) on the host-crop
    path (n_views precropped images per row) or (B_local, H, W, 3) on
    the canvas path (`precropped=False`)."""

    step: int
    rng: jax.Array
    views: np.ndarray
    labels: Optional[np.ndarray]
    precropped: bool

    @property
    def wire_bytes(self) -> int:
        """uint8 payload this process puts on the wire for this batch."""
        n = int(self.views.nbytes)
        if self.labels is not None:
            n += int(self.labels.nbytes)
        return n


class _HostPipeline:
    """Shared host-side machinery: dataset build, batch/steps accounting,
    decode pool, mesh sharding, seeded per-epoch shuffling."""

    def __init__(
        self,
        config: DataConfig,
        mesh: Mesh,
        seed: int = 0,
        dataset=None,
        train: bool = True,
        drop_last: bool = True,
    ):
        self.config = config
        self.mesh = mesh
        self.seed = seed
        self.dataset = dataset or build_dataset(
            config.dataset,
            config.data_dir,
            config.image_size,
            train=train,
            num_workers=config.num_workers,
            cache_dir=config.cache_dir,
        )
        self.batch_size = config.global_batch
        if drop_last and len(self.dataset) < self.batch_size:
            raise ValueError(
                f"dataset of {len(self.dataset)} examples < global batch {self.batch_size}"
            )
        n = len(self.dataset)
        self.steps_per_epoch = n // self.batch_size if drop_last else -(-n // self.batch_size)
        self._pool = ThreadPoolExecutor(max_workers=max(config.num_workers, 1))
        # the wire sharding: batch rows over the data axis (mesh.py) —
        # the same layout the prefetch ring stages uint8 into
        self._sharding = batch_sharding(mesh)
        # Multi-host input sharding (DistributedSampler equivalent,
        # main_moco.py:~L258): this process decodes only the global-batch
        # rows owned by its addressable devices; single-host it holds all
        # rows, so one code path serves both.
        self._partition = ProcessDataPartition(self._sharding, self.batch_size)

    # -- host stage (decode; numpy out, nothing on device) ---------------

    def _host_batch(self, indices: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(images uint8 stack, labels int32) via the native C++ batch path
        when the dataset provides it, else the Python thread pool.

        The whole read runs under the retry layer (site `data.read`):
        a transient filesystem error — or an injected `io@site=data.read`
        fault — degrades to a logged retry instead of aborting the epoch
        through the prefetch thread."""

        def _load():
            faults.maybe_io_error("data.read")
            faults.maybe_delay("data.read")
            if hasattr(self.dataset, "load_batch"):  # native/loader.cc decode pool
                imgs, labels = self.dataset.load_batch(indices)
                return imgs, np.asarray(labels, np.int32)
            loads = list(self._pool.map(self.dataset.load, indices))
            return (
                np.stack([img for img, _ in loads]),
                np.asarray([l for _, l in loads], np.int32),
            )

        # span lands on the prefetch producer's thread track: decode
        # time that OVERLAPS the train step is visible as such in the
        # trace, instead of inflating the step's apparent data wait
        with obs_span("host_decode", n=len(indices)):
            return retry.retry_call(_load, site="data.read")

    def _local_crop_batch(
        self, global_indices: np.ndarray, epoch: int, step: int,
        n_crops: int, scale: tuple, out_size: int,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Host-crop path: sample n_crops RRC boxes per image against its
        original dims, decode once + crop/resize in the loader; returns
        this process's (B_local, n_crops, S, S, 3) uint8 rows + labels.

        The crop uniforms are drawn ONCE per step for the full global
        batch × crops from a (seed, epoch, step)-keyed generator, and
        each process slices its rows by GLOBAL POSITION — process-
        independent, so model-axis replica groups that span processes
        (which hold the SAME global rows) decode identical pixels. (A
        per-(row, crop) seeded Generator here cost ~0.24 ms each of pure
        seeding overhead — ~120 ms of serial host time per 256-image
        batch, scripts/profile_input.py.)"""
        local_idx = self._partition.local_indices(global_indices)

        def _read_dims():
            faults.maybe_io_error("data.read")
            return self.dataset.dims(local_idx)

        dims = retry.retry_call(_read_dims, site="data.read")
        from moco_tpu.data.datasets import draw_rrc_uniforms, rrc_boxes_from_uniforms

        rng = np.random.default_rng((self.seed, epoch, step))
        u = draw_rrc_uniforms(rng, self.batch_size * n_crops)
        pos = np.asarray(self._partition.local_positions, np.int64)
        flat = (pos[:, None] * n_crops + np.arange(n_crops)[None, :]).reshape(-1)
        u_local = {k: v[flat] for k, v in u.items()}
        boxes = rrc_boxes_from_uniforms(
            u_local, np.repeat(dims, n_crops, axis=0), scale=scale
        ).reshape(len(local_idx), n_crops, 4)
        with obs_span("host_decode", n=len(local_idx), crops=n_crops):
            faults.maybe_delay("data.read")
            raw, labels = retry.retry_call(
                self.dataset.load_crop_batch,
                local_idx,
                boxes,
                out_size,
                pool=self._pool,
                site="data.read",
            )
        return raw, np.asarray(labels, np.int32)

    # -- device stage (sharded uint8 device_put + labels) ----------------

    def _assemble_views(self, hb: HostBatch) -> tuple[list[jax.Array], Optional[jax.Array]]:
        """Sharded device_put of one host batch: per-crop uint8 views on
        the host-crop path (slicing the crop axis of an already-assembled
        global array would not be fully-addressable under multi-host),
        the single canvas array otherwise. Registers the `input.h2d`
        comms-ledger entry so the wire shows up in the byte tables next
        to the ICI collectives."""
        part = self._partition
        with comms.tag("input.h2d", "device_put", (hb.views, hb.labels), axis_size=1):
            if hb.precropped:
                views = [
                    part.assemble(np.ascontiguousarray(hb.views[:, c]))
                    for c in range(hb.views.shape[1])
                ]
            else:
                views = [part.assemble(hb.views)]
            labels = (
                part.assemble(hb.labels) if hb.labels is not None else None
            )
        return views, labels

    @property
    def decode_failures(self) -> int:
        """Cumulative undecodable samples seen by the underlying dataset
        (zero-filled slots) — the train driver writes this to
        metrics.jsonl so data corruption is visible, not silent."""
        return int(getattr(self.dataset, "decode_failures", 0))

    def _epoch_order(self, epoch: int) -> np.ndarray:
        """Seeded shuffle per (seed, epoch) — sampler.set_epoch equivalent."""
        return np.random.default_rng((self.seed, epoch)).permutation(len(self.dataset))

    def _epoch_rng(self, epoch: int) -> jax.Array:
        return jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)

    @property
    def host_crops(self) -> bool:
        """Host-side RandomResizedCrop (decode-once/crop-N against the
        ORIGINAL image geometry — torchvision-exact distribution, no
        fixed-canvas clipping) when the dataset and config support it."""
        return self.config.host_rrc and hasattr(self.dataset, "load_crop_batch")

    # -- epoch assembly (shared by the two-crop/labeled pipelines) -------

    def _epoch_iter(self, host_gen, stage, device: bool, depth: Optional[int], donate: bool):
        """Wire one epoch's host generator + device stage into either
        mode (module docstring): sync = both on one producer thread;
        device=True = decode thread → transfer ring → consumer."""
        depth = 2 if depth is None else int(depth)
        if device:
            host_it = _prefetch(host_gen, depth=depth)
            return DevicePrefetchRing(
                host_it, lambda hb: stage(hb, donate), depth=depth
            )

        def gen():
            for hb in host_gen:
                out, _ = stage(hb, donate)
                yield out

        return _prefetch(gen(), depth=depth)


def _jit_pair(fn, donate_argnums: tuple):
    """(plain, donating) jitted variants of one augment fn. The donating
    variant recycles the consumed staging slot's HBM for the normalized
    output (prefetch_donate) — a separate executable, compiled only if
    donation is ever requested."""
    return jax.jit(fn), jax.jit(fn, donate_argnums=donate_argnums)


class TwoCropPipeline(_HostPipeline):
    """Iterable over {'im_q','im_k'} device batches for one epoch at a time."""

    def __init__(self, config: DataConfig, mesh: Mesh, seed: int = 0, dataset=None, train: bool = True):
        super().__init__(config, mesh, seed=seed, dataset=dataset, train=train, drop_last=True)
        self.recipe: AugRecipe = get_recipe(
            config.aug_plus, config.image_size, crops_only=config.crops_only
        )
        recipe, out_size = self.recipe, config.image_size

        def _augment(rng, raw_uint8):
            images = raw_uint8.astype(jnp.float32) / 255.0
            return two_crop_augment(recipe, rng, images, out_size)

        self._augment, self._augment_donated = _jit_pair(_augment, (1,))

        # host-crop variant: images arrive already cropped to out_size;
        # the device applies everything in the recipe EXCEPT the crop
        nocrop = recipe._replace(crop=False)

        def _augment_precropped(rng, q_uint8, k_uint8):
            k_q, k_k = jax.random.split(rng)
            q = apply_recipe(nocrop, k_q, q_uint8.astype(jnp.float32) / 255.0, out_size)
            k = apply_recipe(nocrop, k_k, k_uint8.astype(jnp.float32) / 255.0, out_size)
            return {"im_q": q, "im_k": k}

        self._augment_precropped, self._augment_precropped_donated = _jit_pair(
            _augment_precropped, (1, 2)
        )

    def _host_gen(self, epoch: int):
        order, rng = self._epoch_order(epoch), self._epoch_rng(epoch)
        for step in range(self.steps_per_epoch):
            idx = order[step * self.batch_size : (step + 1) * self.batch_size]
            step_rng = jax.random.fold_in(rng, step)
            if self.host_crops:
                raw, _ = self._local_crop_batch(
                    idx, epoch, step, n_crops=2,
                    scale=self.recipe.crop_scale,
                    out_size=self.config.image_size,
                )
                yield HostBatch(step, step_rng, raw, None, precropped=True)
            else:
                raw, _ = self._host_batch(self._partition.local_indices(idx))
                yield HostBatch(step, step_rng, raw, None, precropped=False)

    def _stage(self, hb: HostBatch, donate: bool):
        views, _ = self._assemble_views(hb)
        # span closed BEFORE the batch is handed on: a generator/queue
        # suspends inside `with`, which would bill consumer time to it
        with obs_span("augment_dispatch", step=hb.step):
            if hb.precropped:
                aug = self._augment_precropped_donated if donate else self._augment_precropped
                out = aug(hb.rng, views[0], views[1])
            else:
                aug = self._augment_donated if donate else self._augment
                out = aug(hb.rng, views[0])
        return out, hb.wire_bytes

    def epoch(
        self,
        epoch: int,
        device: bool = False,
        depth: Optional[int] = None,
        donate: bool = False,
    ) -> Iterator[dict]:
        return self._epoch_iter(self._host_gen(epoch), self._stage, device, depth, donate)


class LabeledPipeline(_HostPipeline):
    """Shuffled (images, labels) train batches with the probe transform
    (`main_lincls.py` train pipeline: RandomResizedCrop + flip + normalize)."""

    def __init__(self, config: DataConfig, mesh: Mesh, seed: int = 0, dataset=None):
        super().__init__(config, mesh, seed=seed, dataset=dataset, train=True, drop_last=True)
        base = get_recipe(config.aug_plus, config.image_size)
        self.recipe = PROBE_RECIPE._replace(mean=base.mean, std=base.std)
        recipe, out_size = self.recipe, config.image_size

        def _augment(rng, raw_uint8):
            images = raw_uint8.astype(jnp.float32) / 255.0
            return apply_recipe(recipe, rng, images, out_size)

        self._augment, self._augment_donated = _jit_pair(_augment, (1,))
        nocrop = recipe._replace(crop=False)

        def _augment_precropped(rng, raw_uint8):
            images = raw_uint8.astype(jnp.float32) / 255.0
            return apply_recipe(nocrop, rng, images, out_size)

        self._augment_precropped, self._augment_precropped_donated = _jit_pair(
            _augment_precropped, (1,)
        )

    def _host_gen(self, epoch: int):
        order, rng = self._epoch_order(epoch), self._epoch_rng(epoch)
        for step in range(self.steps_per_epoch):
            idx = order[step * self.batch_size : (step + 1) * self.batch_size]
            step_rng = jax.random.fold_in(rng, step)
            if self.host_crops:
                raw, labels = self._local_crop_batch(
                    idx, epoch, step, n_crops=1,
                    scale=self.recipe.crop_scale,
                    out_size=self.config.image_size,
                )
                yield HostBatch(step, step_rng, raw, labels, precropped=True)
            else:
                raw, labels = self._host_batch(self._partition.local_indices(idx))
                yield HostBatch(step, step_rng, raw, labels, precropped=False)

    def _stage(self, hb: HostBatch, donate: bool):
        views, labels = self._assemble_views(hb)
        with obs_span("augment_dispatch", step=hb.step):
            if hb.precropped:
                aug = self._augment_precropped_donated if donate else self._augment_precropped
            else:
                aug = self._augment_donated if donate else self._augment
            out = aug(hb.rng, views[0])
        return (out, labels), hb.wire_bytes

    def epoch(
        self,
        epoch: int,
        device: bool = False,
        depth: Optional[int] = None,
        donate: bool = False,
    ) -> Iterator[tuple]:
        return self._epoch_iter(self._host_gen(epoch), self._stage, device, depth, donate)


class EvalPipeline(_HostPipeline):
    """Deterministic center-crop (images, labels, valid_mask) batches for
    the linear probe (`main_lincls.py` val transform: Resize(256),
    CenterCrop(224)). The tail batch is padded to full size with repeats
    and masked so the *entire* split is scored — a truncated class-sorted
    val set would bias top-1 (the last classes would never be evaluated).
    """

    def __init__(self, config: DataConfig, mesh: Mesh, train: bool = False, dataset=None):
        super().__init__(config, mesh, dataset=dataset, train=train, drop_last=False)
        self.steps = self.steps_per_epoch

    def __iter__(self):
        recipe = get_recipe(self.config.aug_plus, self.config.image_size)
        n = len(self.dataset)
        out_size = self.config.image_size

        # uint8 crosses the host->device boundary (4x less transfer than
        # fp32); /255, center-crop, normalize run jitted on the sharded
        # array, like the train pipelines do
        @jax.jit
        def _prep(raw_uint8):
            x = raw_uint8.astype(jnp.float32) / 255.0
            # one decode geometry per run: the branch specializes the one
            # trace, it cannot retrigger (tail batches are padded to size)
            if x.shape[1] != out_size:  # mocolint: disable=JX004
                y0 = (x.shape[1] - out_size) // 2
                x = x[:, y0 : y0 + out_size, y0 : y0 + out_size]
            mean = jnp.asarray(recipe.mean, jnp.float32)
            std = jnp.asarray(recipe.std, jnp.float32)
            return (x - mean) / std

        def gen():
            part = self._partition
            for step in range(self.steps):
                start = step * self.batch_size
                idx = np.arange(start, min(start + self.batch_size, n))
                valid = len(idx)
                if valid < self.batch_size:  # pad the tail, mask the pads
                    idx = np.concatenate([idx, np.full(self.batch_size - valid, idx[-1])])
                mask = (np.arange(self.batch_size) < valid).astype(np.float32)
                # per-process decode of only this host's rows
                raw, labels = self._host_batch(part.local_indices(idx))
                yield (
                    _prep(part.assemble(raw)),
                    part.assemble(np.asarray(labels, np.int32)),
                    part.assemble(mask[part.local_positions]),
                )

        return _prefetch(gen(), depth=2)
