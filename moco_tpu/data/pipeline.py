"""Input pipeline: host decode → device two-crop augment → prefetch.

Replaces the reference's `DataLoader(workers=32)` + `TwoCropsTransform`
(`main_moco.py:~L255-260`, `moco/loader.py`). Split of labor:

- host threads: index shuffling (per-epoch, seeded — the
  `DistributedSampler.set_epoch` equivalent), image decode to a fixed
  uint8 canvas, batch stacking;
- device: ALL stochastic augmentation, batched and jitted
  (`moco_tpu.data.augment.two_crop_augment`), producing {'im_q','im_k'}
  already sharded over the mesh's data axis;
- a depth-2 prefetch queue overlaps host decode with the train step.

drop_last=True semantics (reference DataLoader) — the queue's
`K % global_batch == 0` invariant requires full batches.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from moco_tpu.data.augment import AugRecipe, get_recipe, two_crop_augment
from moco_tpu.data.datasets import build_dataset
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.utils.config import DataConfig


class TwoCropPipeline:
    """Iterable over {'im_q','im_k'} device batches for one epoch at a time."""

    def __init__(
        self,
        config: DataConfig,
        mesh: Mesh,
        seed: int = 0,
        dataset=None,
        train: bool = True,
    ):
        self.config = config
        self.mesh = mesh
        self.seed = seed
        self.dataset = dataset or build_dataset(
            config.dataset, config.data_dir, config.image_size, train=train
        )
        self.batch_size = config.global_batch
        if len(self.dataset) < self.batch_size:
            raise ValueError(
                f"dataset of {len(self.dataset)} examples < global batch {self.batch_size}"
            )
        self.steps_per_epoch = len(self.dataset) // self.batch_size  # drop_last
        self.recipe: AugRecipe = get_recipe(config.aug_plus, config.image_size)
        self._pool = ThreadPoolExecutor(max_workers=max(config.num_workers, 1))
        self._batch_sharding = NamedSharding(mesh, P(DATA_AXIS))

        out_size = config.image_size
        recipe = self.recipe

        @jax.jit
        def _augment(rng, raw_uint8):
            images = raw_uint8.astype(jnp.float32) / 255.0
            return two_crop_augment(recipe, rng, images, out_size)

        self._augment = _augment

    def _host_batch(self, indices: np.ndarray) -> np.ndarray:
        loads = list(self._pool.map(self.dataset.load, indices))
        return np.stack([img for img, _ in loads])

    def epoch(self, epoch: int) -> Iterator[dict]:
        """Shuffled epoch, seeded by (seed, epoch) — sampler.set_epoch equiv."""
        order = np.random.default_rng((self.seed, epoch)).permutation(len(self.dataset))
        rng = jax.random.PRNGKey(self.seed)
        rng = jax.random.fold_in(rng, epoch)

        def gen():
            for step in range(self.steps_per_epoch):
                idx = order[step * self.batch_size : (step + 1) * self.batch_size]
                raw = self._host_batch(idx)
                step_rng = jax.random.fold_in(rng, step)
                raw = jax.device_put(raw, self._batch_sharding)
                yield self._augment(step_rng, raw)

        return _prefetch(gen(), depth=2)


def _prefetch(it: Iterator, depth: int = 2) -> Iterator:
    """Run the producer in a thread, keeping `depth` batches in flight."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    _END = object()

    def producer():
        try:
            for item in it:
                q.put(item)
            q.put(_END)
        except BaseException as e:  # surface producer errors to the consumer
            q.put(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


class EvalPipeline:
    """Deterministic center-crop batches with labels, for the linear probe
    (`main_lincls.py` val transform: Resize(256), CenterCrop(224))."""

    def __init__(self, config: DataConfig, mesh: Mesh, train: bool = False, dataset=None):
        self.config = config
        self.dataset = dataset or build_dataset(
            config.dataset, config.data_dir, config.image_size, train=train
        )
        self.batch_size = config.global_batch
        self.steps = len(self.dataset) // self.batch_size
        self.mesh = mesh
        self._sharding = NamedSharding(mesh, P(DATA_AXIS))
        self._pool = ThreadPoolExecutor(max_workers=max(config.num_workers, 1))

    def __iter__(self):
        from moco_tpu.data.augment import get_recipe, normalize

        recipe = get_recipe(self.config.aug_plus, self.config.image_size)

        def gen():
            for step in range(self.steps):
                idx = np.arange(step * self.batch_size, (step + 1) * self.batch_size)
                loads = list(self._pool.map(self.dataset.load, idx))
                raw = np.stack([img for img, _ in loads])
                labels = np.asarray([l for _, l in loads], np.int32)
                x = jnp.asarray(raw, jnp.float32) / 255.0
                if x.shape[1] != self.config.image_size:
                    y0 = (x.shape[1] - self.config.image_size) // 2
                    x = x[:, y0 : y0 + self.config.image_size, y0 : y0 + self.config.image_size]
                x = normalize(x, recipe.mean, recipe.std)
                yield (
                    jax.device_put(x, self._sharding),
                    jax.device_put(jnp.asarray(labels), self._sharding),
                )

        return _prefetch(gen(), depth=2)
