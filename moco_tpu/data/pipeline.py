"""Input pipeline: host decode → device augment → prefetch.

Replaces the reference's `DataLoader(workers=32)` + `TwoCropsTransform`
(`main_moco.py:~L255-260`, `moco/loader.py`). Split of labor:

- host: index shuffling (per-epoch, seeded — the
  `DistributedSampler.set_epoch` equivalent); for datasets exposing the
  host-crop protocol (ImageFolder), torchvision-exact RandomResizedCrop
  boxes sampled against each image's ORIGINAL geometry and executed in
  the loader (decode once, crop/resize N times — native C++ pool when
  built, else PIL threads); otherwise decode to a fixed uint8 canvas;
- device: the remaining stochastic augmentation (jitter/gray/blur/flip/
  normalize — plus the crop itself on the canvas path), batched and
  jitted (`moco_tpu.data.augment`), already sharded over the mesh's
  data axis;
- a depth-2 prefetch queue overlaps host decode with the train step.

Training pipelines use drop_last=True semantics (reference DataLoader) —
the queue's `K % global_batch == 0` invariant requires full batches. The
eval pipeline instead pads the tail batch and carries a validity mask so
the whole val split is scored (the reference evaluates the full split
too).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from moco_tpu.data.augment import (
    AugRecipe,
    PROBE_RECIPE,
    apply_recipe,
    get_recipe,
    two_crop_augment,
)
from moco_tpu.data.datasets import build_dataset
from moco_tpu.obs.trace import span as obs_span
from moco_tpu.parallel.dist import ProcessDataPartition
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.utils import faults, retry
from moco_tpu.utils.config import DataConfig


def _prefetch(it: Iterator, depth: int = 2) -> Iterator:
    """Run the producer in a thread, keeping `depth` batches in flight."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    _END = object()

    def producer():
        try:
            for item in it:
                q.put(item)
            q.put(_END)
        except BaseException as e:  # surface producer errors to the consumer
            q.put(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


class _HostPipeline:
    """Shared host-side machinery: dataset build, batch/steps accounting,
    decode pool, mesh sharding, seeded per-epoch shuffling."""

    def __init__(
        self,
        config: DataConfig,
        mesh: Mesh,
        seed: int = 0,
        dataset=None,
        train: bool = True,
        drop_last: bool = True,
    ):
        self.config = config
        self.mesh = mesh
        self.seed = seed
        self.dataset = dataset or build_dataset(
            config.dataset,
            config.data_dir,
            config.image_size,
            train=train,
            num_workers=config.num_workers,
            cache_dir=config.cache_dir,
        )
        self.batch_size = config.global_batch
        if drop_last and len(self.dataset) < self.batch_size:
            raise ValueError(
                f"dataset of {len(self.dataset)} examples < global batch {self.batch_size}"
            )
        n = len(self.dataset)
        self.steps_per_epoch = n // self.batch_size if drop_last else -(-n // self.batch_size)
        self._pool = ThreadPoolExecutor(max_workers=max(config.num_workers, 1))
        self._sharding = NamedSharding(mesh, P(DATA_AXIS))
        # Multi-host input sharding (DistributedSampler equivalent,
        # main_moco.py:~L258): this process decodes only the global-batch
        # rows owned by its addressable devices; single-host it holds all
        # rows, so one code path serves both.
        self._partition = ProcessDataPartition(self._sharding, self.batch_size)

    def _put_batch(self, global_indices: np.ndarray) -> tuple[jax.Array, jax.Array]:
        """Decode this process's rows of the step's global batch and
        assemble (images, labels) as globally-sharded jax.Arrays."""
        local_idx = self._partition.local_indices(global_indices)
        raw, labels = self._host_batch(local_idx)
        return (
            self._partition.assemble(raw),
            self._partition.assemble(np.asarray(labels, np.int32)),
        )

    def _host_batch(self, indices: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(images uint8 stack, labels int32) via the native C++ batch path
        when the dataset provides it, else the Python thread pool.

        The whole read runs under the retry layer (site `data.read`):
        a transient filesystem error — or an injected `io@site=data.read`
        fault — degrades to a logged retry instead of aborting the epoch
        through the prefetch thread."""

        def _load():
            faults.maybe_io_error("data.read")
            if hasattr(self.dataset, "load_batch"):  # native/loader.cc decode pool
                imgs, labels = self.dataset.load_batch(indices)
                return imgs, np.asarray(labels, np.int32)
            loads = list(self._pool.map(self.dataset.load, indices))
            return (
                np.stack([img for img, _ in loads]),
                np.asarray([l for _, l in loads], np.int32),
            )

        # span lands on the prefetch producer's thread track: decode
        # time that OVERLAPS the train step is visible as such in the
        # trace, instead of inflating the step's apparent data wait
        with obs_span("host_decode", n=len(indices)):
            return retry.retry_call(_load, site="data.read")

    @property
    def decode_failures(self) -> int:
        """Cumulative undecodable samples seen by the underlying dataset
        (zero-filled slots) — the train driver writes this to
        metrics.jsonl so data corruption is visible, not silent."""
        return int(getattr(self.dataset, "decode_failures", 0))

    def _epoch_order(self, epoch: int) -> np.ndarray:
        """Seeded shuffle per (seed, epoch) — sampler.set_epoch equivalent."""
        return np.random.default_rng((self.seed, epoch)).permutation(len(self.dataset))

    def _epoch_rng(self, epoch: int) -> jax.Array:
        return jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)

    @property
    def host_crops(self) -> bool:
        """Host-side RandomResizedCrop (decode-once/crop-N against the
        ORIGINAL image geometry — torchvision-exact distribution, no
        fixed-canvas clipping) when the dataset and config support it."""
        return self.config.host_rrc and hasattr(self.dataset, "load_crop_batch")

    def _put_crop_batch(
        self, global_indices: np.ndarray, epoch: int, step: int,
        n_crops: int, scale: tuple, out_size: int,
    ) -> tuple[jax.Array, jax.Array]:
        """Host-crop path: sample n_crops RRC boxes per image against its
        original dims, decode once + crop/resize in the loader, assemble
        globally sharded (B, n_crops, S, S, 3) uint8 + labels.

        The crop uniforms are drawn ONCE per step for the full global
        batch × crops from a (seed, epoch, step)-keyed generator, and
        each process slices its rows by GLOBAL POSITION — process-
        independent, so model-axis replica groups that span processes
        (which hold the SAME global rows) decode identical pixels. (A
        per-(row, crop) seeded Generator here cost ~0.24 ms each of pure
        seeding overhead — ~120 ms of serial host time per 256-image
        batch, scripts/profile_input.py.)"""
        local_idx = self._partition.local_indices(global_indices)

        def _read_dims():
            faults.maybe_io_error("data.read")
            return self.dataset.dims(local_idx)

        dims = retry.retry_call(_read_dims, site="data.read")
        from moco_tpu.data.datasets import draw_rrc_uniforms, rrc_boxes_from_uniforms

        rng = np.random.default_rng((self.seed, epoch, step))
        u = draw_rrc_uniforms(rng, self.batch_size * n_crops)
        pos = np.asarray(self._partition.local_positions, np.int64)
        flat = (pos[:, None] * n_crops + np.arange(n_crops)[None, :]).reshape(-1)
        u_local = {k: v[flat] for k, v in u.items()}
        boxes = rrc_boxes_from_uniforms(
            u_local, np.repeat(dims, n_crops, axis=0), scale=scale
        ).reshape(len(local_idx), n_crops, 4)
        with obs_span("host_decode", n=len(local_idx), crops=n_crops):
            raw, labels = retry.retry_call(
                self.dataset.load_crop_batch,
                local_idx,
                boxes,
                out_size,
                pool=self._pool,
                site="data.read",
            )
        # assemble per crop on the HOST side: slicing the crop axis of an
        # already-assembled global array would not be fully-addressable
        # under multi-host
        views = [self._partition.assemble(np.ascontiguousarray(raw[:, c])) for c in range(n_crops)]
        return views, self._partition.assemble(np.asarray(labels, np.int32))


class TwoCropPipeline(_HostPipeline):
    """Iterable over {'im_q','im_k'} device batches for one epoch at a time."""

    def __init__(self, config: DataConfig, mesh: Mesh, seed: int = 0, dataset=None, train: bool = True):
        super().__init__(config, mesh, seed=seed, dataset=dataset, train=train, drop_last=True)
        self.recipe: AugRecipe = get_recipe(
            config.aug_plus, config.image_size, crops_only=config.crops_only
        )
        recipe, out_size = self.recipe, config.image_size

        @jax.jit
        def _augment(rng, raw_uint8):
            images = raw_uint8.astype(jnp.float32) / 255.0
            return two_crop_augment(recipe, rng, images, out_size)

        self._augment = _augment

        # host-crop variant: images arrive already cropped to out_size;
        # the device applies everything in the recipe EXCEPT the crop
        nocrop = recipe._replace(crop=False)

        @jax.jit
        def _augment_precropped(rng, q_uint8, k_uint8):
            k_q, k_k = jax.random.split(rng)
            q = apply_recipe(nocrop, k_q, q_uint8.astype(jnp.float32) / 255.0, out_size)
            k = apply_recipe(nocrop, k_k, k_uint8.astype(jnp.float32) / 255.0, out_size)
            return {"im_q": q, "im_k": k}

        self._augment_precropped = _augment_precropped

    def epoch(self, epoch: int) -> Iterator[dict]:
        order, rng = self._epoch_order(epoch), self._epoch_rng(epoch)

        def gen():
            for step in range(self.steps_per_epoch):
                idx = order[step * self.batch_size : (step + 1) * self.batch_size]
                step_rng = jax.random.fold_in(rng, step)
                if self.host_crops:
                    (q_raw, k_raw), _ = self._put_crop_batch(
                        idx, epoch, step, n_crops=2,
                        scale=self.recipe.crop_scale,
                        out_size=self.config.image_size,
                    )  # two (B, S, S, 3) sharded views
                    # span closed BEFORE the yield: a generator suspends
                    # inside `with`, which would bill consumer time to it
                    with obs_span("augment_dispatch", step=step):
                        out = self._augment_precropped(step_rng, q_raw, k_raw)
                    yield out
                else:
                    raw, _ = self._put_batch(idx)
                    with obs_span("augment_dispatch", step=step):
                        out = self._augment(step_rng, raw)
                    yield out

        return _prefetch(gen(), depth=2)


class LabeledPipeline(_HostPipeline):
    """Shuffled (images, labels) train batches with the probe transform
    (`main_lincls.py` train pipeline: RandomResizedCrop + flip + normalize)."""

    def __init__(self, config: DataConfig, mesh: Mesh, seed: int = 0, dataset=None):
        super().__init__(config, mesh, seed=seed, dataset=dataset, train=True, drop_last=True)
        base = get_recipe(config.aug_plus, config.image_size)
        self.recipe = PROBE_RECIPE._replace(mean=base.mean, std=base.std)
        recipe, out_size = self.recipe, config.image_size

        @jax.jit
        def _augment(rng, raw_uint8):
            images = raw_uint8.astype(jnp.float32) / 255.0
            return apply_recipe(recipe, rng, images, out_size)

        self._augment = _augment
        nocrop = recipe._replace(crop=False)

        @jax.jit
        def _augment_precropped(rng, raw_uint8):
            images = raw_uint8.astype(jnp.float32) / 255.0
            return apply_recipe(nocrop, rng, images, out_size)

        self._augment_precropped = _augment_precropped

    def epoch(self, epoch: int) -> Iterator[tuple]:
        order, rng = self._epoch_order(epoch), self._epoch_rng(epoch)

        def gen():
            for step in range(self.steps_per_epoch):
                idx = order[step * self.batch_size : (step + 1) * self.batch_size]
                step_rng = jax.random.fold_in(rng, step)
                if self.host_crops:
                    (raw,), labels = self._put_crop_batch(
                        idx, epoch, step, n_crops=1,
                        scale=self.recipe.crop_scale,
                        out_size=self.config.image_size,
                    )
                    yield self._augment_precropped(step_rng, raw), labels
                else:
                    raw, labels = self._put_batch(idx)
                    yield self._augment(step_rng, raw), labels

        return _prefetch(gen(), depth=2)


class EvalPipeline(_HostPipeline):
    """Deterministic center-crop (images, labels, valid_mask) batches for
    the linear probe (`main_lincls.py` val transform: Resize(256),
    CenterCrop(224)). The tail batch is padded to full size with repeats
    and masked so the *entire* split is scored — a truncated class-sorted
    val set would bias top-1 (the last classes would never be evaluated).
    """

    def __init__(self, config: DataConfig, mesh: Mesh, train: bool = False, dataset=None):
        super().__init__(config, mesh, dataset=dataset, train=train, drop_last=False)
        self.steps = self.steps_per_epoch

    def __iter__(self):
        recipe = get_recipe(self.config.aug_plus, self.config.image_size)
        n = len(self.dataset)
        out_size = self.config.image_size

        # uint8 crosses the host->device boundary (4x less transfer than
        # fp32); /255, center-crop, normalize run jitted on the sharded
        # array, like the train pipelines do
        @jax.jit
        def _prep(raw_uint8):
            x = raw_uint8.astype(jnp.float32) / 255.0
            # one decode geometry per run: the branch specializes the one
            # trace, it cannot retrigger (tail batches are padded to size)
            if x.shape[1] != out_size:  # mocolint: disable=JX004
                y0 = (x.shape[1] - out_size) // 2
                x = x[:, y0 : y0 + out_size, y0 : y0 + out_size]
            mean = jnp.asarray(recipe.mean, jnp.float32)
            std = jnp.asarray(recipe.std, jnp.float32)
            return (x - mean) / std

        def gen():
            part = self._partition
            for step in range(self.steps):
                start = step * self.batch_size
                idx = np.arange(start, min(start + self.batch_size, n))
                valid = len(idx)
                if valid < self.batch_size:  # pad the tail, mask the pads
                    idx = np.concatenate([idx, np.full(self.batch_size - valid, idx[-1])])
                mask = (np.arange(self.batch_size) < valid).astype(np.float32)
                # per-process decode of only this host's rows
                raw, labels = self._host_batch(part.local_indices(idx))
                yield (
                    _prep(part.assemble(raw)),
                    part.assemble(np.asarray(labels, np.int32)),
                    part.assemble(mask[part.local_positions]),
                )

        return _prefetch(gen(), depth=2)
