from moco_tpu.ops.losses import (
    cross_entropy,
    infonce_logits,
    l2_normalize,
    topk_accuracy,
)

__all__ = ["cross_entropy", "infonce_logits", "l2_normalize", "topk_accuracy"]
