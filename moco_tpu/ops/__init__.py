from moco_tpu.ops.losses import (
    cross_entropy,
    infonce_logits,
    l2_normalize,
    topk_accuracy,
)
from moco_tpu.ops.flash_attention import flash_attention, flash_attention_with_lse
from moco_tpu.ops.fused_infonce import fused_infonce_loss, infonce_stats

__all__ = [
    "cross_entropy",
    "infonce_logits",
    "l2_normalize",
    "topk_accuracy",
    "flash_attention",
    "flash_attention_with_lse",
    "fused_infonce_loss",
    "infonce_stats",
]
