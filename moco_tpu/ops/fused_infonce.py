"""Fused streaming InfoNCE — Pallas TPU kernel.

Reference hot path (`moco/builder.py:~L128-161` + `main_moco.py:~L185`):
materialize `logits = [q·k | q·queueᵀ] / T` of shape (B, 1+K) — at the
default K=65536 that is a 67 MB fp32 intermediate per step — then run
CrossEntropyLoss over it, plus a top-k pass for the proxy accuracy.

This kernel never materializes the logits. The queue streams through
VMEM in (block_k, C) tiles while per-example running statistics are
carried in VMEM scratch across the sequential TPU grid:

    m       running max logit          (flash-softmax trick)
    l       running Σ exp(logit - m)
    n_above running count of negatives whose logit > the positive's

which yield exactly the three things the training step consumes:
  - per-example CE loss  = lse - pos          (lse = m + log l)
  - acc@1 = [n_above == 0], acc@5 = [n_above < 5]  (positive is column 0
    in the reference layout, so rank == #negatives above it)
  - the backward needs only (lse, pos): dq = Σ_j p_j·key_j/T - g·k/T with
    p_j = exp(q·key_j/T - lse), streamed again tile-by-tile.

queue and k get no gradient (the reference detaches both). Normalization
of q happens OUTSIDE (jnp) so autodiff chains through it naturally.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_K = 2048
NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, queue_ref, pos_ref, lse_ref, above_ref, m_sc, l_sc, a_sc, *, inv_t):
    i = pl.program_id(0)
    n = pl.num_programs(0)
    q = q_ref[...]  # (B, C) fp32
    pos = jnp.sum(q * k_ref[...], axis=-1) * inv_t  # (B,)

    @pl.when(i == 0)
    def _():
        m_sc[...] = jnp.maximum(pos, NEG_INF)
        l_sc[...] = jnp.exp(pos - jnp.maximum(pos, NEG_INF))  # == 1
        a_sc[...] = jnp.zeros_like(a_sc)

    tile = queue_ref[...]  # (block_k, C)
    s = jax.lax.dot_general(
        q, tile, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * inv_t  # (B, block_k)

    m_prev, l_prev = m_sc[...], l_sc[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    l_new = l_prev * jnp.exp(m_prev - m_new) + jnp.sum(jnp.exp(s - m_new[:, None]), axis=-1)
    m_sc[...] = m_new
    l_sc[...] = l_new
    a_sc[...] = a_sc[...] + jnp.sum((s > pos[:, None]).astype(jnp.int32), axis=-1)

    @pl.when(i == n - 1)
    def _():
        pos_ref[...] = pos
        lse_ref[...] = m_sc[...] + jnp.log(l_sc[...])
        above_ref[...] = a_sc[...]


def _bwd_kernel(q_ref, queue_ref, lse_ref, g_ref, dq_ref, acc_sc, *, inv_t):
    i = pl.program_id(0)
    n = pl.num_programs(0)
    q = q_ref[...]

    @pl.when(i == 0)
    def _():
        acc_sc[...] = jnp.zeros_like(acc_sc)

    tile = queue_ref[...]
    s = jax.lax.dot_general(
        q, tile, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * inv_t
    p = jnp.exp(s - lse_ref[...][:, None]) * g_ref[...][:, None]  # (B, block_k)
    acc_sc[...] = acc_sc[...] + jax.lax.dot_general(
        p, tile, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(i == n - 1)
    def _():
        dq_ref[...] = acc_sc[...] * inv_t


def _forward(q, k, queue, temperature, block_k, interpret):
    b, c = q.shape
    kk = queue.shape[0]
    kernel = functools.partial(_fwd_kernel, inv_t=1.0 / temperature)
    return pl.pallas_call(
        kernel,
        grid=(kk // block_k,),
        in_specs=[
            pl.BlockSpec((b, c), lambda i: (0, 0)),
            pl.BlockSpec((b, c), lambda i: (0, 0)),
            pl.BlockSpec((block_k, c), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((b,), lambda i: (0,)),
            pl.BlockSpec((b,), lambda i: (0,)),
            pl.BlockSpec((b,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b,), jnp.float32),  # pos
            jax.ShapeDtypeStruct((b,), jnp.float32),  # lse
            jax.ShapeDtypeStruct((b,), jnp.int32),  # negatives above pos
        ],
        scratch_shapes=[
            pltpu.VMEM((b,), jnp.float32),
            pltpu.VMEM((b,), jnp.float32),
            pltpu.VMEM((b,), jnp.int32),
        ],
        interpret=interpret,
    )(q.astype(jnp.float32), k.astype(jnp.float32), queue.astype(jnp.float32))


def _reference(q, k, queue, temperature):
    """Dense jnp oracle (and CPU fallback): same outputs."""
    pos = jnp.sum(q * k, axis=-1) / temperature
    # k/queue are detached by construction: infonce_stats' custom_vjp
    # returns no cotangent for them (_vjp_bwd yields dq only)
    neg = q @ queue.T / temperature  # mocolint: disable=JX005
    all_logits = jnp.concatenate([pos[:, None], neg], axis=1)
    lse = jax.nn.logsumexp(all_logits, axis=-1)
    above = jnp.sum(neg > pos[:, None], axis=-1).astype(jnp.int32)
    return pos, lse, above


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def infonce_stats(
    q: jax.Array,  # (B, C) L2-normalized queries — grads flow
    k: jax.Array,  # (B, C) positive keys — detached
    queue: jax.Array,  # (K, C) negatives — detached
    temperature: float,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
):
    """(pos, lse, n_above) per example, without materializing (B, 1+K)."""
    if queue.shape[0] % block_k or queue.shape[0] == 0:
        return _reference(q, k, queue, temperature)
    return _forward(q, k, queue, temperature, block_k, interpret)


def _vjp_fwd(q, k, queue, temperature, block_k, interpret):
    out = infonce_stats(q, k, queue, temperature, block_k, interpret)
    pos, lse, above = out
    return out, (q, k, queue, lse)


def _vjp_bwd(temperature, block_k, interpret, res, cots):
    q, k, queue, lse = res
    g_pos, g_lse, _ = cots  # n_above is integer — no gradient
    inv_t = 1.0 / temperature
    b, c = q.shape
    kk = queue.shape[0]
    # dq from the lse term: sum_j p_j key_j / T (streamed), j over [pos]+queue
    if g_lse is None:
        g_lse = jnp.zeros((b,), jnp.float32)
    if g_pos is None:
        g_pos = jnp.zeros((b,), jnp.float32)
    if kk % block_k or kk == 0:
        p_neg = jnp.exp(q @ queue.T * inv_t - lse[:, None])
        dq_neg = (p_neg * g_lse[:, None]) @ queue * inv_t
    else:
        kernel = functools.partial(_bwd_kernel, inv_t=inv_t)
        dq_neg = pl.pallas_call(
            kernel,
            grid=(kk // block_k,),
            in_specs=[
                pl.BlockSpec((b, c), lambda i: (0, 0)),
                pl.BlockSpec((block_k, c), lambda i: (i, 0)),
                pl.BlockSpec((b,), lambda i: (0,)),
                pl.BlockSpec((b,), lambda i: (0,)),
            ],
            out_specs=pl.BlockSpec((b, c), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((b, c), jnp.float32),
            scratch_shapes=[pltpu.VMEM((b, c), jnp.float32)],
            interpret=interpret,
        )(q.astype(jnp.float32), queue.astype(jnp.float32), lse, g_lse)
    # pos-logit path: through both the pos output and the lse
    pos = jnp.sum(q * k, axis=-1) * inv_t
    p_pos = jnp.exp(pos - lse)
    coeff = (g_pos + g_lse * p_pos) * inv_t
    dq = dq_neg + coeff[:, None] * k
    return dq.astype(q.dtype), None, None


infonce_stats.defvjp(_vjp_fwd, _vjp_bwd)


def fused_infonce_loss(
    q: jax.Array,
    k: jax.Array,
    queue: jax.Array,
    temperature: float,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
):
    """(mean CE loss, {'acc1','acc5'}) — drop-in for the
    infonce_logits → cross_entropy → topk_accuracy chain with the
    positive at column 0 (labels are implicitly all-zero)."""
    k = jax.lax.stop_gradient(k)
    queue = jax.lax.stop_gradient(queue)
    pos, lse, above = infonce_stats(q, k, queue, temperature, block_k, interpret)
    loss = jnp.mean(lse - pos)
    metrics = {
        "acc1": 100.0 * jnp.mean((above == 0).astype(jnp.float32)),
        "acc5": 100.0 * jnp.mean((above < 5).astype(jnp.float32)),
    }
    return loss, metrics
