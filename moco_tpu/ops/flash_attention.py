"""Blockwise (flash) attention as Pallas TPU kernels, forward AND backward.

Why it exists: the reference is a CNN codebase with no attention at all
(SURVEY.md §5.7); this framework adds the ViT/MoCo-v3 family, and makes
long sequences first-class. At ViT's 197 tokens XLA's fused attention is
already fine — this kernel is for the long-sequence regime (high-res
images, video: thousands of tokens) where materializing the (S, S)
score matrix blows past VMEM. The classic streaming-softmax recipe
(Flash Attention; blockwise attention) keeps O(block²) live state:
running max `m`, running denominator `l`, running numerator `acc`,
renormalized as each key/value block arrives.

Arbitrary sequence lengths are supported by padding to the block size
and masking padded keys inside the kernel (ViT's 197 = 196 patches +
cls is prime — without masking no block size divides it and the kernel
would never engage).

The backward pass is two Pallas kernels (dq; dk/dv), each recomputing
attention probabilities from (q, k, lse) per tile — O(block²) live
state, like the forward. A jnp chunked-recompute fallback remains for
CPU/interpret use and as the grad oracle in tests.

It is also the per-device compute block of ring attention
(`moco_tpu/parallel/ring_attention.py`): `flash_attention_with_lse`
returns the (out, logsumexp) pair that lets partial attention results
from different devices be combined exactly, and the backward carries
the lse cotangent that merge induces.

Non-causal (ViT is bidirectional); fp32 accumulation regardless of
input dtype; jnp reference implementation included for testing and as
the CPU fallback.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# Padded-row lse sentinel: exp(s - LSE_PAD) == 0 for any finite s, so
# padded queries contribute nothing in the backward kernels.
LSE_PAD = 1e30


def _attn_reference(q, k, v, scale):
    """Dense jnp reference: (B, H, S, D) -> (out, lse)."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    lse = jax.nn.logsumexp(logits, axis=-1)
    probs = jnp.exp(logits - lse[..., None])
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


def _pad_axis(x: jax.Array, axis: int, mult: int, value: float = 0.0) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


# ------------------------------------------------------------- forward


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, scale: float, kv_len: int
):
    """One (batch*head, q-block) program: stream all K/V blocks.

    Refs: q (block_q, D); k, v (S_pad, D) — whole K/V in VMEM per program
    (ring attention keeps S_local small; for single-device long-S the
    grid could also block K, at the cost of a scratch accumulator).
    Keys at column ≥ kv_len are padding and masked to -inf.

    Dots run in the INPUT dtype with fp32 accumulation (MXU-native for
    bf16 inputs; forcing fp32 operands was measured ~2x slower than the
    XLA default-precision jnp fallback); softmax statistics stay fp32.
    """
    q = q_ref[...]
    seq_k, d = k_ref.shape
    block_q = q.shape[0]
    masked = kv_len < seq_k

    def body(start, carry):
        acc, m_prev, l_prev = carry
        kb = k_ref[pl.ds(start, block_k), :]
        vb = v_ref[pl.ds(start, block_k), :]
        s = (
            jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )  # (block_q, block_k) fp32
        if masked:  # static: only when padding exists
            cols = start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols < kv_len, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[:, None] + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    num_blocks = seq_k // block_k

    acc, m, l = jax.lax.fori_loop(
        0, num_blocks, lambda i, c: body(i * block_k, c), (acc0, m0, l0)
    )
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)
    # lse is carried as a (1, block_q) row vector: Mosaic requires 2-D
    # blocks whose trailing dims are (8, 128)-aligned or full-array.
    lse_ref[0, :] = m + jnp.log(l)


def _flash_forward(
    q: jax.Array,  # (B, H, S, D)
    k: jax.Array,
    v: jax.Array,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> tuple[jax.Array, jax.Array]:
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    if s_k < block_k:
        # short sequences: the dense path is already a single VMEM tile
        return _attn_reference(q, k, v, scale)
    bh = b * h
    qp = _pad_axis(q.reshape(bh, s_q, d), 1, block_q)
    kp = _pad_axis(k.reshape(bh, s_k, d), 1, block_k)
    vp = _pad_axis(v.reshape(bh, s_k, d), 1, block_k)
    sq_p, sk_p = qp.shape[1], kp.shape[1]

    kernel = functools.partial(_flash_kernel, block_k=block_k, scale=scale, kv_len=s_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, sq_p // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),  # None: squeeze bh
            pl.BlockSpec((None, sk_p, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk_p, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq_p), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return (
        out[:, :s_q].reshape(b, h, s_q, d),
        lse[:, 0, :s_q].reshape(b, h, s_q),
    )


# ------------------------------------------------------------ backward


def _dq_kernel(
    q_ref, g_ref, lse_ref, delta_ref, glse_ref, k_ref, v_ref, dq_ref,
    *, block_k: int, scale: float, kv_len: int,
):
    """One (batch*head, q-block) program: dq for this query block,
    streaming K/V. ds = p ⊙ (g·vᵀ − Δ + g_lse); dq = ds·k·scale.
    Per-row stats arrive as (1, block_q) row vectors (Mosaic 2-D rule).

    NB a single fused dq+dk+dv kernel (score matrix computed once per
    tile, dq accumulated across the minor grid dim) was tried and wedged
    the remote-TPU session at compile/run; the two-pass split below is
    Mosaic-proven. Dots run in the INPUT dtype with fp32 accumulation
    (bf16 MXU passes; forcing fp32 operands measured ~2x slower)."""
    q = q_ref[...]
    g = g_ref[...]
    lse = lse_ref[0, :]
    coeff = glse_ref[0, :] - delta_ref[0, :]  # (block_q,)
    seq_k, d = k_ref.shape
    block_q = q.shape[0]
    masked = kv_len < seq_k

    def body(start, acc):
        kb = k_ref[pl.ds(start, block_k), :]
        vb = v_ref[pl.ds(start, block_k), :]
        s = (
            jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )
        if masked:
            cols = start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols < kv_len, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            g, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp + coeff[:, None])).astype(kb.dtype)
        return acc + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    num_blocks = seq_k // block_k
    acc = jax.lax.fori_loop(0, num_blocks, lambda i, a: body(i * block_k, a), acc0)
    dq_ref[...] = (acc * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    k_ref, v_ref, q_ref, g_ref, lse_ref, delta_ref, glse_ref, dk_ref, dv_ref,
    *, block_q: int, scale: float,
):
    """One (batch*head, k-block) program: dk, dv for this key block,
    streaming Q/G. Padded query rows carry lse = LSE_PAD ⇒ p = 0, so
    they contribute nothing; padded key rows are sliced off outside."""
    kb = k_ref[...]
    vb = v_ref[...]
    seq_q, d = q_ref.shape
    block_k = kb.shape[0]

    def body(start, carry):
        dk_acc, dv_acc = carry
        qb = q_ref[pl.ds(start, block_q), :]
        gb = g_ref[pl.ds(start, block_q), :]
        lse_b = lse_ref[0, pl.ds(start, block_q)]
        coeff_b = glse_ref[0, pl.ds(start, block_q)] - delta_ref[0, pl.ds(start, block_q)]
        s = (
            jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )  # (block_q, block_k)
        p = jnp.exp(s - lse_b[:, None])
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(gb.dtype), gb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            gb, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp + coeff_b[:, None])).astype(qb.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_acc, dv_acc

    zeros = jnp.zeros((block_k, d), jnp.float32)
    num_blocks = seq_q // block_q
    dk, dv = jax.lax.fori_loop(
        0, num_blocks, lambda i, c: body(i * block_q, c), (zeros, zeros)
    )
    dk_ref[...] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_backward_pallas(
    q, k, v, out, lse, g, g_lse, scale, block_q, block_k, interpret
):
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    bh = b * h
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    qp = _pad_axis(q.reshape(bh, s_q, d), 1, block_q)
    gp = _pad_axis(g.reshape(bh, s_q, d), 1, block_q)
    # per-row stats as (bh, 1, Sq) row vectors — Mosaic needs 2-D blocks
    lsep = _pad_axis(lse.reshape(bh, 1, s_q), 2, block_q, value=LSE_PAD)
    deltap = _pad_axis(delta.reshape(bh, 1, s_q), 2, block_q)
    glsep = _pad_axis(g_lse.reshape(bh, 1, s_q), 2, block_q)
    kp = _pad_axis(k.reshape(bh, s_k, d), 1, block_k)
    vp = _pad_axis(v.reshape(bh, s_k, d), 1, block_k)
    sq_p, sk_p = qp.shape[1], kp.shape[1]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, scale=scale, kv_len=s_k),
        grid=(bh, sq_p // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((None, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((None, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((None, sk_p, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk_p, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq_p, d), q.dtype),
        interpret=interpret,
    )(qp, gp, lsep, deltap, glsep, kp, vp)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, scale=scale),
        grid=(bh, sk_p // block_k),
        in_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sq_p, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sq_p, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, 1, sq_p), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, 1, sq_p), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, 1, sq_p), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk_p, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk_p, d), v.dtype),
        ],
        interpret=interpret,
    )(kp, vp, qp, gp, lsep, deltap, glsep)

    return (
        dq[:, :s_q].reshape(b, h, s_q, d),
        dk[:, :s_k].reshape(b, h, s_k, d),
        dv[:, :s_k].reshape(b, h, s_k, d),
    )


def _flash_backward_jnp(q, k, v, out, lse, g, g_lse, scale, block_q):
    """Recompute-based backward, CHUNKED over query blocks: attention
    probabilities are rebuilt from q, k and the saved lse per (block_q,
    S_k) tile inside a sequential `lax.map`, so peak memory is
    O(block_q·S_k) — never the full (S_q, S_k) matrix the forward kernel
    exists to avoid. dk/dv accumulate across chunks; dq is per-chunk.
    Serves as the CPU fallback and the grad oracle for the Pallas bwd."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    outf = out.astype(jnp.float32)
    s_q = q.shape[2]

    def chunk_grads(args):
        qc, gc, outc, lsec, glsec = args  # (B,H,bq,D) / (B,H,bq)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qc, kf) * scale
        p = jnp.exp(logits - lsec[..., None])  # (B,H,bq,Sk)
        dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, gc)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gc, vf)
        delta = jnp.sum(gc * outc, axis=-1, keepdims=True)
        # d(lse)/dq flows through p too
        ds = p * (dp - delta + glsec[..., None])
        dq_c = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
        dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, qc) * scale
        return dq_c, dk_c, dv_c

    if s_q % block_q or s_q == block_q:  # single chunk / odd sizes: one shot
        dq, dk, dv = chunk_grads((qf, gf, outf, lse, g_lse))
    else:
        n_chunks = s_q // block_q

        def to_chunks(x):  # (B,H,Sq,...) -> (n, B,H,bq,...)
            return jnp.stack(jnp.split(x, n_chunks, axis=2))

        dq_c, dk_c, dv_c = jax.lax.map(
            chunk_grads,
            (to_chunks(qf), to_chunks(gf), to_chunks(outf), to_chunks(lse), to_chunks(g_lse)),
        )
        dq = jnp.concatenate(list(dq_c), axis=2)
        dk = jnp.sum(dk_c, axis=0)
        dv = jnp.sum(dv_c, axis=0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """(out, lse) for non-causal attention over (B, H, S, D) inputs.

    `lse[b,h,q] = logsumexp_k(q·k*scale)` — the quantity ring attention
    needs to merge partial results across devices.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_forward(q, k, v, scale, block_q, block_k, interpret)


def _fwd(q, k, v, scale, block_q, block_k, interpret):
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _flash_forward(q, k, v, scale_, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _bwd(scale, block_q, block_k, interpret, res, cotangents):
    q, k, v, out, lse = res
    g, g_lse = cotangents
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    g_lse_f = (
        jnp.zeros(lse.shape, jnp.float32) if g_lse is None else g_lse.astype(jnp.float32)
    )
    # Pallas bwd engages exactly when the fwd kernel did (else the fwd
    # saved lse came from the dense path and shapes are small anyway).
    if k.shape[2] >= block_k:
        dq, dk, dv = _flash_backward_pallas(
            q, k, v, out, lse, g, g_lse_f, scale_, block_q, block_k, interpret
        )
    else:
        dq, dk, dv = _flash_backward_jnp(
            q, k, v, out, lse, g, g_lse_f, scale_, block_q
        )
    return dq, dk, dv


flash_attention_with_lse.defvjp(_fwd, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Attention output only; differentiable."""
    out, _ = flash_attention_with_lse(q, k, v, scale, block_q, block_k, interpret)
    return out
