"""Blockwise (flash) attention as a Pallas TPU kernel.

Why it exists: the reference is a CNN codebase with no attention at all
(SURVEY.md §5.7); this framework adds the ViT/MoCo-v3 family, and makes
long sequences first-class. At ViT's 197 tokens XLA's fused attention is
already fine — this kernel is for the long-sequence regime (high-res
images, video: thousands of tokens) where materializing the (S, S)
score matrix blows past VMEM. The classic streaming-softmax recipe
(Flash Attention; blockwise attention) keeps O(block²) live state:
running max `m`, running denominator `l`, running numerator `acc`,
renormalized as each key/value block arrives.

It is also the per-device compute block of ring attention
(`moco_tpu/parallel/ring_attention.py`): `flash_attention_with_lse`
returns the (out, logsumexp) pair that lets partial attention results
from different devices be combined exactly.

Non-causal (ViT is bidirectional); fp32 accumulation regardless of
input dtype; jnp reference implementation included for testing and as
the CPU fallback.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _attn_reference(q, k, v, scale):
    """Dense jnp reference: (B, H, S, D) -> (out, lse)."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    lse = jax.nn.logsumexp(logits, axis=-1)
    probs = jnp.exp(logits - lse[..., None])
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, scale: float):
    """One (batch*head, q-block) program: stream all K/V blocks.

    Refs: q (block_q, D); k, v (S, D) — whole K/V in VMEM per program
    (ring attention keeps S_local small; for single-device long-S the
    grid could also block K, at the cost of a scratch accumulator).
    """
    q = q_ref[...].astype(jnp.float32) * scale
    seq_k, d = k_ref.shape
    block_q = q.shape[0]

    def body(start, carry):
        acc, m_prev, l_prev = carry
        kb = k_ref[pl.ds(start, block_k), :].astype(jnp.float32)
        vb = v_ref[pl.ds(start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[:, None] + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    num_blocks = seq_k // block_k

    acc, m, l = jax.lax.fori_loop(
        0, num_blocks, lambda i, c: body(i * block_k, c), (acc0, m0, l0)
    )
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[...] = m + jnp.log(l)


def _flash_forward(
    q: jax.Array,  # (B, H, S, D)
    k: jax.Array,
    v: jax.Array,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> tuple[jax.Array, jax.Array]:
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    if s_q % block_q or s_k % block_k:
        # odd sizes (e.g. ViT's 197 tokens): fall back to the dense path
        return _attn_reference(q, k, v, scale)
    bh = b * h
    qr = q.reshape(bh, s_q, d)
    kr = k.reshape(bh, s_k, d)
    vr = v.reshape(bh, s_k, d)

    kernel = functools.partial(_flash_kernel, block_k=block_k, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, s_q // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),  # None: squeeze bh
            pl.BlockSpec((None, s_k, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s_k, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_q), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, s_q, d), lse.reshape(b, h, s_q)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """(out, lse) for non-causal attention over (B, H, S, D) inputs.

    `lse[b,h,q] = logsumexp_k(q·k*scale)` — the quantity ring attention
    needs to merge partial results across devices.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_forward(q, k, v, scale, block_q, block_k, interpret)


def _fwd(q, k, v, scale, block_q, block_k, interpret):
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _flash_forward(q, k, v, scale_, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _bwd(scale, block_q, block_k, interpret, res, cotangents):
    """Recompute-based backward, CHUNKED over query blocks: attention
    probabilities are rebuilt from q, k and the saved lse per (block_q,
    S_k) tile inside a sequential `lax.map`, so peak memory is
    O(block_q·S_k) — never the full (S_q, S_k) matrix the forward kernel
    exists to avoid. dk/dv accumulate across chunks; dq is per-chunk."""
    q, k, v, out, lse = res
    g, g_lse = cotangents
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    outf = out.astype(jnp.float32)
    g_lse_f = (
        jnp.zeros(lse.shape, jnp.float32) if g_lse is None else g_lse.astype(jnp.float32)
    )
    s_q = q.shape[2]

    def chunk_grads(args):
        qc, gc, outc, lsec, glsec = args  # (B,H,bq,D) / (B,H,bq)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qc, kf) * scale_
        p = jnp.exp(logits - lsec[..., None])  # (B,H,bq,Sk)
        dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, gc)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gc, vf)
        delta = jnp.sum(gc * outc, axis=-1, keepdims=True)
        # d(lse)/dq flows through p too
        ds = p * (dp - delta + glsec[..., None])
        dq_c = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale_
        dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, qc) * scale_
        return dq_c, dk_c, dv_c

    if s_q % block_q or s_q == block_q:  # single chunk / odd sizes: one shot
        dq, dk, dv = chunk_grads((qf, gf, outf, lse, g_lse_f))
    else:
        n_chunks = s_q // block_q

        def to_chunks(x):  # (B,H,Sq,...) -> (n, B,H,bq,...)
            return jnp.stack(jnp.split(x, n_chunks, axis=2))

        dq_c, dk_c, dv_c = jax.lax.map(
            chunk_grads,
            (to_chunks(qf), to_chunks(gf), to_chunks(outf), to_chunks(lse), to_chunks(g_lse_f)),
        )
        dq = jnp.concatenate(list(dq_c), axis=2)
        dk = jnp.sum(dk_c, axis=0)
        dv = jnp.sum(dv_c, axis=0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention_with_lse.defvjp(_fwd, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Attention output only; differentiable."""
    out, _ = flash_attention_with_lse(q, k, v, scale, block_q, block_k, interpret)
    return out
