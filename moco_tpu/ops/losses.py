"""Loss and metric primitives: L2-normalize, InfoNCE logits, stable CE, top-k.

Reference semantics being reproduced:
- `q = nn.functional.normalize(q, dim=1)` (`moco/builder.py:~L135,~L146`)
- InfoNCE logits: `l_pos = einsum('nc,nc->n', q, k)`,
  `l_neg = einsum('nc,ck->nk', q, queue)`, concat, `/= T`, labels all zero
  (`moco/builder.py:~L150-159`); loss is `nn.CrossEntropyLoss` in the
  driver (`main_moco.py:~L185`).
- `accuracy(output, target, topk=(1,5))` proxy metric (`main_moco.py:~L377-395`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def l2_normalize(x: jax.Array, axis: int = -1, eps: float = 1e-12) -> jax.Array:
    """Matches torch.nn.functional.normalize: x / max(||x||, eps)."""
    norm = jnp.linalg.norm(x, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, eps)


def infonce_logits(
    q: jax.Array,  # (N, C) L2-normalized queries
    k: jax.Array,  # (N, C) L2-normalized positive keys (stop-gradient'd by caller)
    queue: jax.Array,  # (K, C) negative keys
    temperature: float,
) -> tuple[jax.Array, jax.Array]:
    """Returns ((N, 1+K) logits, (N,) int labels == 0).

    The positive is column 0, negatives follow — exactly the reference's
    `cat([l_pos, l_neg], dim=1)` layout, so label vectors and the top-k
    proxy metric are directly comparable.
    """
    k = jax.lax.stop_gradient(k)
    queue = jax.lax.stop_gradient(queue)
    l_pos = jnp.einsum("nc,nc->n", q, k)[:, None]
    l_neg = jnp.einsum("nc,kc->nk", q, queue)
    logits = jnp.concatenate([l_pos, l_neg], axis=1) / temperature
    labels = jnp.zeros(q.shape[0], dtype=jnp.int32)
    return logits, labels


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy with integer labels (stable log-softmax)."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - true_logit)


def topk_accuracy(logits: jax.Array, labels: jax.Array, ks=(1, 5)) -> dict[str, jax.Array]:
    """Top-k accuracy in percent, as the reference's `accuracy()` reports."""
    max_k = max(ks)
    _, top_idx = jax.lax.top_k(logits, max_k)  # (N, max_k)
    correct = top_idx == labels[:, None]
    return {f"acc{k}": 100.0 * jnp.mean(jnp.any(correct[:, :k], axis=1)) for k in ks}
