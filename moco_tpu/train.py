"""Pretraining driver — the TPU-native `main_moco.py`.

Reference call stack (SURVEY.md §3.1): argparse → `mp.spawn` one process
per GPU → NCCL init → build MoCo → DDP wrap → SGD → per-epoch
`adjust_learning_rate` + `train()` + rank-0 checkpoint. Here the whole
process topology collapses into one SPMD program over a
`jax.sharding.Mesh`: no spawn, no rendezvous, no rank bookkeeping — the
mesh and the jitted `train_step` are the distribution model, the LR
schedule lives inside the optimizer, and Orbax handles multi-host
checkpointing.

Library entry: `train(config) -> final metrics`. CLI: repo-root
`train.py` (argparse mapping the reference's flags onto `TrainConfig`).
"""

from __future__ import annotations

import dataclasses
import math
import os
import signal
import threading
import time
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu import obs
from moco_tpu.core import (
    build_encoder,
    build_predictor,
    create_state,
    make_train_step,
    place_state,
    reshard_state,
    zero_stage23,
)
from moco_tpu.data.pipeline import TwoCropPipeline
from moco_tpu.obs import comms
from moco_tpu.obs.alerts import AlertEngine, FatalAlertError, parse_rules
from moco_tpu.obs.fleet import FleetAggregator, Heartbeat
from moco_tpu.obs.sinks import build_sinks, per_process_filename
from moco_tpu.obs.stepstats import StepTimeProbe, memory_payload, tree_shard_bytes
from moco_tpu.parallel.elastic import (
    RESCALE_EXIT_CODE,
    ElasticCoordinator,
    ElasticRescale,
    plan_rescale,
    surviving_devices,
)
from moco_tpu.parallel.zero import AsyncParamGather, unshard_tree_host
from moco_tpu.parallel import create_mesh, create_multislice_mesh, maybe_initialize_multihost
from moco_tpu.utils import faults, retry
from moco_tpu.utils.checkpoint import CheckpointManager
from moco_tpu.utils.config import (
    ResumeCompatError,
    TrainConfig,
    apply_auto_scale,
    config_to_dict,
    resume_compat_diff,
)
from moco_tpu.utils.metrics import (
    AverageMeter,
    ProfilerWindow,
    ProgressMeter,
    print0,
    profiler_trace,
)
from moco_tpu.utils.schedules import build_optimizer, make_lr_schedule
from moco_tpu.utils.watchdog import StepWatchdog


def train(
    config: TrainConfig,
    dataset=None,
    profile_dir: Optional[str] = None,
    knn_datasets=None,
    profile_steps: Optional[tuple] = None,
) -> dict:
    """Run the full pretraining loop; returns the last epoch's mean metrics.

    `dataset` overrides the config-built dataset (tests inject synthetic
    data of a chosen size this way). `knn_datasets` is an optional
    (bank_dataset, test_dataset) pair for the periodic kNN monitor
    (config.knn_every_epochs); when None it is built from config.data.
    `profile_steps=(a, b)` captures a jax.profiler trace of exactly
    global steps [a, b) into `profile_dir` (or `workdir/profile`)
    instead of the whole-run trace a bare `profile_dir` records.
    """
    # Partitionable threefry, matching tests/conftest.py. With the
    # default threefry, GSPMD materializes replicated random bits via
    # cross-device collectives; those ride in data-INDEPENDENT programs
    # (the device-side augment) that are in flight concurrently with
    # the step chain — and XLA:CPU launches programs on input-readiness,
    # so two independent collective programs can interleave in different
    # per-device orders and deadlock the rendezvous (observed as a
    # first-step wedge on the 8-virtual-device mesh once ZeRO-2/3's
    # gather program joined the flight). Partitionable threefry shards
    # the bit generation instead: no collectives, no race — and it is
    # the setting the entire test suite already runs under.
    jax.config.update("jax_threefry_partitionable", True)
    # Deterministic fault injection (chaos harness): MOCO_FAULTS installs
    # a fresh plan per run; unset leaves any programmatic plan (tests)
    # alone. Zero-cost when no plan is installed.
    faults.install_from_env()
    # Multi-host rendezvous BEFORE the first backend query (the
    # reference's dist.init_process_group; auto-detected from the
    # coordinator env, or forced with MOCO_MULTIHOST=1) — the tracer
    # below needs the process index, and reading it any earlier would
    # initialize a single-process backend.
    maybe_initialize_multihost()
    pidx = jax.process_index()
    # Telemetry (moco_tpu/obs): the span tracer is installed process-wide
    # for the run's duration, so the data pipeline's decode spans, the
    # checkpoint I/O spans, and the kNN-eval spans all land in one trace.
    # Spans stream to trace_events.jsonl (crash-safe tail; per-process
    # filenames when processes share a workdir — scripts/trace_merge.py
    # stitches them into one Perfetto file with a track per host) and
    # export as a Chrome trace on exit.
    tracer = obs.Tracer(
        os.path.join(
            config.workdir, per_process_filename("trace_events.jsonl", pidx)
        ),
        process_index=pidx,
    )
    prev_tracer = obs.set_tracer(tracer)
    try:
        # Elastic outer loop (parallel/elastic.py): each _train_impl
        # attempt runs on one mesh shape; an ElasticRescale (heartbeat
        # loss -> consensus -> emergency checkpoint, raised from the
        # log-step elastic check) shrinks the world and re-enters the
        # setup IN-PROCESS — the resume machinery restores the emergency
        # checkpoint and reshards it onto the surviving mesh
        # (reshard_state), so nothing restarts from scratch.
        ref_config = config
        if ref_config.elastic and not ref_config.auto_scale:
            # anchor the scaling rules at the pre-loss batch, so a
            # rescale derives kappa against the original recipe rather
            # than drifting hyperparameters silently
            ref_config = dataclasses.replace(
                ref_config, auto_scale=f"ref_batch={ref_config.data.global_batch}"
            )
        dead_hosts: set = set()
        while True:
            try:
                return _train_impl(
                    ref_config, dataset, profile_dir, knn_datasets, profile_steps,
                    dead_hosts=frozenset(dead_hosts),
                )
            except ElasticRescale as r:
                if jax.process_count() > 1:
                    # a real multi-process fleet cannot shrink the JAX
                    # distributed runtime in-process: the emergency
                    # checkpoint is durable and the plan is agreed —
                    # exit with the rescale code so the launcher
                    # relaunches the survivors with the derived shape
                    # (the resume then reshards onto it).
                    print0(
                        f"elastic rescale (multi-process): {r}; exiting "
                        f"{RESCALE_EXIT_CODE} for the launcher to relaunch "
                        f"with --num-data {r.plan.new_num_data} "
                        f"--batch-size {r.plan.new_global_batch}"
                    )
                    raise SystemExit(RESCALE_EXIT_CODE) from r
                dead_hosts |= set(r.plan.dead_hosts)
                ref_config = r.new_config
                print0(f"{r} — resuming in-process on the surviving mesh")
    finally:
        try:
            tracer.export_chrome(
                os.path.join(config.workdir, per_process_filename("trace.json", pidx))
            )
        except Exception as e:  # telemetry must never mask the real error
            print(f"WARNING: chrome trace export failed: {e!r}", flush=True)
        obs.set_tracer(prev_tracer)
        tracer.close()


def _train_impl(
    config: TrainConfig,
    dataset,
    profile_dir: Optional[str],
    knn_datasets,
    profile_steps: Optional[tuple],
    dead_hosts: frozenset = frozenset(),
) -> dict:
    # (the multi-host rendezvous already ran in train(), before the
    # tracer needed the process index; this is a no-op then, and keeps
    # direct _train_impl callers working)
    maybe_initialize_multihost()
    # Auto-scale (utils/config.py): `config` arrives carrying REFERENCE
    # hyperparameters; the live lr / EMA momentum are derived here from
    # the actual global batch (kappa = batch/ref_batch: lr linear,
    # momentum m^kappa). The reference config is kept for the elastic
    # rescale, which must re-derive against the same anchor.
    ref_config = config
    config, auto_info = apply_auto_scale(config)
    if auto_info is not None:
        print0(
            f"auto-scale: global batch {config.data.global_batch} vs ref "
            f"{auto_info['ref_batch']} (kappa={auto_info['kappa']:g}) -> "
            f"lr {auto_info['lr']:g}, EMA momentum {auto_info['momentum']:g}"
        )
    if config.elastic and config.parallel.num_model > 1:
        raise ValueError("elastic=True supports num_model=1 meshes only")
    if dead_hosts:
        # post-rescale attempt: the mesh covers the SURVIVING devices
        # only (the agreed width; feasibility was decided by the plan)
        mesh = create_mesh(
            num_data=config.parallel.num_data,
            num_model=config.parallel.num_model,
            devices=surviving_devices(dead_hosts),
        )
    elif config.parallel.num_data is None:
        # slice-aware layout: on multi-slice deployments the data axis
        # orders ICI-adjacent chips together so grad psum rides ICI first
        mesh = create_multislice_mesh(num_model=config.parallel.num_model)
    else:
        mesh = create_mesh(
            num_data=config.parallel.num_data, num_model=config.parallel.num_model
        )
    num_data = mesh.shape["data"]

    pipeline = TwoCropPipeline(config.data, mesh, seed=config.seed, dataset=dataset)
    steps_per_epoch = config.steps_per_epoch or pipeline.steps_per_epoch
    if steps_per_epoch <= 0:
        raise ValueError("empty pipeline: fewer examples than one global batch")

    encoder = build_encoder(config.moco, num_data=num_data)
    predictor = build_predictor(config.moco, num_data=num_data)
    tx = build_optimizer(config.optim, steps_per_epoch=steps_per_epoch)
    lr_schedule = make_lr_schedule(config.optim, steps_per_epoch)

    rng = jax.random.PRNGKey(config.seed)
    init_rng, shuffle_rng = jax.random.split(rng)
    sample = jnp.zeros((1, config.data.image_size, config.data.image_size, 3), jnp.float32)
    zero = config.parallel.shard_weight_update
    zero23 = zero_stage23(config)
    state = create_state(
        init_rng, config, encoder, tx, sample, predictor=predictor,
        zero_num_data=num_data if zero else None,
    )

    # Checkpoint ids are the GLOBAL STEP (unique and monotonic even for
    # mid-epoch preemption saves); the epoch lives in extras. Save
    # frequency is gated here in the driver, not by Orbax's policy.
    ckpt = CheckpointManager(
        config.workdir, keep=config.checkpoint_keep, save_interval=1,
        async_save=config.checkpoint_async,
    )

    def emergency_save(s, completed_epoch: int, reason: str, extra_fields=None) -> None:
        """The shared save-first-die-second path: the watchdog stall,
        the fatal-alert abort, the graceful-preemption (SIGTERM) exit,
        and the elastic rescale all funnel through here — one durable
        mid-epoch checkpoint with the standard resume extras plus the
        exit reason. Skips (not re-saves) a step that is already
        durable; always blocks until the write lands."""
        if int(s.step) in ckpt.all_steps():
            print(
                f"{reason}: step {int(s.step)} already durable, "
                "skipping emergency save", flush=True,
            )
            return
        extra = {
            "epoch": completed_epoch,
            "config": config_to_dict(config),
            "num_data": num_data,
            "emergency": True,
            "reason": reason,
        }
        if extra_fields:
            extra.update(extra_fields)
        ckpt.save(int(s.step), s, extra=extra, force=True)
        ckpt.wait()

    start_epoch = 0
    if ckpt.latest_step() is not None:  # --resume semantics, automatic

        def _check_compat(extra: dict) -> None:
            # fail fast with a readable diff BEFORE the state restore: a
            # shape-mismatched restore would otherwise read as corruption
            # (and quarantine a perfectly good checkpoint)
            diffs = resume_compat_diff(extra, config, num_data)
            if diffs:
                raise ResumeCompatError(
                    f"checkpoint under {config.workdir} is incompatible with the "
                    "live config:\n  " + "\n  ".join(diffs)
                )

        # Layout-aware restore: the ZeRO layout fields
        # (shard_weight_update / zero_stage / the ZeRO mesh width) are
        # "compatible but resharded", not incompatibilities — a
        # checkpoint in a different layout restores into a template of
        # ITS OWN layout, then converts host-side (reshard_state).
        def _layout(z, stage, n):
            return (bool(z), bool(z) and int(stage) >= 2, int(n) if z else 0)

        saved_extra = ckpt.read_extra()
        saved_par = (saved_extra.get("config") or {}).get("parallel") or {}
        saved_zero = bool(saved_par.get("shard_weight_update", zero))
        # pre-zero_stage checkpoints with a recorded config were stage-1
        # by definition; a checkpoint with NO recorded config at all is
        # assumed to match the live layout (the old behavior)
        saved_stage = int(
            saved_par.get(
                "zero_stage",
                1 if "shard_weight_update" in saved_par else config.parallel.zero_stage,
            )
        )
        saved_n = int(saved_extra.get("num_data") or num_data)
        live_layout = _layout(zero, config.parallel.zero_stage, num_data)
        saved_layout = _layout(saved_zero, saved_stage, saved_n)
        if saved_layout != live_layout:
            saved_cfg = dataclasses.replace(
                config,
                parallel=dataclasses.replace(
                    config.parallel,
                    shard_weight_update=saved_zero,
                    zero_stage=saved_stage,
                ),
            )
            saved_template = create_state(  # mocolint: disable=JX003  (restore TEMPLATE: values are overwritten by the checkpoint read, only shapes matter — key reuse is deliberate)
                init_rng, saved_cfg, encoder, tx, sample, predictor=predictor,
                zero_num_data=saved_n if saved_zero else None,
            )
            restored, extra = ckpt.restore(saved_template, validate_extra=_check_compat)
            full_cfg = dataclasses.replace(
                config,
                parallel=dataclasses.replace(
                    config.parallel, shard_weight_update=False
                ),
            )
            full_template = create_state(  # mocolint: disable=JX003  (shape-only template for reshard_state — deliberate key reuse, values never train)
                init_rng, full_cfg, encoder, tx, sample, predictor=predictor
            )
            state = reshard_state(restored, state, full_template)
            print0(
                "resume reshard: checkpoint ZeRO layout "
                f"{saved_layout} -> live {live_layout}"
            )
        else:
            # a corrupt newest checkpoint is quarantined and the next-older
            # step restores instead (fault-tolerance layer)
            state, extra = ckpt.restore(state, validate_extra=_check_compat)
        start_epoch = int(extra.get("epoch", 0)) + 1
        print0(f"resumed from epoch {start_epoch - 1} (step {int(state.step)})")

    shard_q = config.parallel.num_model > 1 and config.moco.num_negatives > 0
    step_fn = make_train_step(
        config,
        encoder,
        tx,
        mesh,
        shard_queue_over_model=shard_q,
        predictor=predictor,
        total_steps=config.optim.epochs * steps_per_epoch,
        state_template=state if zero else None,
    )
    state = place_state(
        state, mesh, shard_queue_over_model=shard_q, zero=zero, zero_params=zero23
    )
    root_rng = jax.device_put(
        shuffle_rng, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    )
    # Analytic at-rest state footprint per device (constant for the run:
    # layout is static) — the ZeRO stages' memory A/B gauge, available
    # on every backend including CPU meshes where memory_stats is not.
    hbm_state_bytes = tree_shard_bytes(state)
    # Analytic PEAK model-param footprint per device (shards + the
    # transient gathered full params): whole-tree for plain zero23, the
    # largest adjacent group pair under layer-granular gathering — the
    # gauge that proves the per-layer schedule's memory claim on hosts
    # without memory_stats. None outside zero23.
    hbm_model_peak_bytes = getattr(step_fn, "hbm_model_peak_bytes", None)
    zero_layer = zero23 and config.parallel.zero_layer_granular

    # Strict tracing (mocolint runtime arm): tracer-leak checking plus a
    # compile-cache-miss counter over the jitted step, read only on log
    # steps. The guard turns a silent recompile loop (minutes per compile
    # on TPU) into a fast, diagnosable abort.
    compile_monitor = None
    recompile_guard = None
    if config.strict_tracing:
        from moco_tpu.analysis.runtime import (
            CompileMonitor,
            RecompileError,
            RecompileGuard,
            enable_strict_tracing,
        )

        enable_strict_tracing()
        compile_monitor = CompileMonitor(step_fn)
        recompile_guard = RecompileGuard(config.recompile_warmup_steps)

    # Collective-schedule sanitizer (mocolint runtime arm, analysis/
    # sanitizer.py): installed BEFORE the first step traces so every
    # comms.tag site lands in the recorder; the cross-process check
    # piggybacks on log steps and aborts with a per-site diff before a
    # schedule mismatch can deadlock the pod.
    schedule_sanitizer = None
    _prev_recorder = None
    if config.sanitize_collectives:
        from moco_tpu.analysis.sanitizer import ScheduleSanitizer, install_recorder

        schedule_sanitizer = ScheduleSanitizer(
            config.workdir,
            process_index=jax.process_index(),
            num_processes=jax.process_count(),
        )
        _prev_recorder = install_recorder(schedule_sanitizer.recorder)

    # Lock-order sanitizer (mocolint v3 runtime arm, analysis/tsan.py):
    # every tsan-factory lock reports acquisition order; a cycle aborts
    # with both stacks (strict — the ScheduleDivergenceError posture)
    # before a lock inversion can wedge the process, and the run report
    # (lock_order.json) lands next to the schedule files on close.
    thread_sanitizer = None
    if config.sanitize_threads:
        from moco_tpu.analysis.tsan import ThreadSanitizer

        thread_sanitizer = ThreadSanitizer(
            workdir=config.workdir, strict=True, profile=True
        )

    # Graceful preemption (TPU VMs are frequently preemptible, typically
    # with a ~30 s SIGTERM grace window): the flag is checked inside the
    # STEP loop, so the save happens within seconds, not at the end of a
    # multi-minute epoch. A second SIGINT raises KeyboardInterrupt so
    # Ctrl-C can always actually stop the process. The reference's
    # failure story is "NCCL hangs, restart by hand with --resume"
    # (SURVEY.md §5.3).
    preempted = {"count": 0}

    def _handle(signum, frame):
        preempted["count"] += 1
        if signum == signal.SIGINT and preempted["count"] > 1:
            raise KeyboardInterrupt
        print0(f"signal {signum}: checkpointing at the next step, then exiting")

    prev_handlers = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev_handlers[sig] = signal.signal(sig, _handle)
        except ValueError:  # not the main thread (tests)
            pass

    # kNN monitor setup (config.knn_every_epochs > 0): frozen-backbone
    # weighted kNN as the cheap probe proxy (moco_tpu/knn.py docstring).
    knn_pair = knn_datasets
    if config.knn_every_epochs and knn_pair is None:
        from moco_tpu.data.datasets import build_dataset

        # same cache as the train pipeline: without it every monitor
        # epoch would re-decode the full dataset through the JPEG path
        knn_pair = (
            build_dataset(
                config.data.dataset, config.data.data_dir, config.data.image_size,
                train=True, num_workers=config.data.num_workers,
                cache_dir=config.data.cache_dir,
            ),
            build_dataset(
                config.data.dataset, config.data.data_dir, config.data.image_size,
                train=False, num_workers=config.data.num_workers,
                cache_dir=config.data.cache_dir,
            ),
        )

    # num_classes once at setup: every in-repo dataset exposes it; for a
    # foreign injected dataset prefer a decode-free label source and only
    # as a last resort scan ALL labels via load() (a first-N scan would
    # under-count on class-sorted layouts like ImageFolder and silently
    # zero out the one_hot votes for the missed classes).
    knn_num_classes = None
    if config.knn_every_epochs and knn_pair is not None:
        bank = knn_pair[0]
        knn_num_classes = getattr(bank, "num_classes", None)
        if knn_num_classes is None:
            labels = getattr(bank, "labels", None)
            if labels is None and getattr(bank, "samples", None) is not None:
                labels = [l for _, l in bank.samples]
            if labels is None:
                labels = [bank.load(i)[1] for i in range(len(bank))]
            knn_num_classes = int(np.max(np.asarray(labels)) + 1)

    def run_knn(epoch: int) -> Optional[float]:
        if not (config.knn_every_epochs and knn_pair):
            return None
        last = epoch == config.optim.epochs - 1
        if epoch % config.knn_every_epochs and not last:
            return None
        from moco_tpu.knn import knn_eval

        bank, test = knn_pair
        num_classes = knn_num_classes
        # ZeRO-2/3: params persist as (n, m) shards — one-shot host
        # gather back to full shapes for the eval-side forward
        params_q = state.params_q
        if zero23:
            params_q = unshard_tree_host(params_q, step_fn.param_shapes["enc"])
        top1 = knn_eval(
            encoder.backbone,
            params_q["backbone"],
            state.batch_stats_q.get("backbone", {}),
            bank,
            test,
            num_classes=num_classes,
            k=min(config.knn_k, len(bank)),
            temperature=config.knn_temperature,
            image_size=config.data.image_size,
            mesh=mesh,  # extraction data-parallel over the mesh
        )
        print0(f"Epoch [{epoch}] kNN top-1: {top1:.2f}%")
        return top1

    # Sink fan-out (obs/sinks.py): metrics.jsonl always (primary; file
    # sinks get per-process names when processes share a workdir), plus
    # whatever config.sinks names; metrics_port>0 additionally serves
    # Prometheus text format on /metrics for scraping long runs (port
    # shifted by the process index so co-hosted processes don't collide).
    pidx = jax.process_index()
    writer = build_sinks(
        config.sinks,
        config.workdir,
        metrics_port=config.metrics_port,
        metrics_host=config.metrics_host,
        process_index=pidx,
    )
    if writer.prometheus is not None:
        # the ACTUAL bound address (derived port, configured host), not
        # the requested one — what a scraper must be pointed at
        print(
            f"[p{pidx}] metrics endpoint: "
            f"http://{writer.prometheus.host}:{writer.prometheus.port}/metrics",
            flush=True,
        )
    # Fleet observability (obs/fleet.py): per-host stats vector gathered
    # across processes on log steps (jitted all_gather over a one-device-
    # per-host mesh); process 0's lines carry the fleet reduction. The
    # heartbeat file is the out-of-band liveness signal obs_report and
    # trace_merge fall back to when a host dies mid-run. The comms
    # ledger is reset here so this run's metrics reflect this run's
    # traced collectives only.
    comms.reset()
    # ZeRO-2/3: hoist the bucketed params all_gather for step k+1 under
    # step k — the driver enqueues it right after step k's dispatch
    # (async; dispatch must stay on THIS thread, see AsyncParamGather's
    # concurrent-Execute deadlock note) and the worker absorbs
    # gather-side stalls; the overlap/zero gauge on every metrics line
    # is the proof. zero_overlap_gather=False keeps the inline schedule.
    # This initial submit TRACES the gather, so it must come AFTER the
    # comms.reset() above or the per-bucket ledger sites would be wiped
    # (tags fire at trace time only).
    gatherer: Optional[AsyncParamGather] = None
    if zero23 and config.parallel.zero_overlap_gather:
        gatherer = AsyncParamGather(step_fn.gather)
        gatherer.submit(state, int(state.step))
    fleet = FleetAggregator() if config.fleet_metrics else None
    heartbeat = Heartbeat(
        config.workdir, process_index=pidx,
        trace_wall_t0=getattr(obs.get_tracer(), "wall_t0", None),
    )
    heartbeat.beat(step=int(state.step), epoch=start_epoch)
    # Alerting engine (obs/alerts.py): declarative rules evaluated
    # against every logged payload; fired alerts land in alerts.jsonl +
    # an in-band event line (Prometheus per-rule gauge rides it).
    engine = (
        AlertEngine(
            parse_rules(config.alert_rules, heartbeat_timeout=config.heartbeat_timeout),
            workdir=config.workdir,
            process_index=pidx,
        )
        if config.alert_rules and config.alert_rules != "none"
        else None
    )
    # Elastic loop trigger (parallel/elastic.py): heartbeat-staleness
    # detection + the rescale-consensus barrier, checked on log steps.
    # Already-rescaled-away hosts are known_dead — their stale files
    # stay in the workdir (obs_report's merged heartbeat table names
    # them) and must not re-trigger.
    elastic_coord: Optional[ElasticCoordinator] = None
    if config.elastic:
        elastic_coord = ElasticCoordinator(
            config.workdir,
            process_index=pidx,
            num_processes=jax.process_count(),
            timeout=config.heartbeat_timeout,
            known_dead=dead_hosts,
        )

    def handle_alerts(gstep: int, epoch: int, fired: list) -> None:
        """Write in-band alert event lines; under --alerts-fatal, make
        an emergency checkpoint durable and abort."""
        if not fired:
            return
        for a in fired:
            print0(
                f"ALERT [{a['severity']}] {a['rule']} @ step {gstep}: {a['message']}",
                flush=True,
            )
            writer.write(
                gstep,
                {"epoch": epoch, "event": "alert", "alert": a["rule"],
                 "severity": a["severity"], f"alert/{a['rule']}": 1},
            )
        writer.fsync()
        if config.alerts_fatal:
            # with elastic on, heartbeat loss is HANDLED (checkpoint +
            # rescale), not fatal: the abort would preempt the rescale
            # the same observation is about to trigger
            fatal = [
                a for a in fired
                if not (config.elastic and a.get("kind") == "heartbeat")
            ]
            if not fatal:
                return
            # emergency checkpoint of the last known-finite state (the
            # fault-tolerance layer's save-first-die-second path)
            emergency_save(
                guard["good_state"], epoch - 1,  # mid-epoch semantics (see watchdog)
                "alert", {"alert": fatal[0]["rule"]},
            )
            raise FatalAlertError(
                f"aborting on fired alert(s) {[a['rule'] for a in fatal]} at step "
                f"{gstep} (--alerts-fatal); emergency checkpoint saved — see "
                f"{engine.path} and {writer.path}"
            )

    def elastic_rescale(gstep: int, epoch: int, dead_now: list) -> None:
        """The elastic loop's commit point: agree on the event with the
        surviving peers, make the emergency checkpoint durable, emit the
        schema'd rescale event line, then raise ElasticRescale for the
        outer loop to rebuild the world on the surviving mesh."""
        all_dead = sorted(set(dead_hosts) | set(dead_now))
        plan, new_ref, info = plan_rescale(
            ref_config, num_data, config.parallel.num_model, all_dead, gstep
        )
        print0(
            f"elastic: hosts {dead_now} lost heartbeat (> "
            f"{config.heartbeat_timeout:g}s stale) at step {gstep}; proposing "
            f"mesh {plan.old_num_data} -> {plan.new_num_data}"
        )
        plan = elastic_coord.agree(plan)
        rescale_extra = {**plan.consensus_key(), "step": plan.step}
        for k in ("kappa", "lr", "momentum"):
            if k in info:
                rescale_extra[k] = float(info[k])
        emergency_save(
            guard["good_state"], epoch - 1,  # mid-epoch: redo this epoch
            "rescale", {"rescale": rescale_extra},
        )
        line = {
            "epoch": epoch,
            "event": "rescale",
            "rescale/dead_hosts": list(plan.dead_hosts),
            "rescale/old_num_data": plan.old_num_data,
            "rescale/new_num_data": plan.new_num_data,
            "rescale/old_global_batch": plan.old_global_batch,
            "rescale/new_global_batch": plan.new_global_batch,
        }
        for k in ("kappa", "lr", "momentum"):
            if k in info:
                line[f"rescale/{k}"] = float(info[k])
        writer.write(gstep, line)
        writer.fsync()  # the rescale must leave its event on disk
        raise ElasticRescale(plan, new_ref, info)
    # Step-time breakdown probe + windowed profiler (obs/stepstats.py,
    # utils/metrics.py): both keyed on the host-side global step counter.
    probe = StepTimeProbe(config.obs_probe_every)
    profile_window: Optional[ProfilerWindow] = None
    if profile_steps is not None:
        profile_window = ProfilerWindow(
            profile_dir or os.path.join(config.workdir, "profile"), *profile_steps
        )
        profile_dir = None  # windowed capture replaces the whole-run trace
    last_avg: dict = {}

    # -- runtime guards (fault-tolerance layer) --------------------------
    # `good_state` is the last state whose loss was observed finite (one
    # extra on-device state reference; refreshed on log steps only). The
    # NaN guard rolls back to it, and the watchdog's emergency save uses
    # it — a wedged device can't be asked for the in-flight state.
    guard = {"nan_steps": 0, "good_state": state, "epoch": start_epoch}
    wd: Optional[StepWatchdog] = None
    if config.watchdog_timeout > 0:

        def _emergency():
            # best-effort, bounded: the main thread is stuck in a device
            # call, and the save itself may hang on a wedged runtime — run
            # it in a sidecar thread and exit regardless after the budget.
            try:
                writer.write(
                    0, {"event": "stall", "epoch": guard["epoch"],
                        "watchdog_timeout": config.watchdog_timeout},
                )
                writer.fsync()
            except Exception:
                pass

            def _save():
                try:
                    # mid-epoch semantics, like the preemption path: the
                    # current epoch is NOT complete, resume redoes it
                    # from the start
                    emergency_save(guard["good_state"], guard["epoch"] - 1, "stall")
                    print("watchdog: emergency checkpoint saved", flush=True)
                except Exception as e:
                    print(f"watchdog: emergency checkpoint failed: {e!r}", flush=True)

            t = threading.Thread(target=_save, daemon=True)
            t.start()
            t.join(timeout=max(30.0, config.watchdog_timeout))

        wd = StepWatchdog(
            config.watchdog_timeout,
            on_stall=_emergency,
            dump_path=os.path.join(config.workdir, "stall_stacks.txt"),
        ).start()

    # Host-side mirror of the global step (one sync here, none per
    # step): drives the profiler window, the probe's sampling schedule,
    # and the log lines — step_fn advances state.step once per dispatch
    # (even on NaN rollback), so the mirror never drifts.
    gstep_host = int(state.step)
    # Software-pipelined step loop (ISSUE 5 tentpole): step k is
    # dispatched against an already device-resident batch while the
    # prefetch ring transfers k+1 and the host decodes k+2. Two loop
    # mechanics make the overlap real:
    # - bounded in-flight window: after each dispatch the loop blocks on
    #   the metrics of the step `prefetch_depth` dispatches BACK (ready
    #   or nearly so in steady state) — backpressure without ever
    #   draining the device queue;
    # - deferred log fetch: a log step's device_get runs one iteration
    #   LATER, after the next step is already queued behind it, so a log
    #   boundary no longer idles the device. Consequence: a non-finite
    #   loss is detected one step late and the rollback also discards
    #   the single in-flight update computed from the poisoned state —
    #   same counters, one extra discarded step.
    pipeline_depth = max(int(config.prefetch_depth), 1)
    try:
        with profiler_trace(profile_dir):
            for epoch in range(start_epoch, config.optim.epochs):
              with obs.span("epoch", epoch=epoch):
                batch_time = AverageMeter("Time", ":6.3f")
                data_time = AverageMeter("Data", ":6.3f")
                losses = AverageMeter("Loss", ":.4e")
                top1 = AverageMeter("Acc@1", ":6.2f")
                top5 = AverageMeter("Acc@5", ":6.2f")
                progress = ProgressMeter(
                    steps_per_epoch,
                    [batch_time, data_time, losses, top1, top5],
                    prefix=f"Epoch: [{epoch}]",
                )
                guard["epoch"] = epoch
                it = iter(pipeline.epoch(
                    epoch,
                    device=config.device_prefetch,
                    depth=config.prefetch_depth,
                    donate=config.prefetch_donate,
                ))
                ring_stats = getattr(it, "stats_payload", None)
                # wall anchor for the smoothed per-step time: t_step on a
                # logged line is (wall since the previous logged flush) /
                # (steps since it) — the sustained rate, which under the
                # pipelined loop is the meaningful number (per-iteration
                # host wall is just dispatch, ~ms)
                flush_anchor = {"wall": time.perf_counter(), "gstep": gstep_host}
                stop_now = False
                pending: Optional[dict] = None
                inflight: deque = deque()

                def flush_log(p: dict) -> None:
                    """Deferred log-step processing: ONE batched
                    device_get for the whole metrics tree (the old
                    per-field float() forced a blocking transfer per
                    metric), then every runtime guard piggybacks on the
                    fetch — NaN guard, chaos hooks, alert engine,
                    recompile guard, fleet gather, heartbeat."""
                    nonlocal state
                    i, gstep = p["i"], p["gstep"]
                    fetched = jax.device_get(p["metrics"])
                    m = {
                        k: (float(v) if getattr(v, "ndim", 1) == 0 else v)
                        for k, v in fetched.items()
                    }
                    if faults.enabled():  # chaos harness hooks
                        m["loss"] = faults.corrupt_loss(m["loss"], gstep)
                        faults.maybe_stall(gstep)
                        faults.maybe_preempt(gstep)
                        # kill@host: sudden host death (exit in a real
                        # fleet; a stale simulated heartbeat on the
                        # fake-fleet mesh — the elastic chaos harness)
                        faults.maybe_kill_host(
                            gstep, config.workdir, pidx, jax.process_count()
                        )
                    if not math.isfinite(m["loss"]):
                        # non-finite-loss guard: skip the poisoned
                        # update (params/opt/queue roll back to the
                        # last finite log step; the step counter keeps
                        # advancing so checkpoint ids stay monotonic
                        # and fault-free/faulted runs agree on step
                        # counts), count it, abort past the threshold.
                        guard["nan_steps"] += 1
                        writer.write(
                            gstep,
                            {"epoch": epoch, "event": "nonfinite_loss",
                             "nan_steps": guard["nan_steps"]},
                        )
                        writer.fsync()
                        if engine is not None:
                            handle_alerts(
                                gstep, epoch,
                                engine.observe(
                                    gstep,
                                    {"event": "nonfinite_loss",
                                     "nan_steps": guard["nan_steps"]},
                                ),
                            )
                        print0(
                            f"WARNING: non-finite loss at step {gstep} "
                            f"({guard['nan_steps']}/{config.nan_guard_threshold})"
                            " — update skipped",
                            flush=True,
                        )
                        if guard["nan_steps"] >= config.nan_guard_threshold:
                            raise FloatingPointError(
                                f"aborting: {guard['nan_steps']} non-finite "
                                f"loss steps (threshold "
                                f"{config.nan_guard_threshold}); last at step "
                                f"{gstep}, epoch {epoch}, lr "
                                f"{float(lr_schedule(gstep - 1)):.3e} — see "
                                f"{writer.path}"
                            )
                        state = guard["good_state"].replace(step=state.step)
                        inflight.clear()  # poisoned-lineage refs: drop them
                        if gatherer is not None:
                            # the in-flight gather belongs to the poisoned
                            # lineage — drop it and gather the rolled-back
                            # shards instead
                            gatherer.resubmit(state, gstep)
                        return
                    # p["state"] is the state AS OF this logged step —
                    # `state` itself may already be one dispatch ahead
                    guard["good_state"] = p["state"]
                    bs = config.data.global_batch
                    losses.update(m["loss"], bs)
                    top1.update(m["acc1"], bs)
                    top5.update(m["acc5"], bs)
                    now = time.perf_counter()
                    steps_since = max(gstep - flush_anchor["gstep"], 1)
                    t_step = (now - flush_anchor["wall"]) / steps_since
                    flush_anchor["wall"], flush_anchor["gstep"] = now, gstep
                    batch_time.update(t_step)
                    # re-pin the probe to THIS step's data wait: the next
                    # iteration's fetch already overwrote it before this
                    # deferred flush ran
                    probe.data_wait(p["t_data"])
                    probe.step_done(t_step)
                    progress.display(i)
                    wire = ring_stats() if ring_stats is not None else {}
                    payload = {
                        "epoch": epoch,
                        "lr": float(lr_schedule(gstep - 1)),
                        **m,
                        # step-time breakdown + device memory
                        # (obs): t_data/t_step always; dispatch/
                        # device split from the latest sampled
                        # step; hbm gauges null where the backend
                        # lacks memory_stats (CPU hosts)
                        **probe.payload(),
                        **memory_payload(),
                        # at-rest state footprint (analytic, per device)
                        "hbm_state_bytes": hbm_state_bytes,
                        # input wire (device prefetch ring): last
                        # batch's transfer time/bytes + live staged
                        # depth — absent on the sync path
                        **wire,
                        # ZeRO-2/3 hoisted-gather overlap efficiency —
                        # absent without the gather worker
                        **(gatherer.payload() if gatherer is not None else {}),
                        # layer-granular stage: mirror the gauge under its
                        # own key so dashboards can tell the per-group
                        # schedule apart from whole-tree gathering, and
                        # publish the analytic peak model footprint
                        **(
                            {"overlap/zero_layer": gatherer.last_overlap}
                            if zero_layer and gatherer is not None
                            else {}
                        ),
                        **(
                            {"hbm_model_peak_bytes": hbm_model_peak_bytes}
                            if hbm_model_peak_bytes is not None
                            else {}
                        ),
                    }
                    # fault-tolerance observability: only present
                    # when nonzero, so clean runs keep clean lines
                    if guard["nan_steps"]:
                        payload["nan_steps"] = guard["nan_steps"]
                    decode_failures = getattr(pipeline, "decode_failures", 0)
                    if decode_failures:
                        payload["decode_failures"] = decode_failures
                    io_retries = retry.snapshot()
                    if io_retries:
                        payload["io_retries"] = io_retries
                    if compile_monitor is not None:
                        # always present under --strict-tracing
                        # (not only-when-nonzero like the fault
                        # counters): dashboards watch it for
                        # FLATNESS, and absence would read as 0
                        misses = compile_monitor.misses()
                        payload["compile_cache_misses"] = misses
                    # comms ledger: analytic per-step wire bytes
                    # for every collective the step traced
                    # (obs/comms.py) — static values, no syncs
                    payload.update(comms.payload())
                    if schedule_sanitizer is not None:
                        # schedule hash on every line: dashboards watch
                        # it for FLATNESS (like compile_cache_misses)
                        payload.update(schedule_sanitizer.recorder.payload())
                    if fleet is not None:
                        # cross-host aggregation: EVERY process
                        # contributes its vector (this is a
                        # collective, keyed on the replicated
                        # log schedule so all hosts agree);
                        # process 0's line carries the fleet view
                        stats = fleet.gather(
                            fleet.host_vector(
                                t_data=payload.get("t_data"),
                                t_step=payload.get("t_step"),
                                t_transfer=wire.get("t_transfer"),
                                dispatch_lag=probe.last_dispatch,
                                io_retries=float(
                                    sum(io_retries.values())
                                ) if io_retries else 0.0,
                                decode_failures=float(decode_failures),
                                hbm_live=payload.get("hbm_live_bytes"),
                            )
                        )
                        if fleet.process_index == 0:
                            payload.update(fleet.payload(stats))
                    heartbeat.beat(step=gstep, epoch=epoch)
                    writer.write(gstep, payload)
                    if engine is not None:
                        handle_alerts(
                            gstep, epoch, engine.observe(gstep, payload)
                        )
                    if elastic_coord is not None:
                        # heartbeat-staleness check (off the hot path:
                        # log steps only, file reads). A newly lost host
                        # commits the rescale: consensus -> emergency
                        # checkpoint -> event line -> ElasticRescale.
                        dead_now = elastic_coord.stale_hosts()
                        if dead_now:
                            elastic_rescale(gstep, epoch, dead_now)
                    if schedule_sanitizer is not None:
                        # publish + cross-check AFTER the line is
                        # durable: a divergence abort must leave the
                        # metrics tail (and the hash) on disk
                        writer.fsync()
                        schedule_sanitizer.check(gstep)
                    if recompile_guard is not None:
                        diagnosis = recompile_guard.update(gstep, misses)
                        if diagnosis is not None:
                            writer.write(
                                gstep,
                                {"epoch": epoch,
                                 "event": "recompile_after_warmup",
                                 "compile_cache_misses": misses},
                            )
                            writer.fsync()
                            raise RecompileError(diagnosis)

                try:
                    for i in range(steps_per_epoch):
                        if profile_window is not None:
                            profile_window.on_step(gstep_host)
                        fetch0 = time.perf_counter()
                        with obs.span("data_wait", step=gstep_host):
                            batch = next(it, None)
                        if batch is None:
                            break
                        t_data = time.perf_counter() - fetch0
                        data_time.update(t_data)
                        probe.data_wait(t_data)
                        t_disp0 = time.perf_counter()
                        with obs.span("step", step=gstep_host):
                            if gatherer is not None:
                                # the gather for THIS step was issued one
                                # iteration ago and ran under the previous
                                # step; take() blocks only for what didn't
                                # fit under it (the overlap/zero gauge)
                                gathered = gatherer.take()
                                state, metrics = step_fn.step(
                                    state, gathered, batch, root_rng
                                )
                                gatherer.submit(state, gstep_host + 1)
                            else:
                                state, metrics = step_fn(state, batch, root_rng)
                        probe.dispatched(time.perf_counter() - t_disp0)
                        if probe.should_sample(gstep_host):
                            # drain the device queue ON SAMPLED STEPS ONLY,
                            # splitting host dispatch from device compute —
                            # every other step stays sync-free
                            with obs.span("device_wait", step=gstep_host):
                                t_dev0 = time.perf_counter()
                                jax.block_until_ready((state, metrics))
                            probe.device_block(time.perf_counter() - t_dev0)
                        gstep_host += 1
                        # bounded in-flight window: wait on the OLDEST
                        # dispatched step only — `pipeline_depth` newer
                        # steps stay queued on the device
                        inflight.append(metrics)
                        if len(inflight) > pipeline_depth:
                            jax.block_until_ready(inflight.popleft())
                        if wd is not None:
                            wd.beat()  # a timestamp assignment — no device sync
                        if pending is not None:
                            # the previous log step's metrics, fetched
                            # with this step already queued behind them
                            flush_log(pending)
                            pending = None
                        if preempted["count"]:
                            stop_now = True
                            break
                        if i % config.log_every == 0 or i == steps_per_epoch - 1:
                            pending = {
                                "i": i, "gstep": gstep_host,
                                "metrics": metrics, "state": state,
                                "t_data": t_data,
                            }
                    if pending is not None and not stop_now:
                        # the epoch's final log step has no successor
                        # iteration — flush it here
                        flush_log(pending)
                        pending = None
                finally:
                    # epoch teardown / preemption exit: release the
                    # prefetch producer + transfer ring (the PR-5
                    # producer-leak fix — an abandoned iterator used to
                    # block its daemon thread on q.put forever, pinning
                    # the decode pool)
                    closer = getattr(it, "close", None)
                    if closer is not None:
                        closer()
                last_avg = {
                    "epoch": epoch,
                    "loss": losses.avg,
                    "acc1": top1.avg,
                    "acc5": top5.avg,
                }
                if not stop_now:
                    knn_top1 = run_knn(epoch)
                    if knn_top1 is not None:
                        last_avg["knn_top1"] = knn_top1
                        writer.write(int(state.step), {"epoch": epoch, "knn_top1": knn_top1})
                # A mid-epoch preemption save records the PREVIOUS epoch
                # as completed, so resume redoes this partial epoch from
                # its start (same granularity the reference's per-epoch
                # checkpoints give a crash, but without losing the work
                # to a SIGKILL: the save happens within one step of the
                # signal, inside a preemption grace window).
                completed_epoch = epoch - 1 if stop_now else epoch
                if stop_now:
                    # Graceful preemption (SIGTERM — how preemptible VMs
                    # announce reclamation — or Ctrl-C): the same
                    # emergency-checkpoint path as the watchdog/alert/
                    # rescale exits (save first, durable before exit),
                    # plus an in-band event line naming the exit.
                    writer.write(gstep_host, {"epoch": epoch, "event": "preempt"})
                    emergency_save(state, completed_epoch, "preempt")
                    writer.fsync()  # the metrics tail must be durable too
                    print0(
                        f"preempted mid-epoch {epoch}: state saved at step "
                        f"{int(state.step)}; resume will redo epoch {epoch}"
                    )
                    break
                if (
                    epoch == config.optim.epochs - 1
                    or epoch % config.checkpoint_every_epochs == 0
                ):
                    ckpt.save(
                        int(state.step),
                        state,
                        extra={
                            "epoch": completed_epoch,
                            "config": config_to_dict(config),
                            # ZeRO opt-state leaves are (num_data, m):
                            # downstream template builders (lincls,
                            # convert_pretrain) need the TRAIN-time mesh
                            # width, which config alone may not pin
                            # (parallel.num_data=None = "all devices")
                            "num_data": num_data,
                        },
                    )
    finally:
        if gatherer is not None:
            gatherer.close()  # join the gather worker; drop a parked result
        if schedule_sanitizer is not None:
            from moco_tpu.analysis.sanitizer import install_recorder

            install_recorder(_prev_recorder)
        if thread_sanitizer is not None:
            thread_sanitizer.close()  # restores hooks, writes lock_order.json
        if profile_window is not None:
            profile_window.close()  # stop a still-open capture window
        if wd is not None:
            wd.stop()
        if engine is not None:
            engine.close()
        writer.close()
        ckpt.close()
        for sig, h in prev_handlers.items():
            signal.signal(sig, h)
    return last_avg
