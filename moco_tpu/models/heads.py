"""Projection / classifier heads.

The reference creates heads by surgery on the torchvision encoder's `fc`:
- v1: `base_encoder(num_classes=dim)` leaves a single Linear fc
  (`moco/builder.py:~L20`).
- v2 (`mlp=True`): `fc = Sequential(Linear(dim_mlp, dim_mlp), ReLU, fc)`
  (`moco/builder.py:~L25-30`).
- linear probe: fresh fc with weight~N(0, 0.01), bias=0
  (`main_lincls.py:~L160-165`).

Here heads are standalone modules composed with the backbone instead.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class ProjectionHead(nn.Module):
    """MoCo projection head: Linear (v1) or 2-layer MLP (v2)."""

    dim: int = 128
    mlp: bool = False
    hidden_dim: int | None = None  # defaults to input feature dim, as in v2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):  # train unused; BN-free head
        x = x.astype(self.dtype)
        if self.mlp:
            hidden = self.hidden_dim or x.shape[-1]
            x = nn.Dense(hidden, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.dim, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class V3MLPHead(nn.Module):
    """MoCo v3 projection/prediction MLP (arXiv:2104.02057 §4 / the
    follow-up `facebookresearch/moco-v3` repo's `build_mlp`): Dense→BN→ReLU
    per hidden layer, final Dense with bias-free output BN (no affine).
    `cross_replica_axis` makes the BN a SyncBN over the mesh's data axis
    (the paper trains with SyncBN in the heads).

    Layer counts / final-BN follow upstream `moco-v3`'s per-family
    builders (`moco/builder.py` `MoCo_ResNet`/`MoCo_ViT`
    `_build_projector_and_predictor_mlps`):
      - ViT:    projector = 3 layers, predictor = 2 layers, both ending
                in the affine-free output BN (`last_bn=True`);
      - ResNet: projector = 2 layers with output BN, predictor =
                2 layers WITHOUT the final BN (`last_bn=False`).
    """

    num_layers: int = 3
    hidden_dim: int = 4096
    dim: int = 256
    cross_replica_axis: str | None = None
    last_bn: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        norm = lambda **kw: nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.cross_replica_axis,
            **kw,
        )
        for _ in range(self.num_layers - 1):
            x = nn.Dense(self.hidden_dim, use_bias=False, dtype=self.dtype)(x)
            x = norm()(x)
            x = nn.relu(x)
        x = nn.Dense(self.dim, use_bias=False, dtype=self.dtype)(x)
        if self.last_bn:
            x = norm(use_bias=False, use_scale=False)(x)
        return x.astype(jnp.float32)


class LinearClassifier(nn.Module):
    """Linear-probe classifier with the reference's init
    (`main_lincls.py:~L160-165`: weight~N(0, 0.01), bias=0)."""

    num_classes: int = 1000

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            self.num_classes,
            kernel_init=nn.initializers.normal(stddev=0.01),
            bias_init=nn.initializers.zeros,
        )(x.astype(jnp.float32))
