"""Projection / classifier heads.

The reference creates heads by surgery on the torchvision encoder's `fc`:
- v1: `base_encoder(num_classes=dim)` leaves a single Linear fc
  (`moco/builder.py:~L20`).
- v2 (`mlp=True`): `fc = Sequential(Linear(dim_mlp, dim_mlp), ReLU, fc)`
  (`moco/builder.py:~L25-30`).
- linear probe: fresh fc with weight~N(0, 0.01), bias=0
  (`main_lincls.py:~L160-165`).

Here heads are standalone modules composed with the backbone instead.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class ProjectionHead(nn.Module):
    """MoCo projection head: Linear (v1) or 2-layer MLP (v2)."""

    dim: int = 128
    mlp: bool = False
    hidden_dim: int | None = None  # defaults to input feature dim, as in v2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        if self.mlp:
            hidden = self.hidden_dim or x.shape[-1]
            x = nn.Dense(hidden, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.dim, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class LinearClassifier(nn.Module):
    """Linear-probe classifier with the reference's init
    (`main_lincls.py:~L160-165`: weight~N(0, 0.01), bias=0)."""

    num_classes: int = 1000

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            self.num_classes,
            kernel_init=nn.initializers.normal(stddev=0.01),
            bias_init=nn.initializers.zeros,
        )(x.astype(jnp.float32))
