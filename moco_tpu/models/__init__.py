from moco_tpu.models.resnet import ARCHS, BasicBlock, Bottleneck, ResNet, create_resnet
from moco_tpu.models.heads import LinearClassifier, ProjectionHead

__all__ = [
    "ARCHS",
    "BasicBlock",
    "Bottleneck",
    "ResNet",
    "create_resnet",
    "LinearClassifier",
    "ProjectionHead",
]
