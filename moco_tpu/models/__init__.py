from moco_tpu.models.resnet import ARCHS, BasicBlock, Bottleneck, ResNet, create_resnet
from moco_tpu.models.heads import LinearClassifier, ProjectionHead, V3MLPHead
from moco_tpu.models.vit import VIT_ARCHS, VisionTransformer, create_vit, sincos_2d_posembed

__all__ = [
    "ARCHS",
    "BasicBlock",
    "Bottleneck",
    "ResNet",
    "create_resnet",
    "LinearClassifier",
    "ProjectionHead",
    "V3MLPHead",
    "VIT_ARCHS",
    "VisionTransformer",
    "create_vit",
    "sincos_2d_posembed",
]
