"""Vision Transformer backbone for MoCo v3.

The reference repo itself is CNN-only (SURVEY.md §5.7); MoCo v3
("An Empirical Study of Training Self-Supervised Vision Transformers",
arXiv:2104.02057, from the same authors' follow-up `facebookresearch/
moco-v3`) is the queue-free ViT variant named by BASELINE.json's config
list. TPU-first choices:
- fixed 2-D sin-cos position embedding (the v3 paper's choice — no
  learned posembed to shard or interpolate);
- optionally frozen random patch projection (v3's key stability trick:
  the patch-embed conv stays at init; handled by the train step masking
  its grads, `freeze_patch_embed` in the config);
- pre-LN blocks, GELU MLP, bf16 compute / fp32 params, static 197-token
  sequence — everything XLA wants: one fused attention matmul chain on
  the MXU, no dynamic shapes.

Attention defaults to plain `jnp.einsum` — at 197 tokens the whole
sequence fits in VMEM and XLA's fusion is already optimal. Setting
`use_flash_attention=True` swaps in the Pallas flash kernel
(`moco_tpu/ops/flash_attention`, which pads + masks ViT's prime 197 to
the block size) via flax's `attention_fn` hook — the parameter tree is
identical either way, so checkpoints are interchangeable between the
two paths. Worth it for the long-sequence regime (high-res/video
tokens); at 197 it is a correctness-exercised alternative, not a win.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from moco_tpu.parallel.compat import axis_size


def flash_attention_fn(query, key, value, **kwargs):
    """`nn.MultiHeadDotProductAttention`-compatible attention_fn backed
    by the Pallas flash kernel. Inputs arrive (B, S, H, Dh); the kernel
    wants (B, H, S, Dh). Ignores bias/mask/dropout (ViT uses none)."""
    from moco_tpu.ops.flash_attention import flash_attention

    q = query.transpose(0, 2, 1, 3)
    k = key.transpose(0, 2, 1, 3)
    v = value.transpose(0, 2, 1, 3)
    out = flash_attention(q, k, v, interpret=jax.default_backend() != "tpu")
    return out.transpose(0, 2, 1, 3)


def ring_attention_fn(axis_name: str):
    """attention_fn computing EXACT attention over a sequence sharded on
    `axis_name`: per-device flash attention against the visiting K/V
    shard, rotated around the ring with ppermute
    (`moco_tpu/parallel/ring_attention.py`). Must run inside `shard_map`
    with the token axis sharded on `axis_name`."""

    def fn(query, key, value, **kwargs):
        from moco_tpu.parallel.ring_attention import ring_attention

        q = query.transpose(0, 2, 1, 3)
        k = key.transpose(0, 2, 1, 3)
        v = value.transpose(0, 2, 1, 3)
        out = ring_attention(
            q, k, v, axis_name, interpret=jax.default_backend() != "tpu"
        )
        return out.transpose(0, 2, 1, 3)

    return fn


def sincos_2d_posembed(dim: int, grid: int, cls_token: bool = True) -> np.ndarray:
    """Fixed 2-D sin-cos position embedding, (1, grid²[+1], dim) fp32."""
    assert dim % 4 == 0, "sincos 2d posembed needs dim % 4 == 0"
    coords = np.arange(grid, dtype=np.float32)
    omega = 1.0 / (10000 ** (np.arange(dim // 4, dtype=np.float32) / (dim // 4)))
    out_h = np.einsum("i,j->ij", coords, omega)  # (grid, dim/4)
    emb_h = np.concatenate([np.sin(out_h), np.cos(out_h)], axis=1)  # (grid, dim/2)
    emb = np.concatenate(
        [
            np.repeat(emb_h[:, None, :], grid, axis=1),  # y
            np.repeat(emb_h[None, :, :], grid, axis=0),  # x
        ],
        axis=-1,
    ).reshape(grid * grid, dim)
    if cls_token:
        emb = np.concatenate([np.zeros((1, dim), np.float32), emb], axis=0)
    return emb[None]


class MlpBlock(nn.Module):
    mlp_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        x = nn.Dense(self.mlp_dim, dtype=self.dtype)(x)
        x = nn.gelu(x)
        return nn.Dense(d, dtype=self.dtype)(x)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.float32
    use_flash_attention: bool = False
    # explicit attention_fn override (e.g. ring_attention_fn for the
    # sequence-parallel path); takes precedence over use_flash_attention.
    # The parameter tree is identical for every attention implementation.
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=self.dtype)(x)
        fn = self.attention_fn or (flash_attention_fn if self.use_flash_attention else None)
        attn_kwargs = {"attention_fn": fn} if fn is not None else {}
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=self.dtype, deterministic=True, **attn_kwargs
        )(y, y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MlpBlock(mlp_dim=self.mlp_dim, dtype=self.dtype)(y)
        return x + y


class VisionTransformer(nn.Module):
    """ViT returning the final-LN pooled feature (pre-head), the
    interface shape `ResNet.__call__` has, so `MoCoEncoder` composes
    either backbone unchanged.

    `pool`: "cls" (v3 default) or "gap" (global average pool, the v3
    paper's ablated alternative — and the mode sequence parallelism
    requires, since a cls token cannot be sharded).

    `sequence_axis`: name of a mesh axis to shard the TOKEN dimension
    over. When the module is applied inside `shard_map` with that axis
    bound, each device patchifies the (replicated) image, keeps only its
    token shard, runs the blocks with ring attention (exact attention
    over the full sequence via ppermute rotation), and gap-pools with a
    psum. Applied OUTSIDE shard_map (init, kNN, export) the same module
    falls back to the dense single-device path — the parameter tree is
    identical, so one set of weights serves both."""

    patch_size: int = 16
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    image_size: int = 224
    dtype: jnp.dtype = jnp.float32
    use_flash_attention: bool = False
    pool: str = "cls"
    sequence_axis: Optional[str] = None

    @property
    def num_features(self) -> int:
        return self.hidden_dim

    @property
    def group_names(self) -> tuple:
        """Schedule-ordered layer groups for the layer-granular ZeRO-3
        apply: patch embedding (+cls token), one group per encoder
        block, and the final norm + pool."""
        return ("embed",) + tuple(f"block_{i}" for i in range(self.depth)) + ("final",)

    def group_param_names(self) -> dict:
        """group -> its top-level param-tree child names (all EXPLICIT
        flax names here, so the map is construction-order independent)."""
        names = {
            "embed": ("patch_embed", "cls_token") if self.pool == "cls" else ("patch_embed",),
            "final": ("final_norm",),
        }
        for i in range(self.depth):
            names[f"block_{i}"] = (f"block_{i}",)
        return names

    @nn.compact
    def __call__(self, x, train: bool = True, group: Optional[str] = None):
        if self.pool not in ("cls", "gap"):
            raise ValueError(f"pool={self.pool!r}: choose 'cls' or 'gap'")
        if group is not None and self.sequence_axis is not None:
            raise ValueError(
                "layer-group apply does not compose with sequence_axis "
                "(the token shard would cross group boundaries)"
            )

        def run_embed(x):
            b, h, w, _ = x.shape
            assert h % self.patch_size == 0 and w % self.patch_size == 0, (
                f"image {h}x{w} not divisible by patch {self.patch_size}"
            )
            grid = h // self.patch_size
            x = x.astype(self.dtype)
            # Patch embedding: conv stride=patch (the "random patch
            # projection" v3 freezes — freezing is the train step's job,
            # not the module's).
            x = nn.Conv(
                self.hidden_dim,
                (self.patch_size, self.patch_size),
                strides=self.patch_size,
                padding="VALID",
                name="patch_embed",
                dtype=self.dtype,
            )(x)
            x = x.reshape(b, grid * grid, self.hidden_dim)
            if self.pool == "cls":
                cls = self.param(
                    "cls_token", nn.initializers.normal(stddev=0.02), (1, 1, self.hidden_dim)
                )
                x = jnp.concatenate(
                    [jnp.broadcast_to(cls.astype(self.dtype), (b, 1, self.hidden_dim)), x],
                    axis=1,
                )
            pos = sincos_2d_posembed(self.hidden_dim, grid, cls_token=self.pool == "cls")
            return x + jnp.asarray(pos, self.dtype)

        def make_block(i, attn_fn):
            return EncoderBlock(
                num_heads=self.num_heads,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                use_flash_attention=self.use_flash_attention,
                attention_fn=attn_fn,
                name=f"block_{i}",
            )

        def run_final(x, seq_total, sp_rank):
            x = nn.LayerNorm(dtype=self.dtype, name="final_norm")(x)
            if self.pool == "cls":
                return x[:, 0].astype(jnp.float32)
            # gap: mean over ALL tokens (psum across the shard ring when SP)
            s = jnp.sum(x.astype(jnp.float32), axis=1)
            if sp_rank is not None:
                s = lax.psum(s, self.sequence_axis)
            return s / seq_total

        if group is not None:
            if group == "embed":
                return run_embed(x)
            if group == "final":
                return run_final(x, x.shape[1], None)
            if group.startswith("block_") and group[6:].isdigit():
                i = int(group[6:])
                if i < self.depth:
                    return make_block(i, None)(x)
            raise ValueError(f"unknown layer group {group!r}")

        x = run_embed(x)
        # Sequence parallelism: bind to the axis if we are inside a
        # shard_map that names it; otherwise (init / single-device eval)
        # run dense. axis_index raises NameError at TRACE time when the
        # axis is unbound, so the fallback costs nothing at runtime.
        seq_total = x.shape[1]
        sp_rank = None
        if self.sequence_axis is not None:
            try:
                sp_rank = lax.axis_index(self.sequence_axis)
                sp_n = axis_size(self.sequence_axis)
            except NameError:
                sp_rank = None
        if sp_rank is not None:
            if self.pool != "gap":
                raise ValueError("sequence_axis requires pool='gap' (cls token cannot be sharded)")
            if seq_total % sp_n:
                raise ValueError(
                    f"{seq_total} tokens not divisible by sequence axis size {sp_n}"
                )
            local = seq_total // sp_n
            x = lax.dynamic_slice_in_dim(x, sp_rank * local, local, axis=1)
            attn_fn = ring_attention_fn(self.sequence_axis)
        else:
            attn_fn = None

        for i in range(self.depth):
            x = make_block(i, attn_fn)(x)
        return run_final(x, seq_total, sp_rank)


_VIT_CONFIGS = {
    "vit_tiny": dict(hidden_dim=192, depth=4, num_heads=3, mlp_dim=768),  # tests
    "vit_s16": dict(hidden_dim=384, depth=12, num_heads=6, mlp_dim=1536),
    "vit_b16": dict(hidden_dim=768, depth=12, num_heads=12, mlp_dim=3072),
    "vit_l16": dict(hidden_dim=1024, depth=24, num_heads=16, mlp_dim=4096),
}


def create_vit(arch: str, image_size: int = 224, **kwargs) -> VisionTransformer:
    if arch not in _VIT_CONFIGS:
        raise ValueError(f"unknown ViT arch {arch!r}; choose from {sorted(_VIT_CONFIGS)}")
    return VisionTransformer(image_size=image_size, **_VIT_CONFIGS[arch], **kwargs)


VIT_ARCHS = tuple(sorted(_VIT_CONFIGS))
