"""ResNet encoder family (Flax), torchvision-architecture-compatible.

The reference builds its encoders from `torchvision.models.resnet*`
(`main_moco.py:~L160`: `moco.builder.MoCo(models.__dict__[arch], ...)`).
This is a TPU-first reimplementation: NHWC layout (XLA's preferred conv
layout on TPU), bf16 compute / fp32 params+BN-stats, and a BatchNorm whose
cross-replica behavior is a constructor knob so the same module serves

- per-device BN (required by Shuffle-BN, `moco/builder.py:~L79-126`), and
- cross-replica SyncBN over optional subgroups (the reference only uses
  SyncBN in detection transfer, `detection/configs/Base-RCNN-C4-BN.yaml`).

Architecture parity notes vs torchvision ResNet v1:
- 7x7 stride-2 stem + 3x3 stride-2 maxpool (or a 3x3 stride-1 CIFAR stem).
- BasicBlock for resnet18/34, Bottleneck (expansion 4) for resnet50/101/152.
- Downsampling via 1x1 stride-2 conv in the residual branch ("v1.5": the
  3x3 conv in Bottleneck carries the stride, matching torchvision).
- Conv init: He normal (fan_out), BN gamma=1 beta=0; no conv bias.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any

# He-normal fan_out matches torchvision's kaiming_normal_(mode="fan_out").
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")


class BatchNorm(nn.Module):
    """`nn.BatchNorm`-compatible BN with two training-statistics modes
    beyond the full batch:

    - `stats_rows=r` — statistics from the first r rows only. The
      byte-reduction lever for the BN-bound step (PROFILE.md: the BN
      statistics reductions are 55% of step time — each training BN
      re-reads its full activation tensor over and above the conv that
      produced it); the forward statistics passes then read only r/B of
      each activation. Normalization still covers ALL rows.
    - `virtual_groups=G` — per-group statistics over G contiguous
      row-groups, each group normalized with its own statistics: the
      reference's per-GPU BatchNorm semantics (`main_moco.py:~L172`,
      batch 256 over 8 DDP ranks = 32-row statistics) reproduced inside
      ONE device's batch. Composed with the in-batch key permutation
      this makes single-chip training Shuffle-BN-faithful — a G-GPU
      recipe on one TPU. Same bytes as full BN (every row is read);
      running statistics are the group average, matching the train
      step's cross-device `pmean` of per-device stats.

    Both modes are faithful to the reference's statistics granularity
    rather than the 8x-larger single-chip batch. Parameter/variable
    names and tree paths match `nn.BatchNorm` (class name included), so
    checkpoints interchange between all modes. Gradients flow through
    the statistics exactly as through full-batch statistics.
    `axis_name` composes subset statistics cross-replica (SyncBN); it is
    rejected with virtual_groups (subgrouped SyncBN already covers the
    cross-device grouping pattern).
    """

    stats_rows: int = 0
    # With stats_rows: wrap the sliced subset in lax.optimization_barrier
    # so the slice is NOT fused into the surrounding conv/reduce clusters.
    # Candidate workaround for the TPU-backend compile pathology on the
    # r50/224 subset-stats program (PROFILE.md r4; scripts/
    # bn_compile_repro.py bisects it) — numerically identical, costs one
    # small (r rows) materialization per BN.
    stats_barrier: bool = False
    virtual_groups: int = 0
    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: jnp.dtype = jnp.float32
    axis_name: Optional[str] = None
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x):
        feats = x.shape[-1]
        scale = self.param("scale", self.scale_init, (feats,), jnp.float32)
        bias = self.param("bias", self.bias_init, (feats,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((feats,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((feats,), jnp.float32)
        )
        if self.stats_rows < 0:
            raise ValueError(f"stats_rows must be >= 0, got {self.stats_rows}")
        if self.virtual_groups < 0:
            raise ValueError(f"virtual_groups must be >= 0, got {self.virtual_groups}")
        if self.stats_rows and self.virtual_groups > 1:
            raise ValueError("stats_rows and virtual_groups are mutually exclusive")
        if self.stats_barrier and not self.stats_rows:
            # inert-flag combo must fail loudly (like the gates above): a
            # compile-pathology A/B with the barrier silently dropped
            # would measure baseline-vs-baseline
            raise ValueError("stats_barrier requires stats_rows > 0")
        if self.virtual_groups > 1 and self.axis_name is not None:
            raise ValueError("virtual_groups does not compose with cross-replica BN")
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        elif self.virtual_groups > 1:
            g = self.virtual_groups
            b = x.shape[0]
            if b % g:
                raise ValueError(f"batch {b} not divisible by virtual_groups {g}")
            xg = x.reshape((g, b // g) + x.shape[1:]).astype(jnp.float32)
            axes = tuple(range(1, xg.ndim - 1))  # all but group + channel
            mean = jnp.mean(xg, axis=axes)  # (g, C)
            mean2 = jnp.mean(jnp.square(xg), axis=axes)
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1 - self.momentum) * mean.mean(0)
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1 - self.momentum) * var.mean(0)
                )
            mul = scale * jax.lax.rsqrt(var + self.epsilon)  # (g, C)
            shift = bias - mean * mul
            bcast = (g,) + (1,) * (xg.ndim - 2) + (feats,)
            # normalize in the input dtype (xg's f32 copy was for the
            # statistics only): a f32 return here would silently switch
            # every downstream conv out of bf16
            y = x.reshape(xg.shape) * mul.reshape(bcast).astype(self.dtype) + shift.reshape(
                bcast
            ).astype(self.dtype)
            return y.reshape(x.shape)
        else:
            rows = x.shape[0]
            if self.stats_rows and self.stats_rows < rows:
                rows = self.stats_rows
            sub = x[:rows]
            if self.stats_barrier and rows < x.shape[0]:
                from moco_tpu.parallel.compat import optimization_barrier

                sub = optimization_barrier(sub)
            sub = sub.astype(jnp.float32)
            reduce_axes = tuple(range(sub.ndim - 1))
            mean = jnp.mean(sub, axis=reduce_axes)
            mean2 = jnp.mean(jnp.square(sub), axis=reduce_axes)
            if self.axis_name is not None and not self.is_initializing():
                mean, mean2 = jax.lax.pmean(
                    (mean, mean2),
                    axis_name=self.axis_name,
                    axis_index_groups=self.axis_index_groups,
                )
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = self.momentum * ra_mean.value + (1 - self.momentum) * mean
                ra_var.value = self.momentum * ra_var.value + (1 - self.momentum) * var
        mul = scale * jax.lax.rsqrt(var + self.epsilon)
        shift = bias - mean * mul
        return x * mul.astype(self.dtype) + shift.astype(self.dtype)


class ConvBN(nn.Module):
    """Conv (no bias) + BatchNorm, the repeated cell of every block."""

    features: int
    kernel_size: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features,
            (self.kernel_size, self.kernel_size),
            strides=self.strides,
            padding=[(self.kernel_size // 2, self.kernel_size // 2)] * 2,
            use_bias=False,
            kernel_init=conv_kernel_init,
            dtype=x.dtype,
        )(x)
        x = self.norm(scale_init=self.scale_init)(x)
        return x


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    expansion: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = ConvBN(self.features, 3, self.strides, self.norm)(x)
        y = nn.relu(y)
        y = ConvBN(self.features, 3, 1, self.norm)(y)
        if residual.shape != y.shape:
            residual = ConvBN(self.features, 1, self.strides, self.norm)(x)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        residual = x
        y = ConvBN(self.features, 1, 1, self.norm)(x)
        y = nn.relu(y)
        # v1.5: stride on the 3x3, as torchvision does.
        y = ConvBN(self.features, 3, self.strides, self.norm)(y)
        y = nn.relu(y)
        y = ConvBN(self.features * self.expansion, 1, 1, self.norm)(y)
        if residual.shape != y.shape:
            residual = ConvBN(self.features * self.expansion, 1, self.strides, self.norm)(x)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet backbone returning pooled features (the pre-`fc` activations).

    The classifier / projection head is deliberately NOT part of this
    module: the reference swaps the encoder's `fc` for a MoCo MLP head
    (`moco/builder.py:~L25-30`) and the linear probe re-attaches a fresh
    `fc` (`main_lincls.py:~L150-165`); keeping the head separate makes
    both operations explicit instead of module surgery.
    """

    stage_sizes: Sequence[int]
    block: ModuleDef = Bottleneck
    num_filters: int = 64
    cifar_stem: bool = False  # 3x3/s1 stem, no maxpool (32x32 inputs)
    dtype: jnp.dtype = jnp.float32
    bn_momentum: float = 0.9  # torch BN momentum 0.1 == flax momentum 0.9
    bn_epsilon: float = 1e-5
    # Cross-replica BN: None = per-device statistics (Shuffle-BN mode);
    # an axis name = SyncBN over that mesh axis (optionally subgrouped).
    bn_cross_replica_axis: Optional[str] = None
    bn_axis_index_groups: Optional[Sequence[Sequence[int]]] = None
    # Training BN statistics from the first N rows of the (per-device)
    # batch; 0 = full batch (exact nn.BatchNorm). See BatchNorm above.
    bn_stats_rows: int = 0
    # Fusion barrier around the subset slice (see BatchNorm.stats_barrier).
    bn_stats_barrier: bool = False
    # Per-group statistics over G contiguous row-groups (the reference's
    # per-GPU BN inside one device's batch). See BatchNorm above.
    bn_virtual_groups: int = 0

    @property
    def num_features(self) -> int:
        return self.num_filters * (2 ** (len(self.stage_sizes) - 1)) * self.block.expansion

    @nn.compact
    def __call__(self, x, train: bool = True):
        custom = self.bn_stats_rows or self.bn_virtual_groups > 1
        norm_cls = BatchNorm if custom else nn.BatchNorm
        extra = (
            {
                "stats_rows": self.bn_stats_rows,
                "stats_barrier": self.bn_stats_barrier,
                "virtual_groups": self.bn_virtual_groups,
            }
            if custom
            else {}
        )
        norm = functools.partial(
            norm_cls,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=self.bn_epsilon,
            dtype=self.dtype,
            axis_name=self.bn_cross_replica_axis,
            axis_index_groups=self.bn_axis_index_groups,
            **extra,
        )
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = ConvBN(self.num_filters, 3, 1, norm)(x)
            x = nn.relu(x)
        else:
            x = nn.Conv(
                self.num_filters,
                (7, 7),
                strides=2,
                padding=[(3, 3), (3, 3)],
                use_bias=False,
                kernel_init=conv_kernel_init,
                dtype=self.dtype,
            )(x)
            x = norm()(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block(self.num_filters * 2**i, strides, norm)(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return x.astype(jnp.float32)


_CONFIGS = {
    "resnet18": dict(stage_sizes=[2, 2, 2, 2], block=BasicBlock),
    "resnet34": dict(stage_sizes=[3, 4, 6, 3], block=BasicBlock),
    "resnet50": dict(stage_sizes=[3, 4, 6, 3], block=Bottleneck),
    "resnet101": dict(stage_sizes=[3, 4, 23, 3], block=Bottleneck),
    "resnet152": dict(stage_sizes=[3, 8, 36, 3], block=Bottleneck),
}


def create_resnet(arch: str, **kwargs) -> ResNet:
    """Factory mirroring `torchvision.models.__dict__[arch]` lookup
    (`main_moco.py:~L160`)."""
    if arch not in _CONFIGS:
        raise ValueError(f"unknown arch {arch!r}; choose from {sorted(_CONFIGS)}")
    return ResNet(**_CONFIGS[arch], **kwargs)


ARCHS = tuple(sorted(_CONFIGS))
