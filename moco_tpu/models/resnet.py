"""ResNet encoder family (Flax), torchvision-architecture-compatible.

The reference builds its encoders from `torchvision.models.resnet*`
(`main_moco.py:~L160`: `moco.builder.MoCo(models.__dict__[arch], ...)`).
This is a TPU-first reimplementation: NHWC layout (XLA's preferred conv
layout on TPU), bf16 compute / fp32 params+BN-stats, and a BatchNorm whose
cross-replica behavior is a constructor knob so the same module serves

- per-device BN (required by Shuffle-BN, `moco/builder.py:~L79-126`), and
- cross-replica SyncBN over optional subgroups (the reference only uses
  SyncBN in detection transfer, `detection/configs/Base-RCNN-C4-BN.yaml`).

Architecture parity notes vs torchvision ResNet v1:
- 7x7 stride-2 stem + 3x3 stride-2 maxpool (or a 3x3 stride-1 CIFAR stem).
- BasicBlock for resnet18/34, Bottleneck (expansion 4) for resnet50/101/152.
- Downsampling via 1x1 stride-2 conv in the residual branch ("v1.5": the
  3x3 conv in Bottleneck carries the stride, matching torchvision).
- Conv init: He normal (fan_out), BN gamma=1 beta=0; no conv bias.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any

# He-normal fan_out matches torchvision's kaiming_normal_(mode="fan_out").
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")


class BatchNorm(nn.Module):
    """`nn.BatchNorm`-compatible BN with two training-statistics modes
    beyond the full batch:

    - `stats_rows=r` — statistics from the first r rows only. The
      byte-reduction lever for the BN-bound step (PROFILE.md: the BN
      statistics reductions are 55% of step time — each training BN
      re-reads its full activation tensor over and above the conv that
      produced it); the forward statistics passes then read only r/B of
      each activation. Normalization still covers ALL rows.
    - `virtual_groups=G` — per-group statistics over G contiguous
      row-groups, each group normalized with its own statistics: the
      reference's per-GPU BatchNorm semantics (`main_moco.py:~L172`,
      batch 256 over 8 DDP ranks = 32-row statistics) reproduced inside
      ONE device's batch. Composed with the in-batch key permutation
      this makes single-chip training Shuffle-BN-faithful — a G-GPU
      recipe on one TPU. Same bytes as full BN (every row is read);
      running statistics are the group average, matching the train
      step's cross-device `pmean` of per-device stats.

    Both modes are faithful to the reference's statistics granularity
    rather than the 8x-larger single-chip batch. Parameter/variable
    names and tree paths match `nn.BatchNorm` (class name included), so
    checkpoints interchange between all modes. Gradients flow through
    the statistics exactly as through full-batch statistics.
    `axis_name` composes subset statistics cross-replica (SyncBN); it is
    rejected with virtual_groups (subgrouped SyncBN already covers the
    cross-device grouping pattern).

    A third training mode, `momentum_stats` ("Momentum² Teacher",
    arXiv:2101.07525 §3.2): normalize with the momentum-UPDATED running
    statistics — `m_new = momentum * running + (1 - momentum) * batch`,
    normalize with `m_new`, store `m_new` — instead of the raw batch
    statistics. Normalization decouples from the per-batch sample (the
    huge-batch alternative to cross-replica statistics: statistics
    precision comes from history, not from syncing one big batch), and
    gradients still flow through the `(1 - momentum) * batch` term.
    Eval mode is unchanged (running average), so checkpoints stay
    interchangeable. Mutually exclusive with stats_rows/virtual_groups;
    composes with `axis_name` (the batch term is then the cross-replica
    mean, i.e. momentum SyncBN).
    """

    stats_rows: int = 0
    # With stats_rows: wrap the sliced subset in lax.optimization_barrier
    # so the slice is NOT fused into the surrounding conv/reduce clusters.
    # Candidate workaround for the TPU-backend compile pathology on the
    # r50/224 subset-stats program (PROFILE.md r4; scripts/
    # bn_compile_repro.py bisects it) — numerically identical, costs one
    # small (r rows) materialization per BN.
    stats_barrier: bool = False
    virtual_groups: int = 0
    # Momentum-statistics mode (Momentum² Teacher): see class docstring.
    momentum_stats: bool = False
    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: jnp.dtype = jnp.float32
    axis_name: Optional[str] = None
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x):
        feats = x.shape[-1]
        scale = self.param("scale", self.scale_init, (feats,), jnp.float32)
        bias = self.param("bias", self.bias_init, (feats,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((feats,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((feats,), jnp.float32)
        )
        if self.stats_rows < 0:
            raise ValueError(f"stats_rows must be >= 0, got {self.stats_rows}")
        if self.virtual_groups < 0:
            raise ValueError(f"virtual_groups must be >= 0, got {self.virtual_groups}")
        if self.stats_rows and self.virtual_groups > 1:
            raise ValueError("stats_rows and virtual_groups are mutually exclusive")
        if self.stats_barrier and not self.stats_rows:
            # inert-flag combo must fail loudly (like the gates above): a
            # compile-pathology A/B with the barrier silently dropped
            # would measure baseline-vs-baseline
            raise ValueError("stats_barrier requires stats_rows > 0")
        if self.virtual_groups > 1 and self.axis_name is not None:
            raise ValueError("virtual_groups does not compose with cross-replica BN")
        if self.momentum_stats and (self.stats_rows or self.virtual_groups > 1):
            raise ValueError(
                "momentum_stats is mutually exclusive with stats_rows/virtual_groups"
            )
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        elif self.virtual_groups > 1:
            g = self.virtual_groups
            b = x.shape[0]
            if b % g:
                raise ValueError(f"batch {b} not divisible by virtual_groups {g}")
            xg = x.reshape((g, b // g) + x.shape[1:]).astype(jnp.float32)
            axes = tuple(range(1, xg.ndim - 1))  # all but group + channel
            mean = jnp.mean(xg, axis=axes)  # (g, C)
            mean2 = jnp.mean(jnp.square(xg), axis=axes)
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1 - self.momentum) * mean.mean(0)
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1 - self.momentum) * var.mean(0)
                )
            mul = scale * jax.lax.rsqrt(var + self.epsilon)  # (g, C)
            shift = bias - mean * mul
            bcast = (g,) + (1,) * (xg.ndim - 2) + (feats,)
            # normalize in the input dtype (xg's f32 copy was for the
            # statistics only): a f32 return here would silently switch
            # every downstream conv out of bf16
            y = x.reshape(xg.shape) * mul.reshape(bcast).astype(self.dtype) + shift.reshape(
                bcast
            ).astype(self.dtype)
            return y.reshape(x.shape)
        else:
            rows = x.shape[0]
            if self.stats_rows and self.stats_rows < rows:
                rows = self.stats_rows
            sub = x[:rows]
            if self.stats_barrier and rows < x.shape[0]:
                from moco_tpu.parallel.compat import optimization_barrier

                sub = optimization_barrier(sub)
            sub = sub.astype(jnp.float32)
            reduce_axes = tuple(range(sub.ndim - 1))
            mean = jnp.mean(sub, axis=reduce_axes)
            mean2 = jnp.mean(jnp.square(sub), axis=reduce_axes)
            if self.axis_name is not None and not self.is_initializing():
                mean, mean2 = jax.lax.pmean(
                    (mean, mean2),
                    axis_name=self.axis_name,
                    axis_index_groups=self.axis_index_groups,
                )
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if self.momentum_stats:
                # Momentum² Teacher: normalize with the momentum-updated
                # running statistics (same math as core/ema.py's
                # momentum_bn_stats — inlined, models/ must not import
                # core/). The batch term keeps the statistics gradient
                # path alive at (1 - momentum) weight.
                mean = self.momentum * ra_mean.value + (1 - self.momentum) * mean
                var = self.momentum * ra_var.value + (1 - self.momentum) * var
                if not self.is_initializing():
                    ra_mean.value = mean
                    ra_var.value = var
            elif not self.is_initializing():
                ra_mean.value = self.momentum * ra_mean.value + (1 - self.momentum) * mean
                ra_var.value = self.momentum * ra_var.value + (1 - self.momentum) * var
        mul = scale * jax.lax.rsqrt(var + self.epsilon)
        shift = bias - mean * mul
        return x * mul.astype(self.dtype) + shift.astype(self.dtype)


class ConvBN(nn.Module):
    """Conv (no bias) + BatchNorm, the repeated cell of every block."""

    features: int
    kernel_size: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features,
            (self.kernel_size, self.kernel_size),
            strides=self.strides,
            padding=[(self.kernel_size // 2, self.kernel_size // 2)] * 2,
            use_bias=False,
            kernel_init=conv_kernel_init,
            dtype=x.dtype,
        )(x)
        x = self.norm(scale_init=self.scale_init)(x)
        return x


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    expansion: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = ConvBN(self.features, 3, self.strides, self.norm)(x)
        y = nn.relu(y)
        y = ConvBN(self.features, 3, 1, self.norm)(y)
        if residual.shape != y.shape:
            residual = ConvBN(self.features, 1, self.strides, self.norm)(x)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    norm: ModuleDef = nn.BatchNorm
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        residual = x
        y = ConvBN(self.features, 1, 1, self.norm)(x)
        y = nn.relu(y)
        # v1.5: stride on the 3x3, as torchvision does.
        y = ConvBN(self.features, 3, self.strides, self.norm)(y)
        y = nn.relu(y)
        y = ConvBN(self.features * self.expansion, 1, 1, self.norm)(y)
        if residual.shape != y.shape:
            residual = ConvBN(self.features * self.expansion, 1, self.strides, self.norm)(x)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet backbone returning pooled features (the pre-`fc` activations).

    The classifier / projection head is deliberately NOT part of this
    module: the reference swaps the encoder's `fc` for a MoCo MLP head
    (`moco/builder.py:~L25-30`) and the linear probe re-attaches a fresh
    `fc` (`main_lincls.py:~L150-165`); keeping the head separate makes
    both operations explicit instead of module surgery.
    """

    stage_sizes: Sequence[int]
    block: ModuleDef = Bottleneck
    num_filters: int = 64
    cifar_stem: bool = False  # 3x3/s1 stem, no maxpool (32x32 inputs)
    dtype: jnp.dtype = jnp.float32
    bn_momentum: float = 0.9  # torch BN momentum 0.1 == flax momentum 0.9
    bn_epsilon: float = 1e-5
    # Cross-replica BN: None = per-device statistics (Shuffle-BN mode);
    # an axis name = SyncBN over that mesh axis (optionally subgrouped).
    bn_cross_replica_axis: Optional[str] = None
    bn_axis_index_groups: Optional[Sequence[Sequence[int]]] = None
    # Training BN statistics from the first N rows of the (per-device)
    # batch; 0 = full batch (exact nn.BatchNorm). See BatchNorm above.
    bn_stats_rows: int = 0
    # Fusion barrier around the subset slice (see BatchNorm.stats_barrier).
    bn_stats_barrier: bool = False
    # Per-group statistics over G contiguous row-groups (the reference's
    # per-GPU BN inside one device's batch). See BatchNorm above.
    bn_virtual_groups: int = 0
    # Momentum-statistics BN (Momentum² Teacher) — see BatchNorm above.
    bn_momentum_stats: bool = False

    @property
    def num_features(self) -> int:
        return self.num_filters * (2 ** (len(self.stage_sizes) - 1)) * self.block.expansion

    @property
    def group_names(self) -> tuple:
        """Schedule-ordered layer groups for the layer-granular ZeRO-3
        apply: the stem, then one group per residual block."""
        return ("stem",) + tuple(f"block{k}" for k in range(sum(self.stage_sizes)))

    def group_param_names(self) -> dict:
        """group -> its top-level param-tree child names. The names are
        flax AUTO-names, so they are pinned by construction order — the
        grouped `__call__` below constructs every submodule in canonical
        order precisely so this map stays true."""
        names = {
            "stem": ("ConvBN_0",) if self.cifar_stem else ("Conv_0", "BatchNorm_0")
        }
        blk = self.block.__name__
        for k in range(sum(self.stage_sizes)):
            names[f"block{k}"] = (f"{blk}_{k}",)
        return names

    @nn.compact
    def __call__(self, x, train: bool = True, group: Optional[str] = None):
        custom = (
            self.bn_stats_rows or self.bn_virtual_groups > 1 or self.bn_momentum_stats
        )
        norm_cls = BatchNorm if custom else nn.BatchNorm
        extra = (
            {
                "stats_rows": self.bn_stats_rows,
                "stats_barrier": self.bn_stats_barrier,
                "virtual_groups": self.bn_virtual_groups,
                "momentum_stats": self.bn_momentum_stats,
            }
            if custom
            else {}
        )
        norm = functools.partial(
            norm_cls,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=self.bn_epsilon,
            dtype=self.dtype,
            axis_name=self.bn_cross_replica_axis,
            axis_index_groups=self.bn_axis_index_groups,
            **extra,
        )
        # Construct EVERY submodule, in canonical order, before calling
        # any: flax assigns auto-names at construction time, so a
        # group-restricted apply must register the same name sequence as
        # the full one or the param tree would silently fork.
        if self.cifar_stem:
            stem_mods = (ConvBN(self.num_filters, 3, 1, norm),)
        else:
            stem_mods = (
                nn.Conv(
                    self.num_filters,
                    (7, 7),
                    strides=2,
                    padding=[(3, 3), (3, 3)],
                    use_bias=False,
                    kernel_init=conv_kernel_init,
                    dtype=self.dtype,
                ),
                norm(),
            )
        blocks = []
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                blocks.append(self.block(self.num_filters * 2**i, strides, norm))

        def run_stem(x):
            x = x.astype(self.dtype)
            if self.cifar_stem:
                return nn.relu(stem_mods[0](x))
            x = stem_mods[0](x)
            x = stem_mods[1](x)
            x = nn.relu(x)
            return nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])

        def run_block(k, x):
            x = blocks[k](x)
            if k == len(blocks) - 1:
                x = jnp.mean(x, axis=(1, 2))  # global average pool
                x = x.astype(jnp.float32)
            return x

        if group is None:
            x = run_stem(x)
            for k in range(len(blocks)):
                x = run_block(k, x)
            return x
        if group == "stem":
            return run_stem(x)
        if not (group.startswith("block") and group[5:].isdigit()):
            raise ValueError(f"unknown layer group {group!r}")
        k = int(group[5:])
        if k >= len(blocks):
            raise ValueError(f"layer group {group!r} out of range ({len(blocks)} blocks)")
        return run_block(k, x)


_CONFIGS = {
    "resnet18": dict(stage_sizes=[2, 2, 2, 2], block=BasicBlock),
    "resnet34": dict(stage_sizes=[3, 4, 6, 3], block=BasicBlock),
    "resnet50": dict(stage_sizes=[3, 4, 6, 3], block=Bottleneck),
    "resnet101": dict(stage_sizes=[3, 4, 23, 3], block=Bottleneck),
    "resnet152": dict(stage_sizes=[3, 8, 36, 3], block=Bottleneck),
}


def create_resnet(arch: str, **kwargs) -> ResNet:
    """Factory mirroring `torchvision.models.__dict__[arch]` lookup
    (`main_moco.py:~L160`)."""
    if arch not in _CONFIGS:
        raise ValueError(f"unknown arch {arch!r}; choose from {sorted(_CONFIGS)}")
    return ResNet(**_CONFIGS[arch], **kwargs)


ARCHS = tuple(sorted(_CONFIGS))
