#!/usr/bin/env python
"""CLI for the linear probe — the TPU-native `main_lincls.py`.

Usage:
    python eval_lincls.py --pretrained /tmp/moco \
        --data imagefolder --data-dir /data/imagenet --batch-size 256

`--pretrained` points at the pretraining workdir (an Orbax checkpoint
directory written by train.py). The model architecture and optimizer
template come from the config stored inside the checkpoint — no need to
re-specify `--arch`/`--mlp` (the reference makes the user repeat them and
asserts the keys match, `main_lincls.py:~L170-195`)."""

from __future__ import annotations

import argparse
import dataclasses

from moco_tpu.utils.config import DataConfig, ProbeConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="MoCo TPU linear probe")
    p.add_argument("--pretrained", required=True, help="pretraining workdir (Orbax)")
    p.add_argument("--lr", type=float, default=30.0)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=0.0)
    p.add_argument("--schedule", type=int, nargs="*", default=[60, 80])
    p.add_argument("--epochs", type=int, default=100)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--data", dest="dataset", choices=("synthetic", "synthetic_learnable", "synthetic_hard", "cifar10", "imagefolder"), default=None)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--batch-size", "-b", type=int, default=None)
    p.add_argument("--workers", "-j", type=int, default=None)
    p.add_argument(
        "--cache-dir", default=None,
        help="decode-once packed RGB cache (see train.py --cache-dir); "
        "defaults to the pretrain checkpoint's setting",
    )
    p.add_argument("--workdir", default=None)
    p.add_argument(
        "--evaluate", "-e", action="store_true",
        help="validation-only: load the probe's model_best (or latest) "
        "and score the val split, no training (main_lincls.py --evaluate)",
    )
    return p


def main() -> None:
    args = build_parser().parse_args()
    from moco_tpu.utils.platform import enable_persistent_compilation_cache, pin_platform_from_env

    pin_platform_from_env()
    enable_persistent_compilation_cache()
    probe = ProbeConfig(
        lr=args.lr,
        momentum=args.momentum,
        weight_decay=args.wd,
        schedule=tuple(args.schedule),
        epochs=args.epochs,
        num_classes=args.num_classes,
    )
    from moco_tpu.lincls import train_lincls
    from moco_tpu.utils.checkpoint import CheckpointManager
    from moco_tpu.utils.config import config_from_dict

    overrides = {
        k: v
        for k, v in {
            "dataset": args.dataset,
            "data_dir": args.data_dir,
            "image_size": args.image_size,
            "global_batch": args.batch_size,
            "num_workers": args.workers,
            "cache_dir": args.cache_dir,
        }.items()
        if v is not None
    }

    if args.evaluate:
        # evaluate-only never touches the pretrain workdir (the probe
        # checkpoint carries both configs); flag overrides apply to the
        # data config inside evaluate_lincls
        from moco_tpu.lincls import evaluate_lincls

        result = evaluate_lincls(
            args.pretrained, probe, workdir=args.workdir, data_overrides=overrides
        )
        print(f"Acc@1: {result['acc1']:.3f}")
        return

    # data defaults come from the checkpointed config; flags override
    mgr = CheckpointManager(args.pretrained)
    extra = mgr.read_extra()
    mgr.close()
    base_data = (
        config_from_dict(extra["config"]).data if "config" in extra else DataConfig()
    )
    data = dataclasses.replace(base_data, **overrides)
    result = train_lincls(args.pretrained, probe, data=data, workdir=args.workdir)
    print(f"best Acc@1: {result['best_acc1']:.3f}")


if __name__ == "__main__":
    main()
