"""Import a reference MoCo `.pth.tar` into a native Orbax checkpoint.

    python import_pretrain.py checkpoint_0199.pth.tar /ckpt/imported \
        [--arch resnet50] [--moco-t 0.2] [--steps-per-epoch 5004]

The output workdir is a first-class pretrain checkpoint: `train.py
--workdir /ckpt/imported ...` auto-resumes from it (EMA encoder, BN
running stats, queue + pointer all restored), and `eval_lincls.py
--pretrained /ckpt/imported` / `convert_pretrain.py` consume it
directly. See moco_tpu/import_torch.py for the weight-layout inverse
(reference save format: `main_moco.py:~L312-320`).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint", help="reference .pth.tar (torch)")
    p.add_argument("workdir", help="output Orbax checkpoint dir")
    p.add_argument("--arch", default=None, help="default: the checkpoint's own 'arch'")
    p.add_argument("--moco-t", type=float, default=None,
                   help="temperature to record (default 0.2 if MLP head else 0.07)")
    p.add_argument("--steps-per-epoch", type=int, default=None,
                   help="sets the imported global step to epoch*steps (LR-schedule "
                   "position on resume); default leaves step=0")
    p.add_argument("--lr", type=float, default=None,
                   help="the ORIGINAL training run's lr, recorded in the config "
                   "(default 0.03 = the reference recipe, marked as guessed)")
    p.add_argument("--epochs", type=int, default=None,
                   help="the ORIGINAL run's total epochs, recorded in the config "
                   "(default 200, marked as guessed)")
    return p


def main() -> None:
    args = build_parser().parse_args()
    from moco_tpu.utils.platform import pin_platform_from_env

    pin_platform_from_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import torch

    from moco_tpu.core import build_encoder, create_state
    from moco_tpu.import_torch import import_reference_state_dict
    from moco_tpu.utils.checkpoint import CheckpointManager
    from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig, config_to_dict
    from moco_tpu.utils.schedules import build_optimizer

    # weights_only: the reference save format is plain tensors/ints/strs
    # — never opt into full-pickle (code-executing) deserialization for a
    # file that may come from an untrusted mirror
    blob = torch.load(args.checkpoint, map_location="cpu", weights_only=True)
    state_dict = blob.get("state_dict", blob)
    state_dict = {k: v.numpy() if hasattr(v, "numpy") else v for k, v in state_dict.items()}
    arch = args.arch or blob.get("arch")
    if not arch:
        sys.exit("checkpoint carries no 'arch' — pass --arch")
    ckpt_epoch = int(blob.get("epoch", 0))  # reference: number of COMPLETED epochs

    pieces = import_reference_state_dict(state_dict, arch)
    mlp = bool(pieces.get("mlp"))
    dim = int(pieces["dim"])
    num_negatives = int(pieces["queue"].shape[0]) if "queue" in pieces else 65536
    temperature = args.moco_t if args.moco_t is not None else (0.2 if mlp else 0.07)

    # stem kind from the imported tree itself (import_torch disambiguates
    # by conv1 kernel size): a CIFAR-stem checkpoint must get a matching
    # template or graft() would die on tree-structure mismatch
    cifar_stem = "ConvBN_0" in pieces["params_q"]["backbone"]
    # A torch checkpoint does not record its optimizer hyperparameters;
    # anything not passed via flags is filled with the reference recipe's
    # defaults and LISTED as guessed in the saved extras, so downstream
    # config readers can tell provenance from measurement.
    guessed = ["data.dataset"]
    if args.lr is None:
        guessed.append("optim.lr")
    if args.epochs is None:
        guessed.append("optim.epochs")
    if args.moco_t is None:
        guessed.append("moco.temperature")
    guessed.append("optim.cos")
    config = TrainConfig(
        moco=MocoConfig(
            arch=arch, dim=dim, num_negatives=num_negatives,
            temperature=temperature, mlp=mlp, cifar_stem=cifar_stem,
        ),
        optim=OptimConfig(
            lr=args.lr if args.lr is not None else 0.03,
            epochs=args.epochs if args.epochs is not None else 200,
            cos=mlp,
        ),
        data=DataConfig(dataset="imagefolder"),
        workdir=args.workdir,
    )
    encoder = build_encoder(config.moco)
    tx = build_optimizer(config.optim, steps_per_epoch=args.steps_per_epoch or 5004)
    template = create_state(
        jax.random.PRNGKey(0), config, encoder, tx,
        jnp.zeros((1, config.data.image_size, config.data.image_size, 3), jnp.float32),
    )

    def graft(tmpl, imported, what):
        """Imported tree must match the template's structure and shapes
        exactly — a silent partial graft would be a broken checkpoint."""
        t_flat = jax.tree_util.tree_flatten_with_path(tmpl)[0]
        i_leaves, i_def = jax.tree_util.tree_flatten(imported)
        t_def = jax.tree_util.tree_structure(tmpl)
        if t_def != i_def:
            sys.exit(f"{what}: tree structure mismatch\n template={t_def}\n imported={i_def}")
        out = []
        for (path, t_leaf), i_leaf in zip(t_flat, i_leaves):
            if tuple(np.shape(t_leaf)) != tuple(np.shape(i_leaf)):
                name = jax.tree_util.keystr(path)
                sys.exit(
                    f"{what}{name}: shape {np.shape(i_leaf)} != template {np.shape(t_leaf)}"
                )
            out.append(jnp.asarray(i_leaf, jnp.asarray(t_leaf).dtype))
        return jax.tree_util.tree_unflatten(t_def, out)

    state = template.replace(
        params_q=graft(template.params_q, pieces["params_q"], "params_q"),
        batch_stats_q=graft(template.batch_stats_q, pieces["batch_stats_q"], "batch_stats_q"),
    )
    if "params_k" in pieces:
        state = state.replace(
            params_k=graft(template.params_k, pieces["params_k"], "params_k"),
            batch_stats_k=graft(template.batch_stats_k, pieces["batch_stats_k"], "batch_stats_k"),
        )
    else:  # v1-style partial saves: key encoder starts as a copy of q
        state = state.replace(
            params_k=jax.tree.map(jnp.copy, state.params_q),
            batch_stats_k=jax.tree.map(jnp.copy, state.batch_stats_q),
        )
    if "queue" in pieces:
        state = state.replace(
            queue=graft(template.queue, pieces["queue"], "queue"),
            queue_ptr=jnp.asarray(pieces.get("queue_ptr", 0), jnp.int32),
        )
    step = ckpt_epoch * args.steps_per_epoch if args.steps_per_epoch else 0
    state = state.replace(step=jnp.asarray(step, jnp.int32))

    mgr = CheckpointManager(args.workdir)
    mgr.save(
        step,
        state,
        extra={
            "epoch": ckpt_epoch - 1,
            "config": config_to_dict(config),
            "num_data": 1,
            "imported_from": args.checkpoint,
            # which recorded config fields are recipe-default guesses,
            # not values the original run actually used (ADVICE r2)
            "config_guessed_fields": guessed,
        },
        force=True,
    )
    mgr.close()
    print(
        f"imported {args.checkpoint} (arch={arch}, dim={dim}, mlp={mlp}, "
        f"K={num_negatives}, epoch={ckpt_epoch}) -> {args.workdir}"
    )


if __name__ == "__main__":
    main()
