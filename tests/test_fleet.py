"""Fleet observability layer (ISSUE 4): cross-host aggregation skew
math, comms bytes-moved formulas per collective, alert-rule firing
(including on injected utils/faults.py faults), heartbeats, trace
merging, the per-process sink satellites, and the schema extensions.

Runs under the 8-virtual-device CPU mesh (tests/conftest.py), following
the tests/test_multihost.py pattern of exercising cross-replica code on
a real mesh: collectives are real, processes are simulated (one host),
and the pure reductions are additionally tested on synthetic multi-host
matrices so the skew math is proven for fleets this box can't spawn.
"""

import json
import os
import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from moco_tpu.obs import alerts as alerts_mod
from moco_tpu.obs import comms, schema, sinks
from moco_tpu.obs.alerts import AlertEngine, parse_rules
from moco_tpu.obs.fleet import (
    FLEET_FIELDS,
    FleetAggregator,
    Heartbeat,
    read_heartbeats,
    reduce_stats,
)
from moco_tpu.parallel import create_mesh
from moco_tpu.parallel.compat import shard_map


# -- fleet reduction (skew math on synthetic multi-host matrices) --------


def test_reduce_stats_min_mean_max_argmax():
    # 3 hosts x 2 fields; t_step is column 1
    m = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [2.0, 6.0]], jnp.float32)
    out = jax.jit(lambda s: reduce_stats(s, 1))(m)
    np.testing.assert_allclose(np.asarray(out["min"]), [1.0, 2.0])
    np.testing.assert_allclose(np.asarray(out["mean"]), [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(out["max"]), [3.0, 6.0])
    assert np.asarray(out["argmax"]).tolist() == [1, 2]
    # skew = (max - mean) / mean over t_step = (6 - 4) / 4
    np.testing.assert_allclose(float(out["straggler_skew"]), 0.5, rtol=1e-6)


def test_reduce_stats_uniform_fleet_has_zero_skew():
    m = jnp.full((4, 3), 2.5, jnp.float32)
    out = reduce_stats(m, 0)
    np.testing.assert_allclose(float(out["straggler_skew"]), 0.0, atol=1e-6)


def test_reduce_stats_nan_aware():
    """A host that can't report a field (NaN) must not poison the fleet
    stats; a field NO host reports stays NaN (-> null in the line)."""
    m = jnp.asarray(
        [[1.0, np.nan, np.nan], [np.nan, 4.0, np.nan]], jnp.float32
    )
    out = reduce_stats(m, 0)
    assert float(out["min"][0]) == 1.0 and float(out["max"][1]) == 4.0
    assert np.isnan(float(out["mean"][2]))  # nobody reported column 2
    # skew over a column with one reporter: max == mean -> 0
    np.testing.assert_allclose(float(out["straggler_skew"]), 0.0, atol=1e-6)


def test_fleet_aggregator_roundtrip_and_payload():
    f = FleetAggregator()
    assert f.num_hosts == 1  # single process, however many devices
    vec = f.host_vector(
        t_data=0.1, t_step=0.5, dispatch_lag=0.02,
        io_retries=3, decode_failures=0, hbm_live=None,
    )
    stats = f.gather(vec)
    pay = f.payload(stats)
    assert pay["fleet_hosts"] == 1
    assert pay["straggler_skew"] == pytest.approx(0.0)
    # one host: min == mean == max; argmax names host 0
    assert pay["fleet/t_step_min"] == pay["fleet/t_step_max"] == pytest.approx(0.5)
    assert pay["fleet/io_retries_mean"] == pytest.approx(3.0)
    assert pay["fleet/t_step_argmax"] == 0
    # unknown hbm travels as NaN and scrubs to null at the sink
    assert np.isnan(pay["fleet/hbm_live_max"])
    rec = sinks.sanitize(pay)
    assert rec["fleet/hbm_live_max"] is None


def test_host_vector_rejects_unknown_field():
    f = FleetAggregator()
    with pytest.raises(ValueError, match="unknown fleet fields"):
        f.host_vector(t_step=1.0, gremlin=2.0)


def test_fleet_fields_include_issue_surface():
    for name in ("t_data", "t_step", "dispatch_lag", "io_retries",
                 "decode_failures", "hbm_live"):
        assert name in FLEET_FIELDS


# -- comms: analytic bytes-moved formulas per collective -----------------


def test_collective_bytes_formulas():
    b, n = 1024, 8
    assert comms.collective_bytes("all_gather", b, n) == b * 7
    assert comms.collective_bytes("all_to_all", b, n) == b * 7 // 8
    assert comms.collective_bytes("psum", b, n) == 2 * b * 7 // 8
    assert comms.collective_bytes("psum_scatter", b, n) == b * 7 // 8
    assert comms.collective_bytes("ppermute", b, n) == b
    assert comms.collective_bytes("broadcast", b, n) == b
    # host->device staging (the input wire): payload crosses once,
    # whatever the axis size — including the degenerate axis of 1
    assert comms.collective_bytes("device_put", b, n) == b
    assert comms.collective_bytes("device_put", b, 1) == b
    # size-1 axis moves nothing — except device_put, which is not a
    # ring collective (the payload crosses the PCIe/DMA wire once
    # regardless of any mesh axis)
    for kind in comms.COLLECTIVES:
        if kind != "device_put":
            assert comms.collective_bytes(kind, b, 1) == 0
    with pytest.raises(ValueError, match="unknown collective"):
        comms.collective_bytes("gossip", b, n)


def test_tree_bytes_counts_pytrees():
    tree = {"a": jnp.zeros((4, 4), jnp.float32), "b": jnp.zeros((8,), jnp.int32)}
    assert comms.tree_bytes(tree) == 4 * 4 * 4 + 8 * 4


def test_tag_records_ledger_inside_shard_map():
    comms.reset()
    mesh = create_mesh(num_data=8)

    def f(x):
        with comms.tag("t.gather", "all_gather", x, 8):
            return lax.all_gather(x, "data", tiled=True)

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False))
    fn(jnp.zeros((16, 4), jnp.float32))  # local shard: (2, 4) f32 = 32 B
    site = comms.snapshot()["t.gather"]
    assert site.operand_bytes == 32
    assert site.bytes_per_call == 32 * 7
    pay = comms.payload()
    assert pay["comms/t.gather"] == 32 * 7
    assert pay["comms/total"] == 32 * 7
    comms.reset()
    assert comms.payload() == {}


def test_tag_calls_per_step_scales_ring():
    comms.reset()
    with comms.tag("r.ring", "ppermute", jnp.zeros((4,), jnp.float32), 8, calls_per_step=8):
        pass
    site = comms.snapshot()["r.ring"]
    assert site.bytes_per_call == 16 and site.bytes_per_step == 16 * 8
    comms.reset()


@pytest.mark.parametrize(
    "shuffle,num_data,expected",
    [
        ("gather_perm", 8, ("shuffle.gather_images", "shuffle.gather_keys", "grad.psum")),
        ("a2a", 4, ("shuffle.a2a", "shuffle.a2a_unshuffle", "queue.enqueue_gather", "grad.psum")),
        ("none", 8, ("queue.enqueue_gather", "grad.psum")),
    ],
)
def test_train_step_registers_comms_sites(shuffle, num_data, expected):
    """One real train step over the mesh must leave the ISSUE's named
    collective sites in the ledger with non-zero analytic bytes."""
    from test_train_step import make_batch, setup, tiny_config

    comms.reset()
    config = tiny_config(shuffle=shuffle)
    _, _, _, state, step = setup(config, num_data=num_data)
    step(state, make_batch(), jax.random.key(1))
    ledger = comms.snapshot()
    for site in expected:
        assert site in ledger, f"missing comms site {site} (have {sorted(ledger)})"
        assert ledger[site].bytes_per_step > 0, site
    # the gradient psum moves the whole trainable tree twice (n-1)/n
    grads_bytes = ledger["grad.psum"].operand_bytes
    n = ledger["grad.psum"].axis_size
    assert ledger["grad.psum"].bytes_per_call == 2 * grads_bytes * (n - 1) // n
    comms.reset()


def test_ring_attention_registers_ppermute_site():
    from moco_tpu.parallel.ring_attention import ring_attention

    comms.reset()
    mesh = create_mesh(num_data=1, num_model=4)
    B, H, S, D = 1, 2, 16, 8

    def f(q, k, v):
        return ring_attention(q, k, v, "model", interpret=True, block_q=4, block_k=4)

    fn = jax.jit(
        shard_map(
            f,
            mesh=mesh,
            in_specs=(P(None, None, "model"), P(None, None, "model"), P(None, None, "model")),
            out_specs=P(None, None, "model"),
            check_vma=False,
        )
    )
    q = jnp.ones((B, H, S, D), jnp.float32)
    fn(q, q, q)
    site = comms.snapshot()["ring_attention.kv_ppermute"]
    # K + V local shards rotate once per ring step, n steps per call
    local_kv_bytes = 2 * B * H * (S // 4) * D * 4
    assert site.operand_bytes == local_kv_bytes
    assert site.calls_per_step == 4
    comms.reset()


def test_zero_registers_reduce_scatter_and_gather_sites():
    import dataclasses

    from moco_tpu.core import create_state, make_train_step, place_state
    from moco_tpu.utils.schedules import build_optimizer
    from test_train_step import IMG, make_batch, tiny_config, tiny_encoder

    comms.reset()
    config = tiny_config(shuffle="none")
    config = dataclasses.replace(
        config, parallel=dataclasses.replace(config.parallel, shard_weight_update=True)
    )
    mesh = create_mesh(num_data=8)
    enc = tiny_encoder()
    tx = build_optimizer(config.optim, steps_per_epoch=10)
    state = create_state(
        jax.random.key(0), config, enc, tx, jnp.zeros((1, IMG, IMG, 3)),
        zero_num_data=8,
    )
    step = make_train_step(config, enc, tx, mesh, state_template=state)
    state = place_state(state, mesh, zero=True)
    step(state, make_batch(), jax.random.key(1))
    ledger = comms.snapshot()
    assert ledger["zero.grad_reduce_scatter"].bytes_per_step > 0
    assert ledger["zero.params_all_gather"].bytes_per_step > 0
    comms.reset()


# -- alert engine --------------------------------------------------------


def test_parse_default_rules_and_extension():
    names = [r.name for r in parse_rules("default")]
    for expected in (
        "step_time_spike", "data_starvation", "straggler_skew_high",
        "ema_drift_runaway", "queue_stale", "nonfinite_loss", "stall",
        "heartbeat_loss",
    ):
        assert expected in names
    extended = parse_rules("default,threshold@name=my_rule:field=loss:value=9")
    assert "my_rule" in [r.name for r in extended]
    assert parse_rules("") == [] and parse_rules("none") == []


def test_parse_rules_rejects_bad_specs():
    with pytest.raises(ValueError, match="unknown alert rule kind"):
        parse_rules("vibes@name=x")
    with pytest.raises(ValueError, match="needs field="):
        parse_rules("threshold@name=x:value=1")
    with pytest.raises(ValueError, match="needs name="):
        parse_rules("threshold@field=loss:value=1")
    with pytest.raises(ValueError, match="duplicate"):
        parse_rules("event@name=x:event=stall,event@name=x:event=stall")


def test_spike_rule_needs_warmup_then_fires():
    eng = AlertEngine(parse_rules("spike@name=s:field=t_step:factor=3:window=8:warmup=4"))
    fired = []
    fired += eng.observe(0, {"t_step": 10.0})  # huge, but inside warmup
    for i in range(1, 6):
        fired += eng.observe(i, {"t_step": 0.1})
    assert fired == []  # warmup absorbed the compile-like first step
    fired += eng.observe(6, {"t_step": 0.9})
    assert [a["rule"] for a in fired] == ["s"]
    assert fired[0]["severity"] == "warn" and fired[0]["step"] == 6


def test_threshold_rule_fires_on_rising_edge_only():
    eng = AlertEngine(parse_rules("threshold@name=t:field=straggler_skew:value=0.5"))
    assert eng.observe(1, {"straggler_skew": 0.2}) == []
    assert len(eng.observe(2, {"straggler_skew": 0.8})) == 1
    assert eng.observe(3, {"straggler_skew": 0.9}) == []  # still over: no re-fire
    assert eng.observe(4, {"straggler_skew": 0.1}) == []  # recovered
    assert len(eng.observe(5, {"straggler_skew": 0.7})) == 1  # new edge


def test_ratio_rule_requires_consecutive_observations():
    eng = AlertEngine(
        parse_rules("ratio@name=starve:num=t_data:den=t_step:value=0.5:consecutive=3")
    )
    fired = []
    fired += eng.observe(1, {"t_data": 0.8, "t_step": 1.0})
    fired += eng.observe(2, {"t_data": 0.8, "t_step": 1.0})
    assert fired == []
    fired += eng.observe(3, {"t_data": 0.8, "t_step": 1.0})
    assert [a["rule"] for a in fired] == ["starve"]
    # a healthy step resets the streak
    eng.observe(4, {"t_data": 0.1, "t_step": 1.0})
    assert eng.observe(5, {"t_data": 0.8, "t_step": 1.0}) == []


def test_queue_staleness_uses_derived_wall_seconds():
    eng = AlertEngine(parse_rules("threshold@name=q:field=queue_stale_seconds:value=100"))
    # 30 steps of queue depth x 2 s/step = 60 s: fine
    assert eng.observe(1, {"queue_age_max": 30.0, "t_step": 2.0}) == []
    # 300 steps x 2 s/step = 600 s: stale
    assert len(eng.observe(2, {"queue_age_max": 300.0, "t_step": 2.0})) == 1


def test_event_rule_fires_on_injected_nan_event(tmp_path):
    """The chaos-harness wiring: a utils/faults.py-injected NaN loss
    produces a nonfinite_loss event payload; the default rules must turn
    it into an alerts.jsonl entry."""
    from moco_tpu.utils import faults

    eng = AlertEngine(parse_rules("default"), workdir=str(tmp_path))
    faults.install("nan@step=5")
    try:
        loss = faults.corrupt_loss(1.0, 5)
        assert loss != loss  # injected NaN
        fired = eng.observe(5, {"event": "nonfinite_loss", "nan_steps": 1})
    finally:
        faults.clear()
    assert [a["rule"] for a in fired] == ["nonfinite_loss"]
    eng.close()
    lines = [json.loads(l) for l in open(tmp_path / "alerts.jsonl")]
    assert lines[0]["rule"] == "nonfinite_loss" and lines[0]["step"] == 5


def test_spike_rule_fires_on_injected_stall(tmp_path, monkeypatch):
    """An injected utils/faults.py stall stretches t_step; the spike rule
    must flag it against the rolling median."""
    from moco_tpu.utils import faults

    sleeps = []
    monkeypatch.setattr(alerts_mod.time, "time", lambda: 0.0)
    import time as _time

    monkeypatch.setattr(_time, "sleep", lambda s: sleeps.append(s))
    faults.install("stall@step=20:seconds=5")
    eng = AlertEngine(
        parse_rules("spike@name=step_time_spike:field=t_step:factor=3:window=16:warmup=4"),
        workdir=str(tmp_path),
    )
    try:
        fired = []
        for step in range(10, 22):
            t0 = 0.1
            faults.maybe_stall(step)  # sleep is stubbed; record the injection
            if sleeps:
                t0 += sleeps.pop()
            fired += eng.observe(step, {"t_step": t0})
    finally:
        faults.clear()
    assert [a["rule"] for a in fired] == ["step_time_spike"]
    assert fired[0]["step"] == 20


def test_heartbeat_loss_rule_names_the_dead_host(tmp_path):
    Heartbeat(str(tmp_path), process_index=1).beat(step=7)
    eng = AlertEngine(
        parse_rules("heartbeat@name=hb:timeout=60:severity=fatal"),
        workdir=str(tmp_path), process_index=0,
    )
    now = read_heartbeats(str(tmp_path))[1]["time"]
    assert eng.observe(1, {}, now=now + 10) == []  # fresh
    fired = eng.observe(2, {}, now=now + 120)
    assert len(fired) == 1 and fired[0]["severity"] == "fatal"
    assert "process 1" in fired[0]["message"]
    # no re-fire while the host stays dead...
    assert eng.observe(3, {}, now=now + 180) == []
    # ...but a revival re-arms the rule
    Heartbeat(str(tmp_path), process_index=1).beat(step=9)
    now2 = read_heartbeats(str(tmp_path))[1]["time"]
    assert eng.observe(4, {}, now=now2 + 1) == []
    assert len(eng.observe(5, {}, now=now2 + 120)) == 1


# -- heartbeats ----------------------------------------------------------


def test_heartbeat_roundtrip_atomic(tmp_path):
    hb = Heartbeat(str(tmp_path), process_index=3, trace_wall_t0=123.5)
    hb.beat(step=42, epoch=2)
    beats = read_heartbeats(str(tmp_path))
    rec = beats[3]
    assert rec["step"] == 42 and rec["epoch"] == 2
    assert rec["trace_wall_t0"] == 123.5
    assert rec["host"] == socket.gethostname()
    assert not os.path.exists(hb.path + ".tmp")  # atomic replace cleaned up
    # junk files are skipped, not fatal
    (tmp_path / "heartbeat.pX.json").write_text("{not json")
    assert set(read_heartbeats(str(tmp_path))) == {3}


# -- trace merging -------------------------------------------------------


def _write_span_stream(path, process, names, t0_us=0.0):
    with open(path, "w") as f:
        for i, name in enumerate(names):
            f.write(json.dumps({
                "name": name, "ts": t0_us + i * 100.0, "dur": 50.0,
                "tid": 1, "thread": "MainThread", "depth": 0, "p": process,
            }) + "\n")


def test_trace_merge_one_track_per_host_with_clock_offsets(tmp_path):
    from conftest import load_script

    _write_span_stream(tmp_path / "trace_events.jsonl", 0, ["epoch", "step"])
    _write_span_stream(tmp_path / "trace_events.p1.jsonl", 1, ["epoch", "step"])
    # host 1's tracer started 2 s after host 0 (wall anchors via heartbeats)
    Heartbeat(str(tmp_path), 0, trace_wall_t0=1000.0).beat(step=2)
    Heartbeat(str(tmp_path), 1, trace_wall_t0=1002.0).beat(step=2)

    tm = load_script("trace_merge.py")
    out = str(tmp_path / "merged_trace.json")
    summary = tm.merge_traces(str(tmp_path), out)
    assert set(summary["processes"]) == {0, 1}
    assert summary["unanchored"] == []
    trace = json.load(open(out))
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in xs} == {0, 1}
    # clock-offset correction: host 1's first span lands 2 s later
    first = {p: min(e["ts"] for e in xs if e["pid"] == p) for p in (0, 1)}
    assert first[1] - first[0] == pytest.approx(2e6)
    # one labeled track group per host
    names = {
        e["pid"]: e["args"]["name"]
        for e in trace["traceEvents"]
        if e.get("ph") == "M" and e["name"] == "process_name"
    }
    assert set(names) == {0, 1} and names[0].startswith("host 0")


def test_trace_merge_survives_missing_heartbeat(tmp_path):
    from conftest import load_script

    _write_span_stream(tmp_path / "trace_events.jsonl", 0, ["step"])
    tm = load_script("trace_merge.py")
    summary = tm.merge_traces(str(tmp_path), str(tmp_path / "m.json"))
    assert summary["unanchored"] == [0]  # merged with zero offset, flagged


# -- sink satellites: per-process files + prometheus port/host -----------


def test_per_process_filename_derivation():
    assert sinks.per_process_filename("metrics.jsonl", 0) == "metrics.jsonl"
    assert sinks.per_process_filename("metrics.jsonl", 2) == "metrics.p2.jsonl"
    assert sinks.per_process_filename("metrics.csv", 1) == "metrics.p1.csv"
    assert sinks.derive_metrics_port(9090, 3) == 9093
    assert sinks.derive_metrics_port(0, 3) == 0  # disabled stays disabled


def test_build_sinks_per_process_files_dont_clobber(tmp_path):
    ms0 = sinks.build_sinks("jsonl,csv", str(tmp_path), process_index=0)
    ms2 = sinks.build_sinks("jsonl,csv", str(tmp_path), process_index=2)
    ms0.write(1, {"loss": 1.0})
    ms2.write(1, {"loss": 2.0})
    ms0.close()
    ms2.close()
    assert json.loads(open(tmp_path / "metrics.jsonl").read())["loss"] == 1.0
    assert json.loads(open(tmp_path / "metrics.p2.jsonl").read())["loss"] == 2.0
    assert os.path.exists(tmp_path / "metrics.csv")
    assert os.path.exists(tmp_path / "metrics.p2.csv")


def test_prometheus_port_shifted_by_process_and_host_passed(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
    # process 1 binds base+1 (base itself stays free for "process 0")
    ms = sinks.build_sinks(
        "jsonl", str(tmp_path), metrics_port=base, metrics_host="127.0.0.1",
        process_index=1,
    )
    try:
        assert ms.prometheus is not None
        assert ms.prometheus.port == base + 1
        assert ms.prometheus.host == "127.0.0.1"
    finally:
        ms.close()


# -- obs_report: merged multi-process view --------------------------------


def _train_line(step, **extra):
    rec = {
        "epoch": 0, "lr": 0.03, "loss": 1.0, "acc1": 10.0, "acc5": 20.0,
        "t_data": 0.01, "t_step": 0.2,
    }
    rec.update(extra)
    return rec


def test_obs_report_merges_per_process_metrics(tmp_path):
    from conftest import load_script

    w0 = sinks.JsonlSink(str(tmp_path))
    w0.write(1, _train_line(1, **{"straggler_skew": 0.1, "fleet_hosts": 2,
                                  "fleet/t_step_max": 0.3, "fleet/t_step_mean": 0.2,
                                  "fleet/t_step_argmax": 1,
                                  "comms/grad.psum": 1024, "comms/total": 1024}))
    w0.close()
    w1 = sinks.JsonlSink(str(tmp_path), filename="metrics.p1.jsonl")
    w1.write(1, _train_line(1))
    w1.close()
    Heartbeat(str(tmp_path), 0).beat(step=1)
    Heartbeat(str(tmp_path), 1).beat(step=1)

    rep = load_script("obs_report.py")
    paths = rep.metrics_paths_for(str(tmp_path))
    assert [os.path.basename(p) for p in paths] == ["metrics.jsonl", "metrics.p1.jsonl"]
    report = rep.render_report(paths, workdir=str(tmp_path))
    assert "2 per-process files" in report
    assert "## Fleet" in report and "straggler_skew" in report
    assert "## Comms" in report and "grad.psum" in report
    assert "host 0" in report and "host 1" in report


# -- schema extensions ---------------------------------------------------


def test_schema_accepts_fleet_and_comms_fields():
    line = {
        "step": 1, "time": 1.0, "epoch": 0, "lr": 0.03, "loss": 1.0,
        "acc1": 1.0, "acc5": 2.0,
        "straggler_skew": 0.2, "fleet_hosts": 4,
        "fleet/t_step_min": 0.1, "fleet/t_step_argmax": 3,
        "fleet/hbm_live_max": None,
        "comms/grad.psum": 1024, "comms/total": 2048,
    }
    assert schema.validate_line(line) == []
    alert_line = {
        "step": 2, "time": 1.0, "event": "alert", "alert": "step_time_spike",
        "severity": "warn", "alert/step_time_spike": 1,
    }
    assert schema.validate_line(alert_line) == []


def test_schema_rejects_bad_fleet_and_alert_values():
    bad = {"step": 1, "time": 1.0, "comms/grad.psum": None}
    assert any("comms/grad.psum" in e for e in schema.validate_line(bad))
    bad2 = {"step": 1, "time": 1.0, "fleet/t_step_min": "slow"}
    assert any("fleet/t_step_min" in e for e in schema.validate_line(bad2))
    bad3 = {"step": 1, "time": 1.0, "event": "alert", "severity": "whatever"}
    assert any("severity" in e for e in schema.validate_line(bad3))


def test_schema_validates_fleet_writer_output(tmp_path):
    """Writer and schema lock each other for the new fields too."""
    f = FleetAggregator()
    stats = f.gather(f.host_vector(t_step=0.5, t_data=0.1))
    w = sinks.JsonlSink(str(tmp_path))
    payload = _train_line(1)
    payload.update(f.payload(stats))
    payload.update({"comms/grad.psum": 123, "comms/total": 123})
    w.write(1, payload)
    w.close()
    assert schema.validate_file(w.path) == []
