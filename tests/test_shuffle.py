"""Shuffle-BN collective patterns on the 8-virtual-device mesh:
inverse property, cross-device movement, and determinism (the properties
the reference gets from NCCL broadcast + all_gather, moco/builder.py:~L79-126)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from moco_tpu.parallel import (
    DATA_AXIS,
    balanced_shuffle,
    balanced_unshuffle,
    create_mesh,
    make_permutation,
    shuffle_gather,
    unshuffle_gather,
)
from moco_tpu.parallel.compat import shard_map


def _mesh():
    return create_mesh(num_data=8, num_model=1)


def test_shuffle_unshuffle_is_identity():
    mesh = _mesh()
    x = jnp.arange(16 * 3, dtype=jnp.float32).reshape(16, 3)

    def f(x, rng):
        perm, inv = make_permutation(rng, 16)
        x_sh = shuffle_gather(x, perm, DATA_AXIS)
        # pretend-encode: identity, so unshuffle must reconstruct x
        local, global_ = unshuffle_gather(x_sh, inv, DATA_AXIS)
        return local, global_

    local, global_ = jax.jit(
        shard_map(
            f, mesh=mesh, in_specs=(P(DATA_AXIS), P()), out_specs=(P(DATA_AXIS), P()), check_vma=False
        )
    )(x, jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(local), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(global_), np.asarray(x))


def test_shuffle_actually_permutes():
    mesh = _mesh()
    x = jnp.arange(16, dtype=jnp.float32).reshape(16, 1)

    def f(x, rng):
        perm, _ = make_permutation(rng, 16)
        return shuffle_gather(x, perm, DATA_AXIS)

    shuffled = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(P(DATA_AXIS), P()), out_specs=P(DATA_AXIS), check_vma=False)
    )(x, jax.random.key(0))
    assert not np.array_equal(np.asarray(shuffled), np.asarray(x))
    assert sorted(np.asarray(shuffled).ravel().tolist()) == list(range(16))


def test_balanced_shuffle_mixes_and_inverts():
    """The property the removed `ring` mode LACKED (it moved batches
    intact, leaving BN batch composition — and therefore the BN leak —
    identical to no shuffle): every device's shuffled batch must mix
    sources, and unshuffle must be an exact inverse."""
    mesh = _mesh()
    # row value encodes source device: device d holds rows valued d
    x = jnp.repeat(jnp.arange(8, dtype=jnp.float32), 8).reshape(64, 1)
    rng = jax.random.key(5)

    def f(x):
        y = balanced_shuffle(rng, x, DATA_AXIS)
        # balanced: exactly local_b/n rows from each source device
        counts = jnp.stack([jnp.sum(y == d) for d in range(8)])
        back = balanced_unshuffle(rng, y, DATA_AXIS)
        return y, back, counts[None]

    y, back, counts = jax.jit(
        shard_map(
            f,
            mesh=mesh,
            in_specs=P(DATA_AXIS),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            check_vma=False,
        )
    )(x)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
    # each device got exactly one row from every source device
    np.testing.assert_array_equal(np.asarray(counts), np.ones((8, 8)))
    assert not np.array_equal(np.asarray(y), np.asarray(x))


def test_balanced_shuffle_changes_per_device_statistics():
    """Regression for the ring-mode bug: per-device batch *statistics*
    (what BN sees) must change under the shuffle."""
    mesh = _mesh()
    x = jax.random.normal(jax.random.key(0), (64, 4))

    def f(x):
        y = balanced_shuffle(jax.random.key(1), x, DATA_AXIS)
        return jnp.mean(x, 0, keepdims=True), jnp.mean(y, 0, keepdims=True)

    mx, my = jax.jit(
        shard_map(
            f, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=(P(DATA_AXIS), P(DATA_AXIS)), check_vma=False
        )
    )(x)
    # per-device means of the shuffled batch differ from the unshuffled ones
    assert not np.allclose(np.asarray(mx), np.asarray(my), atol=1e-6)


def test_permutation_is_deterministic_per_seed():
    p1, i1 = make_permutation(jax.random.key(7), 32)
    p2, _ = make_permutation(jax.random.key(7), 32)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    np.testing.assert_array_equal(np.asarray(p1)[np.asarray(i1)], np.arange(32))


@pytest.mark.slow
def test_leak_control_cheat_arm_trains_and_probes(tmp_path):
    """The BN-cheat positive-control pipeline end-to-end at smoke scale:
    the cheat config (shuffle='none' + virtual per-group BN, opted in
    via allow_leaky_bn) must train on `synthetic_leak_control`, and the
    leak probe must resolve the virtual grouping from the checkpoint by
    default and produce finite aligned/shuffled accuracies. Guards the
    single-chip path scripts/tpu_chains_r4.sh runs at full budget."""
    import numpy as np

    from moco_tpu.data.datasets import build_dataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        ParallelConfig,
        TrainConfig,
    )

    workdir = str(tmp_path / "none")
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=32, num_negatives=64, momentum=0.9,
            temperature=0.2, mlp=True, shuffle="none", cifar_stem=True,
            compute_dtype="float32", bn_virtual_groups=4,
            allow_leaky_bn=True,
        ),
        optim=OptimConfig(lr=0.06, epochs=1, cos=True),
        data=DataConfig(
            dataset="synthetic_leak_control", image_size=32,
            global_batch=16, aug_plus=True, crops_only=True,
        ),
        parallel=ParallelConfig(num_data=1),
        workdir=workdir,
        knn_every_epochs=0,
        seed=0,
    )
    dataset = build_dataset("synthetic_leak_control", None, 32, train=True)
    dataset.num_examples = 64
    final = train(config, dataset=dataset)
    assert np.isfinite(final["loss"])

    from tests.conftest import load_script

    mod = load_script("leak_probe.py")
    # groups=None: must resolve to num_data (1) x bn_virtual_groups (4)
    result = mod.probe_arm("none", workdir, None, batches=2, batch=None)
    assert result["groups"] == 4
    assert np.isfinite(result["contrast_acc_aligned"])
    assert np.isfinite(result["acc_drop_when_decorrelated"])
