"""Shuffle-BN collective patterns on the 8-virtual-device mesh:
inverse property, cross-device movement, and determinism (the properties
the reference gets from NCCL broadcast + all_gather, moco/builder.py:~L79-126)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from moco_tpu.parallel import (
    DATA_AXIS,
    create_mesh,
    make_permutation,
    ring_shift,
    ring_unshift,
    shuffle_gather,
    unshuffle_gather,
)


def _mesh():
    return create_mesh(num_data=8, num_model=1)


def test_shuffle_unshuffle_is_identity():
    mesh = _mesh()
    x = jnp.arange(16 * 3, dtype=jnp.float32).reshape(16, 3)

    def f(x, rng):
        perm, inv = make_permutation(rng, 16)
        x_sh = shuffle_gather(x, perm, DATA_AXIS)
        # pretend-encode: identity, so unshuffle must reconstruct x
        local, global_ = unshuffle_gather(x_sh, inv, DATA_AXIS)
        return local, global_

    local, global_ = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(DATA_AXIS), P()), out_specs=(P(DATA_AXIS), P()), check_vma=False
        )
    )(x, jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(local), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(global_), np.asarray(x))


def test_shuffle_actually_permutes():
    mesh = _mesh()
    x = jnp.arange(16, dtype=jnp.float32).reshape(16, 1)

    def f(x, rng):
        perm, _ = make_permutation(rng, 16)
        return shuffle_gather(x, perm, DATA_AXIS)

    shuffled = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P(DATA_AXIS), P()), out_specs=P(DATA_AXIS), check_vma=False)
    )(x, jax.random.key(0))
    assert not np.array_equal(np.asarray(shuffled), np.asarray(x))
    assert sorted(np.asarray(shuffled).ravel().tolist()) == list(range(16))


def test_ring_shift_moves_whole_batches_and_inverts():
    mesh = _mesh()
    # row value encodes source device: device d holds rows [2d, 2d+1]
    x = jnp.repeat(jnp.arange(8, dtype=jnp.float32), 2).reshape(16, 1)

    def f(x):
        y = ring_shift(x, DATA_AXIS)
        rank = jax.lax.axis_index(DATA_AXIS)
        # leak-prevention guarantee: nothing in my shifted batch is mine
        not_mine = jnp.all(y != rank.astype(jnp.float32))
        back = ring_unshift(y, DATA_AXIS)
        return y, back, jnp.reshape(not_mine, (1,))

    y, back, not_mine = jax.jit(
        jax.shard_map(
            f,
            mesh=mesh,
            in_specs=P(DATA_AXIS),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            check_vma=False,
        )
    )(x)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
    assert np.all(np.asarray(not_mine))
    # shifted by one device: device d now holds device (d-1... d+1)'s rows
    assert not np.array_equal(np.asarray(y), np.asarray(x))


def test_permutation_is_deterministic_per_seed():
    p1, i1 = make_permutation(jax.random.key(7), 32)
    p2, _ = make_permutation(jax.random.key(7), 32)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    np.testing.assert_array_equal(np.asarray(p1)[np.asarray(i1)], np.arange(32))
