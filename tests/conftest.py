"""Test harness: force an 8-virtual-device CPU platform.

This is the JAX-native answer to "test multi-node without a cluster"
(SURVEY.md §4): `--xla_force_host_platform_device_count=8` gives 8
CpuDevices, so every cross-replica pattern (shuffle-BN, queue lockstep,
grad psum) runs under a real Mesh in CI.

Must run before jax initializes a backend; the environment may pin
JAX_PLATFORMS to a TPU tunnel, so we override both the env var and the
config flag.
"""

import os

# MOCO_TPU_TESTS=1 leaves the real accelerator visible so the TPU-gated
# kernel tests (tests/test_tpu_kernels.py) can drive compiled Mosaic
# kernels: `MOCO_TPU_TESTS=1 pytest tests/test_tpu_kernels.py`. Default
# runs pin the 8-virtual-device CPU platform.
if not os.environ.get("MOCO_TPU_TESTS"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax

if not os.environ.get("MOCO_TPU_TESTS"):
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)


def load_script(name: str):
    """Import a module from scripts/ by filename (they are not a
    package); shared by tests that exercise script-level entry points."""
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), "..", "scripts", name)
    spec = importlib.util.spec_from_file_location(name.removesuffix(".py"), path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
