"""mocolint v2: the interprocedural engine (call graph + dataflow
summaries), the cross-function re-hosts of JX002/JX003/JX005, the
baseline workflow, statement-extent suppressions, and the runtime
collective-schedule sanitizer (unit + fake-8-device end-to-end)."""

import json
import os

import pytest

from moco_tpu.analysis import analyze_paths, analyze_source
from moco_tpu.analysis.__main__ import main as mocolint_main
from moco_tpu.analysis.callgraph import Program, build_program, module_name_for
from moco_tpu.analysis.dataflow import build_summaries
from moco_tpu.analysis.engine import (
    Finding,
    load_baseline,
    parse_module,
    write_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures", "lint")


def _program(files: dict[str, str]) -> Program:
    contexts = {}
    for path, src in files.items():
        ctx = parse_module(src, path)
        assert not isinstance(ctx, Finding), ctx.render()
        contexts[path] = ctx
    return build_program(contexts)


def _findings(files: dict[str, str], rules=None) -> list:
    prog = _program(files)
    out = []
    for path, ctx in prog.contexts.items():
        out.extend(
            analyze_source("\n".join(ctx.source_lines), path, rules=rules, ctx=ctx)
        )
    return out


# ---------------------------------------------------------------------------
# call graph


def test_module_name_for():
    assert module_name_for("moco_tpu/parallel/shuffle.py", [""]) == (
        "moco_tpu.parallel.shuffle"
    )
    assert module_name_for("pkg/__init__.py", [""]) == "pkg"


def test_cross_module_call_resolution():
    prog = _program({
        "lib.py": "def helper(x):\n    return x\n",
        "app.py": "from lib import helper\n\ndef main(y):\n    return helper(y)\n",
    })
    edges = prog.edges()
    assert "lib.helper" in edges["app.main"]


def test_method_resolution_via_self():
    prog = _program({
        "m.py": (
            "class C:\n"
            "    def a(self):\n"
            "        return self.b()\n"
            "    def b(self):\n"
            "        return 1\n"
        ),
    })
    assert "m.C.b" in prog.edges()["m.C.a"]


def test_jitted_closure_crosses_modules():
    prog = _program({
        "lib.py": "def helper(x):\n    return float(x)\n",
        "app.py": (
            "import jax\n"
            "from lib import helper\n\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return helper(x)\n"
        ),
    })
    jitted = prog.jitted()
    assert "app.step" in jitted and "lib.helper" in jitted


# ---------------------------------------------------------------------------
# dataflow summaries


def test_summary_sanitizes_and_propagates():
    prog = _program({
        "m.py": (
            "from jax import lax\n\n"
            "def clean(k):\n"
            "    return lax.stop_gradient(k)\n\n"
            "def passthrough(k):\n"
            "    return k * 2\n"
        ),
    })
    table = build_summaries(prog)
    assert table.get("m.clean").sanitizes
    assert "k" in table.get("m.passthrough").returns_taint_of


def test_summary_host_local_and_collectives():
    prog = _program({
        "m.py": (
            "import jax\n"
            "from jax import lax\n\n"
            "def who_am_i():\n"
            "    return jax.process_index()\n\n"
            "def reduce(x, axis_name):\n"
            "    return lax.psum(x, axis_name)\n"
        ),
    })
    table = build_summaries(prog)
    assert table.get("m.who_am_i").returns_host_local
    uses = table.get("m.reduce").collectives
    assert [u.kind for u in uses] == ["psum"]
    assert uses[0].axis_param == "axis_name"


def test_summary_derive_only_rng():
    prog = _program({
        "m.py": (
            "import jax\n\n"
            "def derive(rng, i):\n"
            "    return jax.random.fold_in(rng, i)\n\n"
            "def sample(rng, shape):\n"
            "    return jax.random.normal(rng, shape)\n"
        ),
    })
    table = build_summaries(prog)
    assert "rng" in table.get("m.derive").derives_only_rng_params
    assert "rng" in table.get("m.sample").consumes_rng_params


# ---------------------------------------------------------------------------
# interprocedural rule behavior


def test_jx002_flags_helper_in_other_module():
    findings = _findings({
        "lib.py": "def fetch(x):\n    return float(x)\n",
        "app.py": (
            "import jax\n"
            "from lib import fetch\n\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return fetch(x)\n"
        ),
    }, rules=["JX002"])
    assert [(f.path, f.rule) for f in findings] == [("lib.py", "JX002")]


def test_jx003_derive_only_helper_is_not_consumption():
    src = (
        "import jax\n\n"
        "def derive(rng, i):\n"
        "    return jax.random.fold_in(rng, i)\n\n"
        "def use(rng):\n"
        "    a = jax.random.normal(derive(rng, 1), (2,))\n"
        "    b = jax.random.normal(derive(rng, 2), (2,))\n"
        "    return a + b\n"
    )
    assert analyze_source(src, "m.py", rules=["JX003"]) == []


def test_jx003_consuming_helper_still_counts():
    src = (
        "import jax\n\n"
        "def sample(rng):\n"
        "    return jax.random.normal(rng, (2,))\n\n"
        "def use(rng):\n"
        "    a = sample(rng)\n"
        "    b = sample(rng)\n"
        "    return a + b\n"
    )
    findings = analyze_source(src, "m.py", rules=["JX003"])
    assert [f.line for f in findings] == [8]


def test_jx005_cross_function_fixture():
    """The ISSUE-6 acceptance bullet: the interprocedural JX005 pass
    flags the seeded cross-function stop_gradient violation, at the
    call site, and stays quiet on the stop_gradient'd twin."""
    path = os.path.join(FIXTURES, "jx005_crossfn_bad.py")
    findings = analyze_paths([path], rules=["JX005"])
    assert [f.line for f in findings] == [21]
    assert "project" in findings[0].message and "einsum" in findings[0].message


# ---------------------------------------------------------------------------
# statement-extent suppression (multi-line statements)


def test_suppression_on_closing_line_of_multiline_call():
    src = (
        "import jax\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    t = float(\n"
        "        x\n"
        "    )  # mocolint: disable=JX002  (justified)\n"
        "    return t\n"
    )
    findings = analyze_source(src, "m.py", rules=["JX002"])
    assert len(findings) == 1 and findings[0].suppressed


def test_suppression_does_not_leak_across_statements():
    src = (
        "import jax\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = float(x)  # mocolint: disable=JX002  (justified)\n"
        "    b = float(x)\n"
        "    return a + b\n"
    )
    findings = analyze_source(src, "m.py", rules=["JX002"])
    assert [(f.line, f.suppressed) for f in findings] == [(5, True), (6, False)]


# ---------------------------------------------------------------------------
# baseline workflow


def test_baseline_roundtrip_and_gating(tmp_path):
    bad = os.path.join(FIXTURES, "jx001_bad.py")
    findings = analyze_paths([bad], rules=["JX001"])
    assert findings and all(f.active for f in findings)
    baseline_path = tmp_path / "baseline.json"
    n = write_baseline(str(baseline_path), findings)
    assert n == len(findings)
    fingerprints = load_baseline(str(baseline_path))
    regated = analyze_paths([bad], rules=["JX001"], baseline=fingerprints)
    assert regated and all(f.baselined and not f.active for f in regated)


def test_baseline_path_normalization(tmp_path):
    f = Finding(rule="JX001", message="m", path="./tests/fixtures/lint/x.py", line=3)
    g = Finding(rule="JX001", message="m", path="tests/fixtures/lint/x.py", line=3)
    assert f.fingerprint() == g.fingerprint()


def test_cli_update_baseline_then_pass(tmp_path, capsys):
    bad = os.path.join(FIXTURES, "jx002_bad.py")
    baseline = str(tmp_path / "b.json")
    assert mocolint_main([bad, "--update-baseline", "--baseline", baseline]) == 0
    capsys.readouterr()
    # gated run passes; --no-baseline still fails
    assert mocolint_main([bad, "--baseline", baseline]) == 0
    assert mocolint_main([bad, "--no-baseline"]) == 1
    capsys.readouterr()


def test_cli_new_finding_fails_despite_baseline(tmp_path, capsys):
    old = "import time\nimport jax\n\n@jax.jit\ndef f(x):\n    return x + time.time()\n"
    src_path = tmp_path / "mod.py"
    src_path.write_text(old)
    baseline = str(tmp_path / "b.json")
    assert mocolint_main([str(src_path), "--update-baseline", "--baseline", baseline]) == 0
    # a NEW finding (second impure call) is not fingerprinted -> fail
    src_path.write_text(old + "\n@jax.jit\ndef g(x):\n    return x + time.time()\n")
    assert mocolint_main([str(src_path), "--baseline", baseline]) == 1
    capsys.readouterr()


def test_checked_in_baseline_matches_tree():
    """`--update-baseline` regenerates exactly what is checked in — the
    baseline cannot drift from the tree without CI noticing."""
    baseline = load_baseline(os.path.join(REPO, "mocolint-baseline.json"))
    paths = [
        os.path.join(REPO, d)
        for d in ("moco_tpu", "scripts", "tests")
    ] + [
        os.path.join(REPO, f)
        for f in ("train.py", "eval_lincls.py", "bench.py",
                  "convert_pretrain.py", "import_pretrain.py")
    ]
    findings = analyze_paths(paths)
    current = {f.fingerprint() for f in findings if not f.suppressed}
    assert current == baseline, (
        "baseline drift — rerun: python -m moco_tpu.analysis moco_tpu/ "
        "scripts/ tests/ train.py eval_lincls.py bench.py "
        "convert_pretrain.py import_pretrain.py --update-baseline"
    )


# ---------------------------------------------------------------------------
# runtime collective-schedule sanitizer


def test_recorder_dedupes_and_hashes_deterministically():
    from moco_tpu.analysis.sanitizer import ScheduleRecorder

    r1 = ScheduleRecorder(0)
    for _ in range(3):  # idempotent across retraces
        r1.record("shuffle.a2a", "all_to_all", "(16, 4):float32")
        r1.record("grad.psum", "psum", "(16, 4):float32")
    r2 = ScheduleRecorder(1)
    r2.record("shuffle.a2a", "all_to_all", "(16, 4):float32")
    r2.record("grad.psum", "psum", "(16, 4):float32")
    assert len(r1.entries()) == 2
    assert r1.schedule_hash() == r2.schedule_hash()
    # order matters: a reordered schedule is a DIFFERENT schedule
    r3 = ScheduleRecorder(2)
    r3.record("grad.psum", "psum", "(16, 4):float32")
    r3.record("shuffle.a2a", "all_to_all", "(16, 4):float32")
    assert r3.schedule_hash() != r1.schedule_hash()


def test_diverge_fault_perturbs_schedule():
    from moco_tpu.analysis.sanitizer import ScheduleRecorder
    from moco_tpu.utils import faults

    clean = ScheduleRecorder(0)
    clean.record("queue.enqueue_gather", "all_gather", "(32, 128):float32")
    faults.install("diverge@site=queue.enqueue_gather")
    try:
        divergent = ScheduleRecorder(1)
        divergent.record("queue.enqueue_gather", "all_gather", "(32, 128):float32")
    finally:
        faults.clear()
    assert clean.schedule_hash() != divergent.schedule_hash()
    assert "#diverged" in divergent.entries()[0][2]


def test_sanitizer_clean_and_divergent(tmp_path):
    from moco_tpu.analysis.sanitizer import (
        ScheduleDivergenceError,
        ScheduleRecorder,
        ScheduleSanitizer,
    )

    def make(pidx, sig):
        r = ScheduleRecorder(pidx)
        r.record("shuffle.a2a", "all_to_all", sig)
        r.record("grad.psum", "psum", "(8,):float32")
        return ScheduleSanitizer(
            str(tmp_path), process_index=pidx, num_processes=2, recorder=r
        )

    a = make(0, "(16, 4):float32")
    b = make(1, "(16, 4):float32")
    b.publish(step=0)
    a.check(step=0)  # clean: no raise
    # peer re-publishes a diverged schedule
    b2 = make(1, "(16, 8):float32")
    b2.publish(step=1)
    with pytest.raises(ScheduleDivergenceError) as e:
        a.check(step=1)
    assert "shuffle.a2a" in str(e.value)
    diff = json.loads((tmp_path / "schedule_diff.json").read_text())
    assert diff["divergent_peers"] == [1]
    assert any("shuffle.a2a" in line for line in diff["diff"])


def test_unpublished_peer_is_skipped(tmp_path):
    from moco_tpu.analysis.sanitizer import ScheduleRecorder, ScheduleSanitizer

    r = ScheduleRecorder(0)
    r.record("grad.psum", "psum", "(8,):float32")
    san = ScheduleSanitizer(str(tmp_path), process_index=0, num_processes=4, recorder=r)
    san.check(step=0)  # peers 1..3 never published: not a divergence


def test_comms_tag_feeds_recorder():
    import jax.numpy as jnp

    from moco_tpu.analysis.sanitizer import ScheduleRecorder, install_recorder
    from moco_tpu.obs import comms

    rec = ScheduleRecorder(0)
    prev = install_recorder(rec)
    try:
        with comms.tag("unit.site", "all_gather", jnp.zeros((4, 2)), 8):
            pass
    finally:
        install_recorder(prev)
    entries = rec.entries()
    assert entries == [("unit.site", "all_gather", "(4, 2):float32")]


@pytest.mark.slow
def test_driver_publishes_schedule_hash(tmp_path):
    """`--sanitize-collectives` end-to-end through the train driver: the
    recorder is installed before the first trace, every log line carries
    `collective_schedule_hash` (flat), and the out-of-band
    schedule.p0.json is published with the traced sites."""
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        TrainConfig,
    )

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=64, mlp=True,
            shuffle="gather_perm", cifar_stem=True, compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=16),
        workdir=str(tmp_path),
        log_every=1,
        sanitize_collectives=True,
    )
    dataset = SyntheticDataset(num_examples=48, image_size=16)
    result = train(config, dataset=dataset)
    assert result["epoch"] == 0

    lines = [
        json.loads(l) for l in open(os.path.join(str(tmp_path), "metrics.jsonl"))
    ]
    hashes = {
        l["collective_schedule_hash"] for l in lines if "collective_schedule_hash" in l
    }
    assert len(hashes) == 1, f"schedule hash must be flat on a healthy run: {hashes}"
    sched = json.loads(
        open(os.path.join(str(tmp_path), "schedule.p0.json")).read()
    )
    sites = [e[0] for e in sched["schedule"]]
    assert sites, "driver run traced no comms-tagged collectives"
    assert sched["hash"][:12] == next(iter(hashes))


@pytest.mark.slow
def test_sanitizer_catches_divergence_on_fake_8_device_mesh(tmp_path):
    """End-to-end on the 8-virtual-device mesh: the REAL collective
    schedule (a2a shuffle + gathers + psum, traced through comms.tag) is
    recorded by two simulated processes; an injected diverge@ fault on
    one of them must be caught with a per-site diff, and the clean
    control must pass. Reuses scripts/sanitizer_smoke.py so the CI leg
    and the test cannot drift apart."""
    from conftest import load_script

    smoke = load_script("sanitizer_smoke.py")
    report = smoke.run_smoke(str(tmp_path))
    assert report["control"]["ok"]
    assert report["chaos"]["caught"]
    assert any("shuffle.a2a" in line for line in report["chaos"]["diff_lines"])
    assert os.path.exists(os.path.join(str(tmp_path), "schedule_diff.json"))
