"""Activation-quantized int8 serving (ISSUE 11): calibration observer
determinism + artifact roundtrip, the w8a8 engine's embedding-cosine
floor per bucket, true-int8-vs-emulation equivalence, frozen-recompile
discipline on the quantized bucket keys, the extended donation audit,
and the quant/ivf gauges on the serving surface."""

import json
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.serve import quant
from moco_tpu.serve.engine import (
    EngineRecompileError,
    InferenceEngine,
    quantize_params_int8,
)

IMG = 32  # test_serve.py's lesson: XLA:CPU's 16px conv path is ~10x slower


@pytest.fixture(scope="module")
def toy_encoder():
    from moco_tpu.core import build_encoder
    from moco_tpu.utils.config import MocoConfig

    cfg = MocoConfig(
        arch="resnet18", dim=16, mlp=True, cifar_stem=True,
        shuffle="none", compute_dtype="float32",
    )
    enc = build_encoder(cfg)
    v = enc.init(jax.random.PRNGKey(0), jnp.zeros((1, IMG, IMG, 3)), train=False)
    return enc, v["params"], v.get("batch_stats", {})


@pytest.fixture(scope="module")
def calib_sample():
    return np.random.default_rng(7).integers(0, 255, (16, IMG, IMG, 3), np.uint8)


@pytest.fixture(scope="module")
def toy_calibration(toy_encoder, calib_sample):
    enc, params, stats = toy_encoder
    return quant.calibrate_encoder(enc, params, stats, calib_sample, IMG)


@pytest.fixture(scope="module")
def engines(toy_encoder, toy_calibration):
    """(f32, w8a8) engine pair on shared buckets — AOT compiles are the
    slow part, so every embedding test shares this pair."""
    enc, params, stats = toy_encoder
    f32 = InferenceEngine(enc, params, stats, image_size=IMG, buckets=(1, 4, 8))
    w8a8 = InferenceEngine(
        enc, params, stats, image_size=IMG, buckets=(1, 4, 8),
        engine_quant="w8a8", calibration=toy_calibration,
    )
    w8a8.warmup()
    return f32, w8a8


# -- calibration ----------------------------------------------------------


def test_calibration_deterministic_and_covering(toy_encoder, calib_sample, toy_calibration):
    """Same sample → bitwise-identical ranges (eager f32 forward, no
    PRNG), covering every layer quantize_params_int8 will quantize."""
    enc, params, stats = toy_encoder
    again = quant.calibrate_encoder(enc, params, stats, calib_sample, IMG)
    assert again == toy_calibration  # floats bitwise-equal, keys sorted
    covered = set(toy_calibration["amax"])
    assert quant.quantized_layer_paths(params) <= covered
    assert toy_calibration["num_layers"] == len(covered)
    assert all(v >= 0.0 for v in toy_calibration["amax"].values())


def test_calibration_artifact_roundtrip(tmp_path, toy_calibration):
    """save → load is the identity (json floats via repr), whether
    addressed as a file or as the checkpoint directory."""
    path = quant.save_calibration(str(tmp_path), toy_calibration)
    assert os.path.basename(path) == quant.CALIBRATION_FILENAME
    assert quant.load_calibration(path) == toy_calibration
    assert quant.load_calibration(str(tmp_path)) == toy_calibration
    with open(path) as f:
        raw = json.load(f)
    assert raw["version"] == quant.CALIBRATION_VERSION


def test_calibration_validation_rejects_mismatch(toy_encoder, toy_calibration):
    _, params, _ = toy_encoder
    with pytest.raises(ValueError, match="image_size"):
        quant.validate_calibration(toy_calibration, params, IMG * 2)
    clipped = dict(toy_calibration)
    clipped["amax"] = dict(list(toy_calibration["amax"].items())[:3])
    with pytest.raises(ValueError, match="uncovered"):
        quant.validate_calibration(clipped, params, IMG)


def test_w8a8_requires_calibration(toy_encoder):
    enc, params, stats = toy_encoder
    with pytest.raises(ValueError, match="calib"):
        InferenceEngine(
            enc, params, stats, image_size=IMG, buckets=(1,), engine_quant="w8a8"
        )
    with pytest.raises(ValueError, match="engine_quant"):
        InferenceEngine(
            enc, params, stats, image_size=IMG, buckets=(1,), engine_quant="int4"
        )


# -- embedding quality ----------------------------------------------------


def _mean_cos(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    return float(np.mean(np.sum(a * b, axis=-1)))  # rows L2-normalized


def test_w8a8_cosine_floor_per_bucket(engines):
    """The acceptance floor, per bucket: every padded-bucket executable
    of the quantized engine embeds within cosine 0.99 of f32."""
    f32, w8a8 = engines
    rng = np.random.default_rng(0)
    for n in (1, 4, 8):
        imgs = rng.integers(0, 255, (n, IMG, IMG, 3), np.uint8)
        ef, _ = f32.embed(imgs)
        eq, _ = w8a8.embed(imgs)
        assert _mean_cos(ef, eq) >= 0.99, f"bucket {n}"
        np.testing.assert_allclose(np.linalg.norm(eq, axis=1), 1.0, rtol=1e-5)


def test_w8a8_actually_quantizes(engines):
    """The quantized tier must not silently serve f32: its embeddings
    differ from the f32 engine's (activation rounding is real), while
    the back-compat gauges report the tier."""
    f32, w8a8 = engines
    imgs = np.random.default_rng(1).integers(0, 255, (4, IMG, IMG, 3), np.uint8)
    ef, _ = f32.embed(imgs)
    eq, _ = w8a8.embed(imgs)
    assert np.abs(ef - eq).max() > 0  # not bit-identical: a8 is live
    assert w8a8.quant == "w8a8" and w8a8.int8 and w8a8.calibration is not None
    assert f32.quant == "off" and not f32.int8


def test_int8_true_kernels_match_emulation(toy_encoder, toy_calibration):
    """`int8_compute=True` (the tpu/gpu path, runnable on CPU through
    XLA's generic int8 lowering) and the CPU scaled-integer emulation
    are the SAME arithmetic: int8×int8 products summed exactly. One
    small bucket keeps the generic int8 conv affordable."""
    enc, params, stats = toy_encoder
    imgs = np.random.default_rng(2).integers(0, 255, (1, IMG, IMG, 3), np.uint8)
    outs = {}
    for flag in (False, True):
        e = InferenceEngine(
            enc, params, stats, image_size=IMG, buckets=(1,),
            engine_quant="w8a8", calibration=toy_calibration,
            int8_compute=flag,
        )
        outs[flag], _ = e.embed(imgs)
    np.testing.assert_array_equal(outs[False], outs[True])


def test_quantized_apply_micro_module():
    """quant.py is module-generic: a micro conv+dense net quantizes
    through the same observer/apply pair, and the w8a8 output tracks
    the f32 output within the per-tensor quantization error budget."""

    class Micro(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Conv(8, (3, 3), padding="SAME")(x)
            x = nn.relu(x)
            x = x.reshape((x.shape[0], -1))
            return nn.Dense(4)(x)

    m = Micro()
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 8, 8, 3)), jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x)
    ref = m.apply(v, x)
    obs = quant.ActivationObserver()
    with obs.intercept():
        m.apply(v, x)
    assert len(obs.amax) == 2
    qp, qs = quantize_params_int8(v["params"])
    scales = {p: jnp.float32(s) for p, s in quant.fit_scales(obs.amax).items()}
    out = quant.quantized_apply(m, qp, qs, {}, scales, x, int8_compute=False)
    assert np.abs(np.asarray(out) - np.asarray(ref)).max() < 0.15
    assert np.abs(np.asarray(out) - np.asarray(ref)).max() > 0  # quantized


# -- freeze + donation discipline -----------------------------------------


def test_quant_engine_frozen_recompile_raises(engines):
    """The (mode, quant) bucket keys obey the same freeze contract as
    the f32 engine: a warm quantized engine refuses new buckets."""
    _, w8a8 = engines
    assert w8a8.recompiles_after_warmup == 0
    with pytest.raises(EngineRecompileError):
        w8a8._compile(64)


def test_quant_donation_audit_extends_to_qtrees(engines):
    """On CPU: input donation gated off (None), quantized trees audited
    alive (True) — never False, which serve_smoke fails loudly on."""
    _, w8a8 = engines
    w8a8.embed(np.zeros((2, IMG, IMG, 3), np.uint8))
    audit = w8a8.donation_audit()
    qtree_keys = [k for k in audit if isinstance(k, str) and k.startswith("qtree:")]
    assert qtree_keys, audit
    assert all(audit[k] is True for k in qtree_keys), audit
    assert not any(v is False for v in audit.values()), audit


def test_w8_backcompat_spelling(toy_encoder):
    """int8=True still means weight-only PTQ (the PR-9 contract)."""
    enc, params, stats = toy_encoder
    e = InferenceEngine(
        enc, params, stats, image_size=IMG, buckets=(1,), int8=True
    )
    assert e.quant == "w8" and e.int8
    out, _ = e.embed(np.zeros((1, IMG, IMG, 3), np.uint8))
    assert out.shape[0] == 1


# -- serving surface ------------------------------------------------------


def test_server_quant_and_ivf_gauges(engines):
    """GET /stats carries serve/quant_tier and the ivf_stats() gauges
    (serve/ivf_spill, serve/ivf_occupancy) the ROADMAP names as the
    re-fit trigger; schema validates the flushed line."""
    from moco_tpu.obs import schema
    from moco_tpu.serve.index import EmbeddingIndex
    from moco_tpu.serve.server import ServeServer

    _, w8a8 = engines
    rng = np.random.default_rng(5)
    dim = w8a8.num_features or 16
    rows = rng.normal(size=(64, dim)).astype(np.float32)
    rows /= np.linalg.norm(rows, axis=1, keepdims=True)
    index = EmbeddingIndex(64, dim)
    index.snapshot(rows)
    index.train_ivf(nlist=4, nprobe=4)
    server = ServeServer(
        w8a8, index=index, port=0, slo_ms=1000.0,
        neighbors_k=3, neighbors_mode="ivf_fused", nprobe=4,
    )
    try:
        stats = server.stats()
    finally:
        server.close()
    assert stats["serve/quant_tier"] == 2
    assert stats["serve/int8"] == 1
    assert stats["serve/ivf_spill"] == index.ivf_stats()["spilled"]
    assert stats["serve/ivf_occupancy"] == pytest.approx(
        index.ivf_stats()["occupancy"]
    )
    line = {k: v for k, v in stats.items() if k.startswith("serve/")}
    errors = schema.validate_line(dict(line, step=0, time=0.0))
    assert not errors, errors


def test_schema_quant_validators():
    from moco_tpu.obs import schema

    ok = {"step": 0, "time": 0.0, "serve/quant_tier": 2,
          "serve/ivf_spill": 0, "serve/ivf_occupancy": 0.5}
    assert not schema.validate_line(ok)
    for bad in (
        {"serve/quant_tier": 3},
        {"serve/ivf_spill": -1},
        {"serve/ivf_occupancy": 1.5},
    ):
        assert schema.validate_line(dict(bad, step=0, time=0.0)), bad
