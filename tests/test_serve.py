"""Serving subsystem: index kernels (+ bitwise equivalence with the
pre-refactor queue/kNN paths), AOT engine, continuous batcher, HTTP
server, schema/port satellites, and the perf-ledger serving series."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.ops.losses import l2_normalize
from moco_tpu.serve.batcher import BatcherClosedError, ContinuousBatcher, ServeMetrics
from moco_tpu.serve.index import (
    EmbeddingIndex,
    IndexRecompileError,
    fifo_write,
    topk_cosine,
)

from tests.conftest import load_script


# -- shared kernels: bitwise equivalence with the pre-refactor paths ----


def _old_enqueue(queue, ptr, keys):
    """core/queue.py's enqueue body as it was before the serve refactor
    (PR 7 state) — the oracle the shared kernel must match bitwise."""
    num_neg = queue.shape[0]
    keys = jax.lax.stop_gradient(keys).astype(queue.dtype)
    queue = jax.lax.dynamic_update_slice(queue, keys, (ptr, jnp.zeros_like(ptr)))
    new_ptr = (ptr + keys.shape[0]) % num_neg
    return queue, new_ptr


def _old_knn_scan(q, bank, k):
    """knn.py's inline cosine top-k as it was before the refactor."""
    sims = q @ bank.T
    return jax.lax.top_k(sims, k)


@pytest.mark.parametrize("ptr", [0, 8, 56])
def test_fifo_write_bitwise_matches_pre_refactor(ptr):
    from moco_tpu.core.queue import enqueue, init_queue

    queue = init_queue(jax.random.PRNGKey(0), 64, 16)
    keys = jax.random.normal(jax.random.PRNGKey(1), (8, 16), jnp.float32)
    new, np_new = enqueue(queue, jnp.int32(ptr), keys)
    old, np_old = _old_enqueue(queue, jnp.int32(ptr), keys)
    np.testing.assert_array_equal(np.asarray(new), np.asarray(old))
    assert int(np_new) == int(np_old)
    # and under jit (the in-step context), still bitwise
    new_j, _ = jax.jit(fifo_write)(queue, jnp.int32(ptr), keys)
    np.testing.assert_array_equal(np.asarray(new_j), np.asarray(old))


def test_topk_cosine_bitwise_matches_pre_refactor_knn_scan():
    rng = np.random.default_rng(0)
    bank = np.asarray(l2_normalize(jnp.asarray(rng.normal(size=(128, 32)), jnp.float32)))
    q = np.asarray(l2_normalize(jnp.asarray(rng.normal(size=(16, 32)), jnp.float32)))
    s_new, i_new = jax.jit(lambda q, b: topk_cosine(q, b, 10))(q, bank)
    s_old, i_old = jax.jit(lambda q, b: _old_knn_scan(q, b, 10))(q, bank)
    np.testing.assert_array_equal(np.asarray(s_new), np.asarray(s_old))
    np.testing.assert_array_equal(np.asarray(i_new), np.asarray(i_old))


def test_knn_classify_unchanged_by_rehost():
    """knn_classify on the shared kernel == the inline pre-refactor
    classifier, bitwise on the predictions."""
    from moco_tpu.knn import knn_classify

    rng = np.random.default_rng(1)
    bank = np.asarray(l2_normalize(jnp.asarray(rng.normal(size=(200, 16)), jnp.float32)))
    bank_y = rng.integers(0, 4, 200)
    q = np.asarray(l2_normalize(jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)))
    preds = knn_classify(bank, bank_y, q, num_classes=4, k=20)

    bank_j, labels_j = jnp.asarray(bank), jnp.asarray(bank_y)

    @jax.jit
    def old_classify(qb):
        top_sims, top_idx = _old_knn_scan(qb, bank_j, 20)
        weights = jnp.exp(top_sims / 0.07)
        votes = jax.nn.one_hot(labels_j[top_idx], 4)
        return jnp.argmax(jnp.einsum("mk,mkc->mc", weights, votes), axis=-1)

    np.testing.assert_array_equal(preds, np.asarray(old_classify(jnp.asarray(q))))


@pytest.mark.slow
def test_train_step_trajectory_bit_identical_after_rehost():
    """The acceptance bullet, executable: a train run whose queue update
    goes through the rehosted kernel is BIT-identical (queue, ptr,
    params, loss) to the same run with the pre-refactor inline enqueue
    monkeypatched back in."""
    from moco_tpu.core import moco as moco_mod
    from moco_tpu.core.moco import build_encoder, create_state, make_train_step, place_state
    from moco_tpu.parallel import create_mesh, shard_batch
    from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
    from moco_tpu.utils.schedules import build_optimizer

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=64, mlp=True,
            shuffle="gather_perm", cifar_stem=True, compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=16),
    )
    mesh = create_mesh()
    encoder = build_encoder(config.moco, num_data=mesh.shape["data"])
    tx = build_optimizer(config.optim, steps_per_epoch=2)
    rng = jax.random.PRNGKey(0)
    ims = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 16, 3), jnp.float32)
    batch = shard_batch(mesh, {"im_q": ims[0], "im_k": ims[1]})
    root = jax.device_put(
        jax.random.PRNGKey(2),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )

    def run(enqueue_impl):
        orig = moco_mod.enqueue
        moco_mod.enqueue = enqueue_impl
        try:
            state = create_state(
                rng, config, encoder, tx, jnp.zeros((1, 16, 16, 3), jnp.float32)
            )
            state = place_state(state, mesh)
            step = make_train_step(config, encoder, tx, mesh)
            for _ in range(2):
                state, metrics = step(state, batch, root)
            return jax.device_get(state), float(metrics["loss"])
        finally:
            moco_mod.enqueue = orig

    state_new, loss_new = run(moco_mod.enqueue)
    state_old, loss_old = run(_old_enqueue)
    assert loss_new == loss_old
    np.testing.assert_array_equal(np.asarray(state_new.queue), np.asarray(state_old.queue))
    assert int(state_new.queue_ptr) == int(state_old.queue_ptr)
    for a, b in zip(jax.tree.leaves(state_new.params_q), jax.tree.leaves(state_old.params_q)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- EmbeddingIndex ------------------------------------------------------


def _clusters(num_clusters=4, per=50, dim=32, noise=0.05, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(num_clusters, dim)).astype(np.float32) * 4
    rows = np.concatenate(
        [centers[i] + rng.normal(0, noise, (per, dim)).astype(np.float32)
         for i in range(num_clusters)]
    )
    labels = np.repeat(np.arange(num_clusters), per)
    rows = np.asarray(l2_normalize(jnp.asarray(rows)))
    return rows, labels, centers


def test_index_recall_at_k_on_clustered_data():
    """Every query's top-k must come from its own cluster (well-separated
    synthetic clusters -> exact scan recall@k should be 1.0)."""
    rows, labels, centers = _clusters()
    idx = EmbeddingIndex(rows.shape[0], rows.shape[1])
    idx.snapshot(rows)
    queries = np.asarray(l2_normalize(jnp.asarray(centers)))
    scores, nbr = idx.query(queries, 10)
    for c in range(len(centers)):
        assert (labels[nbr[c]] == c).all(), f"cluster {c} recall@10 < 1"
        assert (np.diff(scores[c]) <= 1e-6).all(), "scores not sorted"


def test_index_fifo_eviction_order():
    idx = EmbeddingIndex(8, 4)
    blocks = [np.full((4, 4), float(i + 1), np.float32) for i in range(3)]
    for b in blocks:
        idx.add(np.asarray(l2_normalize(jnp.asarray(b))))
    # capacity 8, three blocks of 4: block 0 evicted, 2 and 1 resident
    rows = np.asarray(idx.rows)
    np.testing.assert_allclose(rows[:4], np.asarray(l2_normalize(jnp.asarray(blocks[2]))))
    np.testing.assert_allclose(rows[4:], np.asarray(l2_normalize(jnp.asarray(blocks[1]))))
    assert idx.count == 8


def test_index_valid_count_masks_unfilled_rows():
    rows, _, _ = _clusters(num_clusters=2, per=8)
    idx = EmbeddingIndex(64, rows.shape[1])
    idx.snapshot(rows[:4])
    scores, nbr = idx.query(rows[:2], 4)
    assert (nbr < 4).all(), "query surfaced an unfilled row"
    scores_full, _ = idx.query(rows[:2], 8)
    assert (scores_full[:, 4:] == -np.inf).all(), "unfilled rows not masked"


def test_index_sharded_matches_single_device():
    from moco_tpu.parallel import create_mesh

    rows, _, centers = _clusters(dim=16)
    queries = np.asarray(l2_normalize(jnp.asarray(centers)))
    plain = EmbeddingIndex(rows.shape[0], 16)
    plain.snapshot(rows)
    mesh = create_mesh()
    sharded = EmbeddingIndex(rows.shape[0], 16, mesh=mesh)
    sharded.snapshot(rows)
    assert sharded.capacity % mesh.shape["data"] == 0
    s1, i1 = plain.query(queries, 5)
    s2, i2 = sharded.query(queries, 5)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_allclose(s1, s2, rtol=1e-6, atol=1e-6)


def test_index_frozen_rejects_unprepared_shape():
    idx = EmbeddingIndex(16, 8)
    idx.snapshot(np.eye(8, dtype=np.float32))
    idx.prepare([4], k=2)
    idx.freeze()
    idx.query(np.eye(8, dtype=np.float32)[:4], 2)  # prepared: fine
    with pytest.raises(IndexRecompileError):
        idx.query(np.eye(8, dtype=np.float32)[:3], 2)
    assert idx.recompiles_after_warmup == 0


def test_index_from_train_queue_roundtrip():
    from moco_tpu.core.queue import init_queue

    queue = init_queue(jax.random.PRNGKey(3), 32, 8)
    idx = EmbeddingIndex.from_train_queue(np.asarray(queue), queue_ptr=16)
    assert idx.count == 32 and idx.capacity == 32 and idx._ptr == 16
    q = np.asarray(queue)[:2]
    scores, nbr = idx.query(q, 1)
    np.testing.assert_array_equal(nbr[:, 0], [0, 1])
    np.testing.assert_allclose(scores[:, 0], 1.0, rtol=1e-5)


def test_index_add_wrap_splits_at_capacity_boundary():
    """Serving ingest takes arbitrary block sizes: a block crossing the
    capacity boundary splits into two no-wrap writes (training keeps its
    K % N == 0 invariant and never wraps)."""
    idx = EmbeddingIndex(8, 4)
    blocks = [
        np.asarray(l2_normalize(jnp.full((3, 4), float(i + 1), jnp.float32)))
        for i in range(3)
    ]
    for b in blocks:
        idx.add(b)
    # 9 rows through capacity 8: head wrapped to 0 and row 0 holds the
    # last row of block 2; rows 3..5 hold block 1, 6..7 block 2's head
    rows = np.asarray(idx.rows)
    np.testing.assert_allclose(rows[0], blocks[2][2])
    np.testing.assert_allclose(rows[3:6], blocks[1])
    np.testing.assert_allclose(rows[6:8], blocks[2][:2])
    assert idx.count == 8 and idx._ptr == 1
    with pytest.raises(ValueError, match="exceeds capacity"):
        idx.add(np.zeros((9, 4), np.float32))


# -- engine + server (shared fixture: AOT compiles are the slow part) ---

IMG = 32  # NB not 16: XLA:CPU's tiny-spatial-dim conv path is ~10x slower


@pytest.fixture(scope="module")
def toy_engine():
    from moco_tpu.core import build_encoder
    from moco_tpu.serve.engine import InferenceEngine
    from moco_tpu.utils.config import MocoConfig

    cfg = MocoConfig(
        arch="resnet18", dim=16, mlp=True, cifar_stem=True,
        shuffle="none", compute_dtype="float32",
    )
    enc = build_encoder(cfg)
    v = enc.init(jax.random.PRNGKey(0), jnp.zeros((1, IMG, IMG, 3)), train=False)
    engine = InferenceEngine(
        enc, v["params"], v.get("batch_stats", {}), image_size=IMG, buckets=(1, 4, 8)
    )
    engine.warmup()
    return engine


def test_engine_padding_never_leaks(toy_engine):
    """Padding rows must not contaminate valid rows: within ONE bucket
    program, the same images embed bitwise-identically at any occupancy
    (pad contents differ, results must not). Across buckets the
    programs differ (XLA fuses per batch size), so only allclose."""
    imgs = np.random.default_rng(0).integers(0, 255, (8, IMG, IMG, 3), np.uint8)
    full, _ = toy_engine.embed(imgs)  # bucket 8, occupancy 8/8
    for n in (5, 7):  # bucket 8 at partial occupancy: bitwise
        part, executed = toy_engine.embed(imgs[:n])
        assert executed == [(8, n)]
        np.testing.assert_array_equal(part, full[:n])
    p2, ex2 = toy_engine.embed(imgs[:2])  # bucket 4 vs bucket 4
    p3, ex3 = toy_engine.embed(imgs[:3])
    assert ex2 == [(4, 2)] and ex3 == [(4, 3)]
    np.testing.assert_array_equal(p2, p3[:2])
    # cross-bucket: same math, different program -> tolerance only
    np.testing.assert_allclose(p3, full[:3], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.linalg.norm(full, axis=1), 1.0, rtol=1e-5)


def test_engine_zero_recompiles_across_mixed_sizes(toy_engine):
    from moco_tpu.serve.engine import EngineRecompileError

    rng = np.random.default_rng(1)
    for n in (1, 2, 3, 4, 5, 8, 9, 17):
        toy_engine.embed(rng.integers(0, 255, (n, IMG, IMG, 3), np.uint8))
    assert toy_engine.recompiles_after_warmup == 0
    with pytest.raises(EngineRecompileError):
        toy_engine._compile(64)  # post-warmup compile must refuse


def test_engine_bucket_selection(toy_engine):
    assert toy_engine.bucket_for(1) == 1
    assert toy_engine.bucket_for(2) == 4
    assert toy_engine.bucket_for(8) == 8
    with pytest.raises(ValueError):
        toy_engine.bucket_for(9)
    imgs = np.random.default_rng(2).integers(0, 255, (17, IMG, IMG, 3), np.uint8)
    _, executed = toy_engine.embed(imgs)  # chunks of max bucket 8: 8+8+1
    assert executed == [(8, 8), (8, 8), (1, 1)]


def test_engine_donation_audit_disabled_on_cpu(toy_engine):
    audit = toy_engine.donation_audit()
    # CPU backend: donation gated off -> audited as None (not False)
    assert audit and all(v is None for v in audit.values())


def test_embed_and_query_matches_separate_calls(toy_engine):
    rng = np.random.default_rng(3)
    imgs = rng.integers(0, 255, (5, IMG, IMG, 3), np.uint8)
    feats, _ = toy_engine.embed(imgs)
    idx = EmbeddingIndex(16, feats.shape[1])
    idx.snapshot(feats)
    emb, scores, nbr, executed = toy_engine.embed_and_query(imgs, idx, 3)
    np.testing.assert_array_equal(emb, feats)
    np.testing.assert_array_equal(nbr[:, 0], np.arange(5))
    s2, i2 = idx.query(feats, 3)
    np.testing.assert_array_equal(nbr, i2)
    np.testing.assert_allclose(scores, s2, rtol=1e-6)


@pytest.mark.slow
def test_load_serving_encoder_key_side(tmp_path):
    """The serving loader restores the KEY (EMA) encoder + queue: make
    params_k distinguishable from params_q in the checkpoint and assert
    the served embeddings come from the key side."""
    sm = load_script("serve_smoke.py")
    from moco_tpu.serve.engine import InferenceEngine, load_serving_encoder
    from moco_tpu.utils.checkpoint import CheckpointManager

    ckpt = str(tmp_path / "ckpt")
    sm.make_toy_checkpoint(ckpt)
    # perturb params_k so the sides differ (create_state copies q -> k)
    from moco_tpu.lincls import restore_pretrain_state

    state, config = restore_pretrain_state(ckpt)
    state = state.replace(
        params_k=jax.tree.map(lambda x: x * 1.5, state.params_k)
    )
    mgr = CheckpointManager(ckpt)
    from moco_tpu.utils.config import config_to_dict

    mgr.save(1, state, extra={"epoch": 0, "config": config_to_dict(config), "num_data": 1})
    mgr.close()

    module, params, stats, queue, queue_ptr, _ = load_serving_encoder(ckpt)
    assert queue.shape == (64, 16) and queue_ptr == 0
    k_leaf = jax.tree.leaves(params)[0]
    q_leaf = jax.tree.leaves(state.params_q)[0]
    np.testing.assert_allclose(np.asarray(k_leaf), np.asarray(q_leaf) * 1.5, rtol=1e-6)
    module_q, params_q, *_ = load_serving_encoder(ckpt, side="q")
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(params_q)[0]), np.asarray(q_leaf)
    )


# -- batcher -------------------------------------------------------------


def _echo_run_batch(images, want_neighbors):
    return {"embedding": np.arange(images.shape[0], dtype=np.float32)[:, None]}, [
        (8, images.shape[0])
    ]


def test_batcher_size_flush_before_deadline():
    calls = []

    def run_batch(images, wn):
        calls.append(images.shape[0])
        return _echo_run_batch(images, wn)

    b = ContinuousBatcher(run_batch, max_batch=8, slo_ms=10_000)
    try:
        t0 = time.perf_counter()
        futs = [b.submit(np.zeros((2, 4, 4, 3), np.uint8)) for _ in range(4)]
        outs = [f.result(10) for f in futs]
        # flushed by SIZE (8 rows), far before the 5s deadline
        assert time.perf_counter() - t0 < 2.0
        assert calls and calls[0] == 8
        # scatter: each future got ITS rows, in submit order
        got = np.concatenate([o["embedding"][:, 0] for o in outs])
        np.testing.assert_array_equal(got, np.arange(8, dtype=np.float32))
    finally:
        b.close()


def test_batcher_deadline_flush_without_size():
    b = ContinuousBatcher(_echo_run_batch, max_batch=1000, slo_ms=200)
    try:
        t0 = time.perf_counter()
        out = b.submit(np.zeros((3, 4, 4, 3), np.uint8)).result(10)
        dt = time.perf_counter() - t0
        assert out["embedding"].shape == (3, 1)
        # flushed by the slo/2 deadline (~100ms), never by size
        assert 0.05 < dt < 2.0
    finally:
        b.close()


def test_batcher_slo_violation_accounting():
    def slow_run(images, wn):
        time.sleep(0.12)
        return _echo_run_batch(images, wn)

    b = ContinuousBatcher(slow_run, max_batch=4, slo_ms=100)
    try:
        futs = [b.submit(np.zeros((4, 4, 4, 3), np.uint8)) for _ in range(2)]
        for f in futs:
            f.result(10)
        p = b.metrics.payload()
        assert p["serve/requests"] == 2
        assert p["serve/slo_violations"] == 2  # 120ms compute > 100ms SLO
        assert p["serve/p99_ms"] > 100
    finally:
        b.close()


def test_batcher_close_unblocks_put_blocked_producers():
    release = threading.Event()

    def stuck_run(images, wn):
        release.wait(5)
        return _echo_run_batch(images, wn)

    b = ContinuousBatcher(stuck_run, max_batch=1, slo_ms=50, queue_depth=1)
    errors = []

    def producer():
        try:
            for _ in range(100):
                b.submit(np.zeros((1, 4, 4, 3), np.uint8))
        except BatcherClosedError:
            errors.append("closed")

    threads = [threading.Thread(target=producer) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.3)  # producers now blocked on the bounded queue
    release.set()
    b.close()
    for t in threads:
        t.join(timeout=5)
    assert not any(t.is_alive() for t in threads), "producer leaked (JX011)"
    assert len(errors) == 3


def test_batcher_close_fails_pending_futures():
    def slow_run(images, wn):
        time.sleep(0.2)
        return _echo_run_batch(images, wn)

    b = ContinuousBatcher(slow_run, max_batch=1, slo_ms=1000, queue_depth=8)
    futs = [b.submit(np.zeros((1, 4, 4, 3), np.uint8)) for _ in range(4)]
    b.close()
    resolved = failed = 0
    for f in futs:
        try:
            f.result(5)
            resolved += 1
        except BatcherClosedError:
            failed += 1
    assert resolved + failed == 4 and failed >= 1
    with pytest.raises(BatcherClosedError):
        b.submit(np.zeros((1, 4, 4, 3), np.uint8))


def test_batcher_run_batch_error_propagates_to_futures():
    def bad_run(images, wn):
        raise RuntimeError("engine on fire")

    b = ContinuousBatcher(bad_run, max_batch=1, slo_ms=50)
    try:
        with pytest.raises(RuntimeError, match="engine on fire"):
            b.submit(np.zeros((1, 4, 4, 3), np.uint8)).result(10)
    finally:
        b.close()


def test_serve_metrics_payload_schema():
    from moco_tpu.obs import schema

    m = ServeMetrics(slo_ms=100)
    m.record_flush([(8, 5), (32, 30)])
    m.record_request(0.050)
    m.record_request(0.250)  # violation
    rec = {"step": 1, "time": time.time(), **m.payload()}
    assert schema.validate_line(rec) == []
    assert rec["serve/occupancy"] == 35 / 40
    assert rec["serve/slo_violations"] == 1
    assert rec["serve/bucket_8"] == 1 and rec["serve/bucket_32"] == 1
    # a malformed serve/ value must be rejected by the prefix validator
    assert schema.validate_line({"step": 1, "time": 0.0, "serve/qps": "fast"})


# -- server + satellites -------------------------------------------------


def test_resolve_serve_port_offset_rule():
    from moco_tpu.obs.sinks import SERVE_PORT_STRIDE, resolve_serve_port

    # no metrics endpoint: plain per-process family
    assert resolve_serve_port(8000, 0, 0) == 8000
    assert resolve_serve_port(8000, 0, 3) == 8003
    # collision with the Prometheus family -> shift by the stride
    # this test ASSERTS the offset rule, so it hand-computes the
    # expected values on purpose
    assert resolve_serve_port(9090, 9090, 0) == 9090 + SERVE_PORT_STRIDE  # mocolint: disable=JX018
    assert resolve_serve_port(9090, 9090, 2) == 9092 + SERVE_PORT_STRIDE  # mocolint: disable=JX018
    # distinct families never shift
    assert resolve_serve_port(8000, 9090, 1) == 8001
    # 0 = ephemeral stays 0
    assert resolve_serve_port(0, 9090, 1) == 0


@pytest.mark.slow
def test_server_end_to_end(toy_engine, tmp_path):
    from moco_tpu.obs import schema
    from moco_tpu.obs.sinks import JsonlSink
    from moco_tpu.serve.server import ServeServer

    rng = np.random.default_rng(0)
    seed_imgs = rng.integers(0, 255, (8, IMG, IMG, 3), np.uint8)
    feats, _ = toy_engine.embed(seed_imgs)
    index = EmbeddingIndex(16, feats.shape[1])
    index.snapshot(feats)
    sink = JsonlSink(str(tmp_path))
    server = ServeServer(
        toy_engine, index=index, port=0, slo_ms=5000, neighbors_k=3,
        sink=sink, metrics_flush_s=0.2,
        warmup=False,  # module-scoped engine is already warm
    )
    index.prepare(toy_engine.buckets, 3)
    index.freeze()
    try:
        base = f"http://127.0.0.1:{server.port}"

        def post(path, imgs):
            req = urllib.request.Request(
                base + path, data=imgs.tobytes(),
                headers={"X-Image-Shape": ",".join(map(str, imgs.shape))},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        out = post("/embed", seed_imgs[:2])
        np.testing.assert_allclose(np.asarray(out["embedding"]), feats[:2], atol=1e-5)
        out = post("/neighbors?k=2", seed_imgs[:3])
        nbr = np.asarray(out["indices"])
        assert nbr.shape == (3, 2)
        np.testing.assert_array_equal(nbr[:, 0], np.arange(3))
        # malformed request -> 400, not a crash
        req = urllib.request.Request(
            base + "/embed", data=b"xx", headers={"X-Image-Shape": "1,2,3"}
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=10)
        assert exc_info.value.code == 400
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert json.loads(r.read())["ok"] is True
        with urllib.request.urlopen(base + "/stats", timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["serve/recompiles_after_warmup"] == 0
        assert stats["serve/requests"] >= 2
        time.sleep(0.5)  # let the flusher write at least one line
    finally:
        server.close()
        sink.close()
    errors = schema.validate_file(str(tmp_path / "metrics.jsonl"))
    assert not errors, errors
    lines = schema.read_metrics(str(tmp_path / "metrics.jsonl"))
    assert any("serve/qps" in r for r in lines)


# -- perf ledger: the serving series gates like the headline ------------


def test_perf_ledger_gates_serving_series(tmp_path):
    pl = load_script("perf_ledger.py")
    ledger = str(tmp_path / "ledger.json")
    base_rec = {
        "metric": "moco_v1_r18_cpu_smoke_imgs_per_sec",
        "value": 10.0,
        "serving": {
            "metric": "moco_serve_resnet18_cpu_smoke_queries_per_sec",
            "value": 8.0,
        },
    }
    cand = str(tmp_path / "bench.json")
    with open(cand, "w") as f:
        json.dump(base_rec, f)
    assert pl.check(ledger, cand) == 0  # empty ledger: nothing comparable
    pl.append(ledger, cand, "t01")
    entry = pl.load_ledger(ledger)["entries"][0]
    assert entry["serving"]["value"] == 8.0  # serving rides the entry
    # healthy: same numbers pass
    assert pl.check(ledger, cand) == 0
    # training headline fine, serving regressed beyond the cpu threshold
    bad = dict(base_rec, serving={**base_rec["serving"], "value": 2.0})
    with open(cand, "w") as f:
        json.dump(bad, f)
    assert pl.check(ledger, cand) == 1
    # serving fine, headline regressed -> still gated
    bad2 = dict(base_rec, value=1.0)
    with open(cand, "w") as f:
        json.dump(bad2, f)
    assert pl.check(ledger, cand) == 1
    # a record with no serving block (old bench) still checks cleanly
    legacy = {"metric": base_rec["metric"], "value": 9.9}
    with open(cand, "w") as f:
        json.dump(legacy, f)
    assert pl.check(ledger, cand) == 0
