"""Known-good fixture for JX011: the repo-idiomatic shutdown contract —
responsive put (timeout + stop flag), drain-then-join close()
(data/pipeline.py's _PrefetchIterator shape)."""

import queue
import threading


class JoinedProducer:
    def __init__(self, src):
        self._q = queue.Queue(maxsize=4)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(src,), daemon=True, name="producer"
        )
        self._thread.start()

    def _run(self, src):
        for item in src:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)  # responsive to close()
                    break
                except queue.Full:
                    continue

    def close(self, timeout=5.0):
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()  # unblock a put-blocked producer
            except queue.Empty:
                break
        self._thread.join(timeout=timeout)


def scoped_worker(fn):
    t = threading.Thread(target=fn)
    t.start()
    fn()
    t.join()
