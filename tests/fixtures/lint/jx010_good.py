"""Known-good fixture for JX010: helper-issued collectives whose axis
agrees with the shard_map declaration — via a constant, and via an
axis-name parameter bound correctly at the call site (the
parallel/shuffle.py idiom)."""

from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"


def helper_reduce(x):
    return lax.psum(x, DATA_AXIS)


def step(x):
    return helper_reduce(x)


def build(mesh):
    return shard_map(step, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(DATA_AXIS))


def helper_param_axis(x, axis_name):
    return lax.all_gather(x, axis_name)


def step_binds_declared_axis(x):
    return helper_param_axis(x, DATA_AXIS)


def build2(mesh):
    return shard_map(
        step_binds_declared_axis, mesh=mesh, in_specs=(P("data"),), out_specs=P("data")
    )
