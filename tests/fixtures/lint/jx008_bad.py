"""Known-bad fixture for JX008: collectives issued under host-local
control flow — the SPMD divergence/deadlock bug class."""

import time

import jax
from jax import lax


def gather_when_retrying(x, io_retries):
    if io_retries > 0:  # per-host counter: hosts disagree
        return lax.all_gather(x, "data")  # expect: JX008
    return x


def reduce_on_host0(x):
    idx = jax.process_index()
    if idx == 0:
        return lax.psum(x, "data")  # expect: JX008
    return x


def reduce_on_wall_clock(x, deadline):
    if time.monotonic() < deadline:
        return lax.pmean(x, "data")  # expect: JX008
    return x


def gather_in_handler(x, loader):
    try:
        y = loader(x)
    except ValueError:
        y = lax.all_gather(x, "data")  # expect: JX008
    return y


def issue_reduce(x):
    return lax.psum(x, "data")


def helper_under_host_branch(x):
    if jax.process_index() == 0:
        return issue_reduce(x)  # expect: JX008
    return x
