"""Known-good twin of jx015_bad: every emission is covered by a field
validator or a prefix family, every validator is live, and the prefix
family wins the longest match for a real emission."""


def _num(v):
    return isinstance(v, (int, float))


FIELD_VALIDATORS = {
    "train/loss": _num,
}

PREFIX_VALIDATORS = {
    "train/": _num,
}


def flush(sink, loss, group, lr):
    payload = {"train/loss": loss}
    payload[f"train/lr_{group}"] = lr
    sink.write(payload)
