"""Known-bad fixture for JX002: implicit host transfers in jitted scope."""

import jax
import numpy as np


@jax.jit
def leaky_step(x):
    total = float(x.sum())  # expect: JX002
    first = int(x[0])  # expect: JX002
    nonzero = bool(x.min())  # expect: JX002
    host = np.asarray(x)  # expect: JX002
    scalar = x.mean().item()  # expect: JX002
    return total + first + nonzero + scalar + host.size
