"""Known-bad fixture for JX010: helper-issued collectives whose axis
disagrees with the enclosing shard_map declaration — invisible to the
lexical JX007 because the collective lives in the helper."""

from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

MODEL_AXIS = "model"


def helper_reduce(x):
    return lax.psum(x, MODEL_AXIS)


def step(x):
    return helper_reduce(x)  # expect: JX010


def build(mesh):
    return shard_map(step, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))


def helper_param_axis(x, axis_name):
    return lax.all_gather(x, axis_name)


def step_binds_wrong_axis(x):
    return helper_param_axis(x, "rows")  # expect: JX010


def build2(mesh):
    return shard_map(
        step_binds_wrong_axis, mesh=mesh, in_specs=(P("data"),), out_specs=P("data")
    )
