"""Known-bad fixture for JX003: PRNG keys consumed twice."""

import jax


def correlated_noise(rng):
    a = jax.random.normal(rng, (4,))
    b = jax.random.uniform(rng, (4,))  # expect: JX003
    return a + b


def cross_iteration_reuse(rng, n):
    total = 0.0
    for _ in range(n):
        total += jax.random.normal(rng, ())  # expect: JX003
    return total


def double_split():
    root_rng = jax.random.PRNGKey(0)
    first = jax.random.split(root_rng, 2)
    second = jax.random.split(root_rng, 2)  # expect: JX003
    return first, second
