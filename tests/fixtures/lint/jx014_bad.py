"""Known-bad fixture for JX014: a freeze-disciplined engine that lazily
compiles whatever request shape arrives — after freeze() this traces on
live traffic (the EngineRecompileError class, uncaught)."""

import jax


class LazyEngine:
    def __init__(self, forward, buckets):
        self.buckets = tuple(sorted(buckets))
        self._compiled = {}
        self._frozen = False

    def freeze(self):
        self._frozen = True

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def run(self, images):
        b = images.shape[0]
        if b not in self._compiled:
            self._compiled[b] = jax.jit(self._fwd).lower(images).compile()  # expect: JX014
        return self._compiled[b](images)
