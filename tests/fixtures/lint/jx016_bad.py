"""Known-bad: HTTP-protocol drift from the declared registry (JX016).

A handler serving a route the registry never declared (and /ingest
under the wrong method), a client calling a typo'd route, a POST to
/ingest without its required X-Rows-Shape header, and a retry wrapper
whose guard admits the non-idempotent /ingest route.
"""

import urllib.request


class Handler:
    def do_GET(self):
        path = self.path.split("?")[0]
        if path == "/admin/reboot":  # expect: JX016
            self._json(200, {})
        elif path == "/ingest":  # expect: JX016
            self._json(200, {})

    def _json(self, code, obj):
        pass


def probe(base):
    req = urllib.request.Request(base + "/statz")  # expect: JX016
    return urllib.request.urlopen(req)


def ingest(base, rows):
    req = urllib.request.Request(base + "/ingest", data=rows.tobytes())  # expect: JX016
    return urllib.request.urlopen(req)


def forward(retry_call, path, body):
    if path not in ("/embed", "/ingest"):
        return None
    return retry_call(lambda: body)  # expect: JX016
