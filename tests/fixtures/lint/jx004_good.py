"""Known-good fixture for JX004: hashable statics that exist in the
wrapped signature; shape reads (not branches) inside jitted scope."""

import jax


def apply_fn(params, x, mode):
    return params["w"] @ x if mode == "train" else x


run = jax.jit(apply_fn, static_argnames=("mode",))


def call_sites(params, x):
    return run(params, x, mode="train"), run(params, x, mode="eval")


@jax.jit
def shape_reader(x):
    b = x.shape[0]  # reading shapes is static and fine; branching is not
    return x * b
