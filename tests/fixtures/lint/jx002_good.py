"""Known-good fixture for JX002: shape-derived casts are static, scalar
reads happen outside the compiled region."""

import jax
import jax.numpy as jnp


@jax.jit
def clean_step(x):
    b = int(x.shape[0])  # shapes are trace constants: static, no sync
    return jnp.asarray(x, jnp.float32) / b


def host_read(metrics):
    # device->host reads belong outside the jitted region (log steps)
    return {k: float(v) for k, v in metrics.items()}
