"""Known-good twin of jx018_bad: exit codes come from the shared
constants module and port offsets from the sanctioned resolver."""

import os

from moco_tpu.obs.sinks import derive_metrics_port
from moco_tpu.utils.contracts import (
    KILL_EXIT_CODE,
    RESCALE_EXIT_CODE,
    STALL_EXIT_CODE,
)


def watchdog_fire():
    os._exit(STALL_EXIT_CODE)


def harness(run):
    proc = run(expect_rc=RESCALE_EXIT_CODE)
    if proc.returncode == KILL_EXIT_CODE:
        return "killed"
    return "ok"


def metrics_port_for(port, process_index):
    return derive_metrics_port(port, process_index)
