"""Known-good fixture for JX006: the donated name is immediately
rebound to the call's result — the only safe way to use donation."""

import jax


def step_fn(state, batch):
    return state + batch


step = jax.jit(step_fn, donate_argnums=(0,))


def train_loop(state, batches):
    for batch in batches:
        state = step(state, batch)
    return state
