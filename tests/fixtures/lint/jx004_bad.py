"""Known-bad fixture for JX004: recompile hazards."""

import jax


def apply_fn(params, x):
    return params["w"] @ x


misnamed = jax.jit(apply_fn, static_argnames=("mode",))  # expect: JX004
out_of_range = jax.jit(apply_fn, static_argnums=(5,))  # expect: JX004

static_shaped = jax.jit(apply_fn, static_argnums=(1,))


def call_with_list(params):
    return static_shaped(params, [1, 2, 3])  # expect: JX004


@jax.jit
def shape_branching(x):
    if x.shape[0] > 128:  # expect: JX004
        return x[:128]
    return x
