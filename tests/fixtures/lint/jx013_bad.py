"""Known-bad fixture for JX013: an AB/BA lock-order cycle between the
ingest and stats paths, and a blocking queue put issued under a lock."""

import queue
import threading


class DeadlockProne:
    def __init__(self):
        self._index_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._q = queue.Queue(maxsize=8)
        self._thread = threading.Thread(target=self._ingest, daemon=True)
        self._thread.start()

    def _ingest(self):
        # ingest path: index lock, then stats lock
        with self._index_lock:
            with self._stats_lock:
                self.rows = 1

    def stats(self):
        # stats path: stats lock, then index lock — the inverted order
        with self._stats_lock:
            with self._index_lock:  # expect: JX013
                return {"rows": self.rows}

    def publish(self, item):
        with self._index_lock:
            self._q.put(item)  # expect: JX013

    def close(self):
        self._thread.join()
