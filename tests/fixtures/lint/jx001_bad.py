"""Known-bad fixture for JX001: impure calls inside jitted scope.

Lines carrying an expectation marker comment must each produce exactly
one finding; tests/test_analysis.py compares rule ids and line numbers
exactly. This file is parsed by the analyzer, never imported/executed.
"""

import random
import time

import jax

COUNTER = 0


@jax.jit
def impure_step(x):
    global COUNTER  # expect: JX001
    t0 = time.perf_counter()  # expect: JX001
    noise = random.random()  # expect: JX001
    print("step", x)  # expect: JX001
    return x * noise + t0


def compiled_indirectly(x):
    stamp = time.time()  # expect: JX001
    return x + stamp


run = jax.jit(compiled_indirectly)
