"""Known-good fixture for JX013: one global acquisition order on both
paths, and the blocking put moved outside the lock (with a timeout —
the JX011 contract rides along)."""

import queue
import threading


class OrderedLocks:
    def __init__(self):
        self._index_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._q = queue.Queue(maxsize=8)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._ingest, daemon=True)
        self._thread.start()

    def _ingest(self):
        # both paths agree: index lock outermost
        with self._index_lock:
            with self._stats_lock:
                self.rows = 1

    def stats(self):
        with self._index_lock:
            with self._stats_lock:
                return {"rows": self.rows}

    def publish(self, item):
        with self._index_lock:
            payload = {"item": item, "rows": self.rows}
        while not self._stop.is_set():
            try:
                self._q.put(payload, timeout=0.1)
                return
            except queue.Full:
                continue

    def close(self):
        self._stop.set()
        self._thread.join()
