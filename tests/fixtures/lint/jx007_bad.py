"""Known-bad fixture for JX007: collectives naming axes the enclosing
shard_map/pmap does not declare."""

import jax
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def wrong_axis_step(x):
    return lax.psum(x, "model")  # expect: JX007


def build_shard_map(mesh):
    return shard_map(
        wrong_axis_step, mesh=mesh, in_specs=(P("data"),), out_specs=P("data")
    )


def wrong_pmap_step(x):
    return lax.pmean(x, "j")  # expect: JX007


def build_pmap():
    return jax.pmap(wrong_pmap_step, axis_name="i")
