"""Known-good fixture for JX005: the sanitizing patterns of
ops/losses.py:36 and core/queue.py:37 — stop_gradient before the loss."""

import jax
import jax.numpy as jnp
from jax import lax


def cross_entropy(logits, labels):
    logz = jax.nn.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - true)


def clean_infonce(encoder, params_q, params_k, im_q, im_k, queue, temperature):
    q = encoder(params_q, im_q)
    k = lax.stop_gradient(encoder(params_k, im_k))
    queue = lax.stop_gradient(queue)
    l_pos = jnp.einsum("nc,nc->n", q, k)
    l_neg = q @ queue.T
    return jnp.concatenate([l_pos[:, None], l_neg], axis=1) / temperature


def clean_rebinding(encoder, q, params_k, im_k, labels):
    k = encoder(params_k, im_k)
    k = lax.stop_gradient(k)  # in-place rebinding clears the taint
    return cross_entropy(q @ k.T, labels)


def clean_state_queue(q, state):
    return q @ lax.stop_gradient(state.queue).T
