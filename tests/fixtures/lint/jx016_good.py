"""Known-good twin of jx016_bad: declared routes under their declared
methods, required headers read on the handler side and sent on the
client side, and the retry guard admitting only idempotent routes."""

import urllib.request


class Handler:
    def do_GET(self):
        path = self.path.split("?")[0]
        if path == "/healthz":
            self._json(200, {"ok": True})

    def do_POST(self):
        path = self.path.split("?")[0]
        if path == "/ingest":
            shape = self.headers.get("X-Rows-Shape", "")
            ckpt_step = self.headers.get("X-Ckpt-Step")
            self._json(200, {"shape": shape, "ckpt_step": ckpt_step})

    def _json(self, code, obj):
        pass


def probe(base):
    with urllib.request.urlopen(base + "/healthz", timeout=5.0) as r:
        return r.read()


def ingest(base, rows):
    req = urllib.request.Request(
        base + "/ingest",
        data=rows.tobytes(),
        headers={"X-Rows-Shape": ",".join(str(s) for s in rows.shape)},
    )
    with urllib.request.urlopen(req, timeout=5.0) as r:
        return r.read()


def forward(retry_call, path, body):
    if path not in ("/embed", "/neighbors"):
        return None
    return retry_call(lambda: body)
