"""Known-bad fixture for JX011: threads with no join-on-close and
blocking puts with no poison-pill path (the producer-leak shape PR 5
fixed in data/pipeline.py)."""

import queue
import threading


class LeakyProducer:
    def __init__(self, src):
        self._src = src
        self._q = queue.Queue(maxsize=4)
        self._thread = threading.Thread(target=self._run, daemon=True)  # expect: JX011
        self._thread.start()

    def _run(self):
        for item in self._src:
            self._q.put(item)  # expect: JX011

    def close(self):
        # drains nothing, joins nothing: a put-blocked producer hangs here
        self._src = None


def fire_and_forget(fn):
    threading.Thread(target=fn, daemon=True).start()  # expect: JX011
