"""Known-good fixture for JX001: side effects stay on the host side,
per-step device printing goes through jax.debug.print."""

import time

import jax


@jax.jit
def pure_step(x):
    jax.debug.print("step {x}", x=x)
    return x * 2


def host_loop(xs):
    t0 = time.perf_counter()  # host code: timing the loop is fine
    outs = [pure_step(x) for x in xs]
    print(f"ran {len(outs)} steps")
    return outs, time.perf_counter() - t0
