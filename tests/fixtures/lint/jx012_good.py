"""Known-good fixture for JX012: every cross-thread attribute access
holds the one lock; thread-safe primitives (queues, events) and
single-thread attributes are not findings."""

import queue
import threading


class LockedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._q = queue.Queue()  # unbounded: JX011-clean too
        self.completed = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            with self._lock:
                self.completed += 1

    def record(self):
        with self._lock:
            self.completed += 1

    def stats(self):
        with self._lock:
            return {"completed": self.completed}

    def close(self):
        self._stop.set()
        self._thread.join()


class SingleThreadState:
    """Written only on its own worker thread: one root, no finding."""

    def __init__(self):
        self._stop = threading.Event()
        self._seen = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            self._seen += 1

    def close(self):
        self._stop.set()
        self._thread.join()
