"""Known-good fixture for JX007: collectives and specs agree, both via
string literals and via symbolic axis-name constants."""

import jax
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"


def psum_step(x):
    return lax.psum(x, DATA_AXIS)


def build_shard_map(mesh):
    batch_spec = P(DATA_AXIS)
    return shard_map(
        psum_step, mesh=mesh, in_specs=(batch_spec,), out_specs=P()
    )


def pmap_step(x):
    return lax.pmean(x, "i")


def build_pmap():
    return jax.pmap(pmap_step, axis_name="i")
