"""Known-bad fixture for JX006: a donated buffer read after the call."""

import jax


def step_fn(state, batch):
    return state + batch


step = jax.jit(step_fn, donate_argnums=(0,))


def train_loop(state, batches):
    for batch in batches:
        new_state = step(state, batch)
        print(state.sum())  # expect: JX006
        state = new_state
    return state
