"""Seeded CROSS-FUNCTION stop_gradient violation (the interprocedural
JX005 acceptance fixture): the key-encoder taint enters through one
helper's return and reaches the einsum sink inside ANOTHER helper —
both call sites look innocent to a per-function pass."""

import jax.numpy as jnp
from jax import lax


def encode(params, x):
    return x @ params["w"]


def project(q, k):
    return jnp.einsum("nc,kc->nk", q, k)


def bad_loss(params_q, params_k, batch):
    q = encode(params_q, batch)
    k = encode(params_k, batch)  # tainted THROUGH encode's summary
    return project(q, k)  # expect: JX005


def good_loss(params_q, params_k, batch):
    q = encode(params_q, batch)
    k = lax.stop_gradient(encode(params_k, batch))
    return project(q, k)
