"""Known-bad: re-typed exit codes and hand-computed port offsets
(JX018). Every magic number here exists as a shared constant in
utils/contracts.py; every offset has a sanctioned resolver in
obs/sinks.py.
"""

import os

SERVE_PORT_STRIDE = 16


def watchdog_fire():
    os._exit(42)  # expect: JX018


def harness(run):
    proc = run(expect_rc=75)  # expect: JX018
    if proc.returncode == 113:  # expect: JX018
        return "killed"
    return "ok"


def metrics_port_for(port, process_index):
    return port + process_index  # expect: JX018


def serve_port_for(port):
    return port + SERVE_PORT_STRIDE  # expect: JX018
