"""Known-bad: fault-grammar site drift (JX017).

A chaos spec naming a site no hook can fire (the stage was renamed and
the spec literal never followed), and a hook call whose site is missing
from the declared FAULT_SITES vocabulary.
"""

from moco_tpu.utils import faults


def chaos_leg(install):
    install("slow@site=serve.engine_exec:ms=250")  # expect: JX017


def handle(batch):
    faults.maybe_slow("serve.bogus_stage")  # expect: JX017
    return batch
