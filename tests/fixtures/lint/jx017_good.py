"""Known-good twin of jx017_bad: the spec names a registered stage site
and the hook site is in the declared FAULT_SITES vocabulary."""

from moco_tpu.utils import faults


def chaos_leg(install):
    install("slow@site=serve.engine_execute:ms=250")


def handle(batch):
    faults.maybe_slow("serve.engine_execute")
    return batch
