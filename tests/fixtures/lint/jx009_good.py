"""Known-good fixture for JX009: bf16 wire/compute with f32
accumulation — preferred_element_type on the matmuls (the repo's kernel
idiom, ops/fused_infonce.py) and cast-up-before-psum."""

import jax.numpy as jnp
from jax import lax


def good_matmul(x, w):
    xb = x.astype(jnp.bfloat16)
    wb = w.astype(jnp.bfloat16)
    return jnp.matmul(xb, wb, preferred_element_type=jnp.float32)


def good_einsum(q, k):
    qb = q.astype(jnp.bfloat16)
    return jnp.einsum("nc,kc->nk", qb, k, preferred_element_type=jnp.float32)


def good_psum_cast_up(g):
    gb = g.astype(jnp.bfloat16)
    g32 = gb.astype(jnp.float32)
    return lax.psum(g32, "data")


def f32_throughout(x, w):
    return jnp.matmul(x, w)
