"""Known-good fixture for JX014: the AOT discipline — compile only
bucket-table shapes up front, guard the lazy seam with the frozen
check, pad requests through bucket_for()."""

import jax
import numpy as np


class BucketedEngine:
    def __init__(self, forward, buckets, image_size):
        self._fwd = forward
        self.image_size = int(image_size)
        self.buckets = tuple(sorted(buckets))
        self._compiled = {}
        self._frozen = False
        for b in self.buckets:
            self._compile(b)

    def _compile(self, bucket):
        if self._frozen:
            raise RuntimeError(
                f"bucket {bucket} has no AOT executable and the engine is warm"
            )
        shape = jax.ShapeDtypeStruct(
            (bucket, self.image_size, self.image_size, 3), "uint8"
        )
        compiled = jax.jit(self._fwd).lower(shape).compile()
        self._compiled[bucket] = compiled
        return compiled

    def freeze(self):
        self._frozen = True

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"batch of {n} exceeds {self.buckets[-1]}")

    def run(self, images):
        bucket = self.bucket_for(images.shape[0])
        padded = np.zeros((bucket,) + images.shape[1:], images.dtype)
        padded[: images.shape[0]] = images
        return self._compiled[bucket](padded)
