"""Known-good fixture for JX008: collectives keyed on replicated state,
and host-local branching that issues NO collective (the correct idioms,
straight from the PR-4 fix: the fleet gather rides the log schedule)."""

import jax
from jax import lax


def gather_on_log_schedule(x, step, log_every):
    if step % log_every == 0:  # every host computes the same schedule
        return lax.all_gather(x, "data")
    return x


def host0_logs_after_collective(x):
    reduced = lax.psum(x, "data")  # unconditional: every host enters
    if jax.process_index() == 0:
        summary = float(reduced[0])
        return reduced, summary
    return reduced, None


def retry_counter_stays_local(x, io_retries):
    if io_retries > 0:
        x = x * 0.0  # host-local branch, but no collective inside
    return lax.pmean(x, "data")
