"""Known-bad fixture for JX005: key-encoder/queue tensors reach a loss
without stop_gradient — the MoCo invariant violation that trains wrong
silently (loss falls, gradients flow into the EMA tower)."""

import jax
import jax.numpy as jnp


def cross_entropy(logits, labels):
    logz = jax.nn.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - true)


def leaky_infonce(encoder, params_q, params_k, im_q, im_k, queue, temperature):
    q = encoder(params_q, im_q)
    k = encoder(params_k, im_k)  # key-encoder output, never detached
    l_pos = jnp.einsum("nc,nc->n", q, k)  # expect: JX005
    l_neg = q @ queue.T  # expect: JX005
    return jnp.concatenate([l_pos[:, None], l_neg], axis=1) / temperature


def leaky_direct(encoder, q, params_k, im_k, labels):
    k = encoder(params_k, im_k)
    return cross_entropy(q @ k.T, labels)  # expect: JX005


def leaky_state_queue(q, state):
    return q @ state.queue.T  # expect: JX005
