"""Known-bad: metric-schema drift (JX015).

The module defines its own validator tables (standing in for
obs/schema.py), emits one key nothing validates, one family head
nothing validates, and carries one dead field validator plus one prefix
family that can never be the longest match for anything.
"""


def _num(v):
    return isinstance(v, (int, float))


FIELD_VALIDATORS = {
    "train/loss": _num,
    "train/abandoned_gauge": _num,  # expect: JX015
}

PREFIX_VALIDATORS = {
    "train/": _num,
    "serve/trace_": _num,  # expect: JX015
}


def flush(sink, loss, group, lr, stage, ms):
    payload = {
        "train/loss": loss,
        "queue/depth": 3,  # expect: JX015
    }
    payload[f"train/lr_{group}"] = lr
    payload[f"debug/{stage}_ms"] = ms  # expect: JX015
    sink.write(payload)
