"""Known-bad fixture for JX012: shared mutable attributes written
across threads with no common lock — the unlocked-counter /
torn-snapshot shapes the serving stack grew in PRs 8-12."""

import threading


class UnlockedCounter:
    """A flusher thread and the caller both bump a bare int."""

    def __init__(self):
        self._stop = threading.Event()
        self.completed = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            self.completed += 1  # expect: JX012

    def record(self):
        self.completed += 1

    def close(self):
        self._stop.set()
        self._thread.join()


class HalfLockedStats:
    """Writes hold the lock; the stats read skips it — the interleaved
    /stats-vs-ingest snapshot shape."""

    def __init__(self):
        self._lock = threading.Lock()
        self.rows = 0
        self._thread = threading.Thread(target=self._ingest, daemon=True)
        self._thread.start()

    def _ingest(self):
        with self._lock:
            self.rows += 1

    def stats(self):
        return {"rows": self.rows}  # expect: JX012

    def close(self):
        self._thread.join()
