"""Known-good fixture for JX003: keys threaded through split/fold_in."""

import jax


def decorrelated_noise(rng):
    a_rng, b_rng = jax.random.split(rng)
    a = jax.random.normal(a_rng, (4,))
    b = jax.random.uniform(b_rng, (4,))
    return a + b


def per_step_derivation(rng, n):
    # fold_in with distinct data derives a fresh child per iteration;
    # the parent key is never consumed directly
    return [jax.random.normal(jax.random.fold_in(rng, i), ()) for i in range(n)]


def rethreaded_loop(rng, n):
    total = 0.0
    for _ in range(n):
        rng, sub_rng = jax.random.split(rng)
        total += jax.random.normal(sub_rng, ())
    return total


def exclusive_branches(rng, flag):
    # the two consumers are in exclusive branches: one use per trace
    if flag:
        return jax.random.normal(rng, ())
    return jax.random.uniform(rng, ())
