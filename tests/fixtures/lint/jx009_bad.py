"""Known-bad fixture for JX009: bf16 operands reaching matmul/einsum/
psum sinks without f32 accumulation."""

import jax.numpy as jnp
from jax import lax


def bad_matmul(x, w):
    xb = x.astype(jnp.bfloat16)
    wb = w.astype(jnp.bfloat16)
    return jnp.matmul(xb, wb)  # expect: JX009


def bad_einsum(q, k):
    qb = q.astype(jnp.bfloat16)
    return jnp.einsum("nc,kc->nk", qb, k)  # expect: JX009


def bad_operator_matmul(x, w):
    xb = x.astype("bfloat16")
    return xb @ w  # expect: JX009


def bad_grad_psum(g):
    gb = g.astype(jnp.bfloat16)
    return lax.psum(gb, "data")  # expect: JX009
