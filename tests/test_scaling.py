"""Scaling-law battery (scripts/scaling_smoke.py, ISSUE 20): the pure
verdict function, the schema-validated scaling/* ledger, and the
auto-scale wiring the harness legs rely on. The full trainings live in
the smoke itself (CI tier-1 job); these tests pin the battery's
decision logic without compiling a step."""

import json

import pytest

from tests.conftest import load_script

smoke = load_script("scaling_smoke.py")


def _gauges(drift=0.01, gap=1.0, fstd=0.04, **kw):
    g = {"ema_drift": drift, "logit_gap": gap, "feature_std_norm": fstd}
    g.update(kw)
    return g


def test_evaluate_leg_auto_passes_control_fails():
    ref = 0.01
    auto = smoke.evaluate_leg(_gauges(drift=0.0095), ref)
    assert auto["verdict"] == "PASS" and auto["failed_checks"] == []
    assert auto["drift_ratio"] == pytest.approx(0.95)
    # the constant-momentum signature: drift ratio well over the band
    ctrl = smoke.evaluate_leg(_gauges(drift=0.019), ref)
    assert ctrl["verdict"] == "FAIL"
    assert ctrl["failed_checks"] == ["drift_ratio"]
    # the band itself is exclusive: landing exactly on it fails
    edge = smoke.evaluate_leg(_gauges(drift=ref * smoke.DRIFT_RATIO_MAX), ref)
    assert "drift_ratio" in edge["failed_checks"]


def test_evaluate_leg_gap_and_collapse_gates():
    ref = 0.01
    flat = smoke.evaluate_leg(_gauges(gap=0.0), ref)
    assert flat["failed_checks"] == ["logit_gap"]
    collapsed = smoke.evaluate_leg(
        _gauges(fstd=smoke.FEATURE_STD_FLOOR / 2), ref
    )
    assert collapsed["failed_checks"] == ["feature_std"]
    # gates compose: a leg can fail several at once
    dead = smoke.evaluate_leg(_gauges(drift=0.05, gap=-0.1, fstd=0.0), ref)
    assert dead["failed_checks"] == ["drift_ratio", "feature_std", "logit_gap"]


def test_ledger_lines_are_schema_valid(tmp_path):
    from moco_tpu.obs import schema

    path = str(tmp_path / "scaling_battery.jsonl")
    ledger = smoke.Ledger(path)
    ledger.emit(
        "kappa4", "PASS", 8,
        {"kappa": 4.0, "drift_ratio": 0.94, "logit_gap": 0.01,
         "feature_std_norm": 0.013},
    )
    ledger.emit("zero_layer_ab", "PASS", 8, {"peak_ratio": 2.29, "overlap_zero": 0.54})
    with open(path) as f:
        lines = [json.loads(l) for l in f if l.strip()]
    assert [r["scaling/leg"] for r in lines] == ["kappa4", "zero_layer_ab"]
    for rec in lines:
        assert schema.validate_line(rec) == []
    # a malformed verdict (numeric where the schema wants a string) is
    # rejected at write time, not discovered downstream
    with pytest.raises(AssertionError, match="schema"):
        ledger.emit("bad", 1, 8, {})  # type: ignore[arg-type]


def test_scaling_gated_validators_resolve_in_schema():
    """Every runtime-coverage gate in utils/contracts.py must name a
    validator obs/schema.py actually applies (explicit field or prefix
    family) — a gate on a validator that can never fire would fail
    every future --contract-coverage smoke."""
    from moco_tpu.obs import schema
    from moco_tpu.utils.contracts import SCALING_GATED_VALIDATORS

    for gate in SCALING_GATED_VALIDATORS:
        assert gate in schema.FIELD_VALIDATORS or gate in schema.PREFIX_VALIDATORS, gate


def test_harness_legs_apply_the_scaling_rules(tmp_path):
    """The auto legs' config derives lr*kappa and momentum^kappa from
    the kappa=1 reference recipe — the exact rules the battery then
    verifies behaviorally."""
    from moco_tpu.utils.config import apply_auto_scale

    cfg = smoke._config(
        str(tmp_path), batch=smoke.REF_BATCH * 4, lr=smoke.REF_LR,
        momentum=smoke.REF_MOMENTUM, auto_scale=f"ref_batch={smoke.REF_BATCH}",
    )
    derived, info = apply_auto_scale(cfg)
    assert info["kappa"] == pytest.approx(4.0)
    assert derived.optim.lr == pytest.approx(smoke.REF_LR * 4)
    assert derived.moco.momentum == pytest.approx(smoke.REF_MOMENTUM**4)
    # the control leg declares no reference: its config passes through
    ctrl = smoke._config(
        str(tmp_path), batch=smoke.REF_BATCH * 4, lr=smoke.REF_LR * 4,
        momentum=smoke.REF_MOMENTUM,
    )
    same, none_info = apply_auto_scale(ctrl)
    assert none_info is None and same.optim.lr == pytest.approx(smoke.REF_LR * 4)
